"""XLA compile watcher — trace/cache-hit accounting for every `jax.jit`
site in the engine.

A JAX streaming engine's worst silent failure mode is the recompile
storm: a shape- or dtype-unstable input (growing key capacity, a mixed
micro-batch tail, an unpinned static argument) makes every fold re-trace,
and throughput collapses by 100-1000x with NOTHING in the metrics to say
why — the fold "works", it is just compiling every call. TiLT (arxiv
2301.12030) treats compile cost as a first-class stream-query concern;
this module makes it measurable: `watched_jit` wraps `jax.jit` so each
site counts traces vs cache hits, records a compile-time histogram, tags
every compile with the argument shape/dtype signature that caused it,
and flags a storm (same site, many distinct signatures) as a structured
warning + flight-recorder event.

Detection rides jit semantics, no private JAX API: the wrapped function
body only EXECUTES while jax is tracing it, so a per-call flag set inside
the body distinguishes a trace (compile) from a cache hit. The cache-hit
path adds two attribute writes, one perf_counter read and two integer
increments per call (~1µs against 60µs+ folds — bench full_pipe records
the measured ratio as `devwatch_overhead`). Signature extraction — the
only allocation-heavy step — runs ONLY when a trace actually happened.

Counters are telemetry-grade: hit/call increments are unlocked (a lost
increment under a racing dispatch is acceptable; compile-side bookkeeping
takes the record lock).
"""
from __future__ import annotations

import threading
import time as _time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .histogram import LatencyHistogram

#: distinct compile signatures at one site before it is flagged as a
#: recompile storm (legitimate respecialization — capacity doublings,
#: pane-mask combos — stays in single digits; shape churn does not)
STORM_SIGNATURES = 8

#: per-site signature table cap: a real storm can produce one signature per
#: batch forever; past the cap new signatures only bump `sig_overflow`
SIG_CAP = 128

#: retired-accumulator table cap: keyed by (op, rule), so it only grows
#: with distinct rule ids ever seen; past the cap the oldest keys drop
#: (their counters reset — an explicit bound, not a leak)
RETIRED_CAP = 4096


def _arg_signature(args: tuple, kwargs: dict) -> str:
    """Shape/dtype signature of one call's arguments — the jit cache key's
    observable part. Arrays render as dtype[d0,d1,...]; everything else
    (static argnums: ints, tuples) renders by repr, truncated."""
    import jax

    parts: List[str] = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs)):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{dtype}[{','.join(str(d) for d in shape)}]")
        else:
            parts.append(repr(leaf)[:48])
    return "|".join(parts)


class OpWatch:
    """Per-jit-site record: one per watched_jit() call (a DeviceGroupBy
    owns ~6 of these; instances do not share jit caches, so they do not
    share watch records either)."""

    def __init__(self, op: str, rule: Optional[str],
                 kind: str = "hot") -> None:
        from . import kernwatch

        self.op = op
        self.rule = rule  # attributed lazily from the rule thread context
        self.calls = 0
        self.traces = 0
        self.compile_hist = LatencyHistogram()  # µs per compile
        self.signatures: Dict[str, int] = {}  # sig -> compiles it caused
        self.sig_overflow = 0
        self.storms = 0  # threshold crossings flagged (0 or 1 per site)
        # device-side twin (observability/kernwatch.py): cost capture on
        # compiles + sampled device timing, cadence per site kind
        self.kern = kernwatch.KernelRecord(op, kind)
        self._trace_pending = False
        self._lock = threading.Lock()

    def __del__(self):
        # the registry tracks watches by WEAKREF (a live rule's counters
        # must never be evicted out from under it); monotonicity across
        # rule restarts comes from folding a dying watch's counts into
        # the retired rollup here, at the moment its owner is collected
        try:
            _registry.retire_dead(self)
        except Exception:
            pass  # interpreter teardown: registry may already be gone

    # ------------------------------------------------------------- recording
    def on_compile(self, us: float, args: tuple, kwargs: dict) -> None:
        if self.rule is None:
            # attribution rides the compile path only (compiles are rare;
            # a per-call context lookup tripled the cache-hit overhead):
            # construction and every compile run on rule-context threads
            # (the rule FSM worker at plan time, node workers at runtime)
            from ..utils.rulelog import current_rule

            self.rule = current_rule()
        self.compile_hist.record(us)
        try:
            sig = _arg_signature(args, kwargs)
        except Exception:
            sig = "<unavailable>"
        with self._lock:
            self.traces += 1
            if sig in self.signatures:
                self.signatures[sig] += 1
            elif len(self.signatures) < SIG_CAP:
                self.signatures[sig] = 1
            else:
                self.sig_overflow += 1
            n_sigs = len(self.signatures) + self.sig_overflow
            storm = n_sigs > STORM_SIGNATURES and self.storms == 0
            if storm:
                self.storms = 1
        if storm:
            from ..runtime.events import recorder
            from ..utils.infra import logger

            logger.warning(
                "recompile storm: op %s has compiled %d distinct "
                "shape/dtype signatures (%d traces over %d calls) — "
                "input shapes are unstable, every fold pays compile "
                "latency; latest signature: %s",
                self.op, n_sigs, self.traces, self.calls, sig)
            recorder().record(
                "compile_storm", rule=self.rule or "", severity="warn",
                op=self.op, signatures=n_sigs, traces=self.traces,
                last_signature=sig[:256])

    def signature_dump(self) -> Dict[str, int]:
        """Full signature table copy (sig -> compiles it caused) — the
        deep-capture bundle's HLO-signature dump (health.capture_profile);
        too wide for the per-scrape snapshot."""
        with self._lock:
            return dict(self.signatures)

    # -------------------------------------------------------------- queries
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            sigs = len(self.signatures) + self.sig_overflow
            out = {
                "op": self.op,
                "rule": self.rule,
                "calls": self.calls,
                "compiles": self.traces,
                "cache_hits": max(self.calls - self.traces, 0),
                "distinct_signatures": sigs,
                "storms": self.storms,
            }
        out["compile_us"] = self.compile_hist.snapshot()
        return out


class _WatchedJit:
    """The callable watched_jit returns — jit cache behavior is identical
    to a bare jax.jit(fn, **jit_kwargs) (one cache per instance)."""

    __slots__ = ("rec", "_jitted")

    def __init__(self, fn: Callable, rec: OpWatch, jit_kwargs: dict) -> None:
        import jax

        self.rec = rec

        def traced(*args, **kwargs):
            # executes ONLY under tracing: jit replays the compiled
            # executable on cache hits without entering the Python body
            rec._trace_pending = True
            return fn(*args, **kwargs)

        self._jitted = jax.jit(traced, **jit_kwargs)

    def __call__(self, *args, **kwargs):
        rec = self.rec
        rec._trace_pending = False
        kern = rec.kern
        sampled = kern.tick()
        t0 = _time.perf_counter()
        out = self._jitted(*args, **kwargs)
        t1 = _time.perf_counter()
        rec.calls += 1
        compiled = rec._trace_pending
        if compiled:
            # the call's wall time IS trace+compile (+ one dispatch, noise
            # against multi-ms XLA compiles)
            rec.on_compile((t1 - t0) * 1e6, args, kwargs)
            # cost_analysis off the lowered HLO — compiles only (lower()
            # re-traces; never worth it on the call path)
            kern.on_compile(self._jitted, args, kwargs)
        if sampled and not compiled:
            # sampled device-timing path: block on the outputs and split
            # the call into host-dispatch vs device time (kernwatch). A
            # call that COMPILED is never a timing sample — its wall time
            # is the compile, which would poison the dispatch floor and
            # device/roofline math and double-count against the compile
            # histogram in any dispatch/compile/device decomposition
            kern.sample(out, t0, t1, args, kwargs)
        return out


class _Registry:
    """Weakref index of live OpWatch records + retired accumulators.

    Strong ownership lives with the _WatchedJit (and through it, the
    kernel object holding the jit site) — the registry must never pin a
    dead rule's watches NOR evict a live rule's (freezing its counters
    mid-flight). When an owner is collected, OpWatch.__del__ folds its
    final counts into the per-(op, rule) retired rollup, so exported
    counters stay monotonic across rule restarts. Watches that die
    having never traced or been called (e.g. a subclass re-wrapping a
    site its base registered) retire to nothing and simply vanish."""

    def __init__(self) -> None:
        import weakref

        self._weakref = weakref
        self._lock = threading.Lock()
        self._watches: List = []  # weakref.ref[OpWatch]
        self._retired: Dict[Tuple[str, str], Dict[str, int]] = {}

    def register(self, op: str, rule: Optional[str],
                 kind: str = "hot") -> OpWatch:
        w = OpWatch(op, rule, kind)
        with self._lock:
            self._watches.append(self._weakref.ref(w))
            if len(self._watches) % 64 == 0:  # amortized dead-ref prune
                self._watches = [r for r in self._watches
                                 if r() is not None]
        return w

    def retire_dead(self, w: OpWatch) -> None:
        """Fold a dying watch's counts into the retired rollup (called
        from OpWatch.__del__; w is mid-collection — touch plain counters
        only, never its histogram/lock machinery)."""
        if w.calls == 0 and w.traces == 0:
            return  # never used: leave no zero-valued metric rows behind
        key = (w.op, w.rule or "")
        kern = getattr(w, "kern", None)
        if kern is not None:
            from . import kernwatch

            kernwatch.retire(w.op, w.rule or "", kern)
        with self._lock:
            acc = self._retired.setdefault(
                key, {"calls": 0, "compiles": 0, "storms": 0})
            acc["calls"] += w.calls
            acc["compiles"] += w.traces
            acc["storms"] += w.storms
            while len(self._retired) > RETIRED_CAP:
                del self._retired[next(iter(self._retired))]

    # -------------------------------------------------------------- queries
    def watches(self) -> List[OpWatch]:
        with self._lock:
            refs = list(self._watches)
        return [w for w in (r() for r in refs) if w is not None]

    def aggregate(self) -> Dict[Tuple[str, str], Dict[str, Any]]:
        """Rollup by (op, rule) for the Prometheus exposition: counters
        include retired instances; the compile histogram merges live ones."""
        watches = self.watches()
        with self._lock:
            out: Dict[Tuple[str, str], Dict[str, Any]] = {
                k: {**v, "hist": None, "signatures": 0}
                for k, v in self._retired.items()}
        for w in watches:
            snap = w.snapshot()
            key = (w.op, w.rule or "")
            acc = out.setdefault(
                key, {"calls": 0, "compiles": 0, "storms": 0,
                      "hist": None, "signatures": 0})
            acc["calls"] += snap["calls"]
            acc["compiles"] += snap["compiles"]
            acc["storms"] += snap["storms"]
            acc["signatures"] += snap["distinct_signatures"]
            if acc["hist"] is None:
                acc["hist"] = LatencyHistogram()
            acc["hist"].merge(w.compile_hist)
        return out

    def rule_status(self, rule_id: str) -> Dict[str, Any]:
        """Per-op compile summary for one rule's /status JSON."""
        out: Dict[str, Any] = {}
        for w in self.watches():
            if (w.rule or "") != rule_id:
                continue
            snap = w.snapshot()
            acc = out.get(w.op)
            if acc is None:
                out[w.op] = {k: snap[k] for k in (
                    "calls", "compiles", "cache_hits",
                    "distinct_signatures", "storms", "compile_us")}
            else:
                for k in ("calls", "compiles", "cache_hits",
                          "distinct_signatures", "storms"):
                    acc[k] += snap[k]
        return out

    def totals(self) -> Dict[str, int]:
        """Engine-wide compile/call totals (bench warm-vs-cold segments)."""
        calls = compiles = storms = 0
        watches = self.watches()
        with self._lock:
            for v in self._retired.values():
                calls += v["calls"]
                compiles += v["compiles"]
                storms += v["storms"]
        for w in watches:
            snap = w.snapshot()
            calls += snap["calls"]
            compiles += snap["compiles"]
            storms += snap["storms"]
        return {"calls": calls, "compiles": compiles, "storms": storms}

    def clear(self) -> None:
        """Test hook."""
        with self._lock:
            self._watches.clear()
            self._retired.clear()


_registry = _Registry()


def registry() -> _Registry:
    return _registry


def watched_jit(fn: Callable, op: str, kind: str = "hot",
                **jit_kwargs) -> Callable:
    """Drop-in instrumented `jax.jit(fn, **jit_kwargs)`. `op` names the
    site in metrics (`kuiper_xla_*{op=...}`); the owning rule is read from
    the rule thread context at first call (plan/worker threads carry it).
    `kind` is the kernwatch site class — "hot" (per-batch path, sparse
    device-timing samples) or "boundary" (window/trigger cadence, dense
    samples are affordable)."""
    from ..utils.rulelog import current_rule

    return _WatchedJit(fn, _registry.register(op, current_rule(), kind),
                       jit_kwargs)


#: `le` ladder for kuiper_xla_compile_seconds, in µs (rendered as seconds:
#: 1ms .. 2min — XLA fold compiles span ~10ms CPU to minutes on a
#: tunneled TPU)
COMPILE_BOUNDS_US = (1_000, 5_000, 25_000, 100_000, 500_000,
                     1_000_000, 5_000_000, 30_000_000, 120_000_000)


def render_prometheus(out: List[str], esc) -> None:
    """Append the kuiper_xla_* families to a /metrics scrape. `esc` is the
    exposition label escaper (observability/prometheus.py _esc)."""
    agg = _registry.aggregate()
    rows = sorted(agg.items())

    def label(op: str, rule: str) -> str:
        return f'op="{esc(op)}",rule="{esc(rule or "__engine__")}"'

    fams = (
        ("kuiper_xla_compile_total", "counter",
         "XLA traces (compiles) per jit site", lambda v: v["compiles"]),
        ("kuiper_xla_cache_hit_total", "counter",
         "jit executable cache hits per site",
         lambda v: max(v["calls"] - v["compiles"], 0)),
        ("kuiper_xla_compile_signatures", "gauge",
         "distinct arg shape/dtype signatures compiled per site",
         lambda v: v["signatures"]),
        ("kuiper_xla_compile_storms_total", "counter",
         "recompile storms flagged (unstable input shapes)",
         lambda v: v["storms"]),
    )
    for name, mtype, help_txt, value in fams:
        out.append(f"# TYPE {name} {mtype}")
        out.append(f"# HELP {name} {help_txt}")
        for (op, rule), v in rows:
            out.append(f"{name}{{{label(op, rule)}}} {value(v)}")
    name = "kuiper_xla_compile_seconds"
    out.append(f"# TYPE {name} histogram")
    out.append(f"# HELP {name} XLA compile wall time per jit site (s)")
    for (op, rule), v in rows:
        hist = v.get("hist")
        if hist is None:
            continue
        cum, count, total_us = hist.export(COMPILE_BOUNDS_US)
        lbl = label(op, rule)
        for b_us, c in zip(COMPILE_BOUNDS_US, cum):
            out.append(f'{name}_bucket{{{lbl},le="{b_us / 1e6:g}"}} {c}')
        out.append(f'{name}_bucket{{{lbl},le="+Inf"}} {count}')
        out.append(f"{name}_sum{{{lbl}}} {total_us / 1e6:g}")
        out.append(f"{name}_count{{{lbl}}} {count}")
