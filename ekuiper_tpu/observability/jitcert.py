"""jitcert — static compile-contract certification for every jitted kernel.

devwatch (this package) measures recompile storms AFTER they burn compile
time; jitcert proves the storm class away BEFORE a kernel ever traces.
Every `watched_jit` site in ops/ and parallel/ is covered by a **signature
certificate**: the closed set of (shape, dtype) argument signatures the
site may legally be traced with, derived by an abstract shape/dtype
interpreter over the engine's plan-time declarations —

  * the key-capacity growth ladder and the uint16/int32 `slot_dtype`
    boundary (ops/groupby.py `slot_dtype`, ops/keytable.py capacity
    doubling),
  * the micro-batch pad buckets every kernel input is padded to
    (runtime/ingest.py `pad_col_for_device` / `pad_slots_for_device`),
  * pane counts and spans from the shared-fold planner
    (planner/sharing.py MAX_SPAN_PANES, ops/panestore.py pane rings),
  * aggregate component layouts (ops/aggspec.py DEVICE_AGGS /
    WIDE_COMPONENTS), and
  * the power-of-two value pad buckets of the count-min sketch
    (ops/sketches.py).

Certificates are rendered in exactly devwatch's `_arg_signature` string
format, so the runtime twin (`diff_live`) can hold the engine to them:
any signature devwatch OBSERVES that the certificate does not contain is
a report — surfaced in `GET /diagnostics/xla`, the kuiperdiag bundle,
and per bench round. The TiLT argument (arxiv 2301.12030) applied to
tracing: compile-time reasoning about the kernel surface is what lets
operator breadth grow without paying tracing tax per shape.

Three consumers make the certificate load-bearing:

  1. kuiperlint passes (tools/kuiperlint/passes/jitcert.py):
     `cert-coverage` fails any watched_jit site in ops//parallel/ whose
     op does not resolve to a derivation registered here;
     `sig-stability` fails signature-unstable idioms inside jit bodies.
  2. the runtime diff (`diff_live`) — bench rounds and /diagnostics/xla
     gate on observed ⊆ certified.
  3. QoS admission (runtime/control.py) prices a candidate rule's
     *certified* new-signature count (`estimate_plan_signatures`)
     instead of waiting for the live storm-edge signal.

docs/STATIC_ANALYSIS.md § jitcert describes the certificate format and
how to certify a new jit site (required reading for ROADMAP items 2/4).
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

#: capacity doublings certified above the construction capacity — the
#: growth ladder is closed (10 doublings of the 16384 default reaches
#: 16M key slots, far past any single-chip HBM budget)
MAX_GROWS = int(os.environ.get("KUIPER_JITCERT_MAX_GROWS", "10") or 10)

#: enumeration bound per site: a derivation whose legal set would exceed
#: this is truncated and marked open (diff then reports the site as
#: uncertifiable instead of silently passing everything)
ENUM_CAP = 4096

#: validity-mask presence subsets enumerated per column set; past this
#: the derivation keeps only the none/all corners and marks truncation
MASK_SUBSET_CAP = 64

#: top of the certified count-min value pad ladder (the floor is
#: ops/sketches.py SKETCH_PAD_FLOOR — the padding site owns it); the
#: count-min hosts bounded candidate sets, so batches past 128k values
#: would be a bug worth surfacing as an uncertified signature
SKETCH_PAD_CAP = 1 << 17


def _sig(parts: List[str]) -> str:
    return "|".join(parts)


def _arr(dtype: str, *dims: int) -> str:
    return f"{dtype}[{','.join(str(d) for d in dims)}]"


@dataclass
class SiteCert:
    """One jit site's compile contract: the closed legal signature set
    plus the machine-checkable derivation that produced it (re-deriving
    from `params` with the named builder must reproduce `signatures`
    bit-for-bit — tools/jitcert certify verifies exactly that)."""

    op: str
    rule: Optional[str]
    builder: str                       # derivation function name
    params: Dict[str, Any]             # derivation inputs (plan-time)
    signatures: FrozenSet[str] = field(default_factory=frozenset)
    derivation: List[str] = field(default_factory=list)
    truncated: bool = False            # enumeration cap hit -> open set
    #: the TRUE cardinality of the legal set, computed from the
    #: derivation's product formula without enumerating — equals
    #: len(signatures) for closed certs, and stays honest past the
    #: enumeration caps (admission prices THIS, so a wide-column rule
    #: cannot under-price its compile surface by overflowing the cap)
    full_count: int = 0

    def to_json(self) -> Dict[str, Any]:
        return {
            "op": self.op,
            "rule": self.rule,
            "builder": self.builder,
            "params": {k: (sorted(v) if isinstance(v, (set, frozenset))
                           else v) for k, v in self.params.items()},
            "n_signatures": len(self.signatures),
            "full_count": self.full_count,
            "truncated": self.truncated,
            "derivation": self.derivation,
        }


# ------------------------------------------------------------ shape atoms
def _ladder(base_capacity: int, grows: int = MAX_GROWS) -> List[int]:
    return [int(base_capacity) << i for i in range(grows + 1)]


def _slot_dtypes() -> Tuple[str, ...]:
    # slots ship uint16 while the encoder's capacity allows and int32 past
    # 65,535 (ops/groupby.py slot_dtype). Cached pre-padded uint16 arrays
    # stay VALID after a grow (their values predate it), and the neutral
    # ingest table may run ahead of the kernel's own capacity — so both
    # wire dtypes are legal at every ladder step; only the shapes bind.
    return ("uint16", "int32")


def _state_leaves(comps: Dict[str, Tuple[int, int]], n_panes: int,
                  capacity: int, lead: Optional[int] = None,
                  touch: bool = False) -> List[str]:
    """Signature leaves of a group-by state pytree: dict keys sort, `act`
    rides along; `comps` maps component -> (n_specs, wide_size-or-0);
    `lead` prepends the multirule rule axis; `touch` appends the tiered
    state's per-slot uint32 counter (ops/tierstore.py — key axis only,
    no pane axis, sorts last among the state keys)."""
    parts: List[str] = []
    names = list(comps) + ["act"] + (["touch"] if touch else [])
    for comp in sorted(names):
        if comp == "touch":
            parts.append(_arr("uint32", capacity))
            continue
        if comp == "act":
            dims: Tuple[int, ...] = (n_panes, capacity)
        else:
            k, wide = comps[comp]
            dims = (n_panes, capacity, k) + ((wide,) if wide else ())
        if lead is not None:
            dims = (lead,) + dims
        parts.append(_arr("float32", *dims))
    return parts


def _col_leaves(columns: List[str], mb: int,
                mask_subset: FrozenSet[str],
                masks_always: bool = False,
                col_dtypes: Optional[Dict[str, str]] = None) -> List[str]:
    """Leaves of the kernel-columns dict: one [mb] array per column
    (float32 unless the plan's expression IR declared another dtype —
    int32 string-dict codes / rebased ts32, KernelPlan.col_dtypes) plus
    a bool[mb] validity mask per column in `mask_subset` (absent masks
    are None and vanish from the pytree — the sharded path materializes
    all of them, `masks_always`)."""
    dts = col_dtypes or {}
    present = set(columns) if masks_always else set(mask_subset)
    keys = sorted(list(columns) + [f"__valid_{c}" for c in present])
    return [_arr("bool", mb) if k.startswith("__valid_")
            else _arr(dts.get(k, "float32"), mb) for k in keys]


def _mask_subsets(columns: List[str]) -> Tuple[List[FrozenSet[str]], bool]:
    """All validity-mask presence combinations (a column carries a mask
    only when its batch had nulls — per batch, per column)."""
    n = len(columns)
    if (1 << n) > MASK_SUBSET_CAP:
        return [frozenset(), frozenset(columns)], True
    out: List[FrozenSet[str]] = []
    for bits in range(1 << n):
        out.append(frozenset(c for i, c in enumerate(columns)
                             if bits & (1 << i)))
    return out, False


# ------------------------------------------------------- kernel spec view
@dataclass
class KernelShape:
    """The plan-time facts a derivation consumes, extracted once from a
    live kernel (or synthesized for admission pricing)."""

    comps: Dict[str, Tuple[int, int]]   # comp -> (n_specs, wide)
    columns: List[str]
    n_panes: int
    micro_batch: int
    base_capacity: int
    lead_rules: Optional[int] = None    # multirule rule axis
    host_finalize_only: bool = False    # heavy_hitters plans
    #: expression-IR column dtype overrides (KernelPlan.col_dtypes):
    #: int32 string-dict / ts32 columns change the fold leaves
    col_dtypes: Dict[str, str] = field(default_factory=dict)
    #: tiered key state (ops/tierstore.py): the per-slot uint32 touch
    #: column rides the state pytree of every site
    touch: bool = False


def _kernel_shape(kernel) -> KernelShape:
    from ..ops.aggspec import WIDE_COMPONENTS
    from ..ops.groupby import _wide_size

    comps = {
        comp: (len(idxs),
               _wide_size(comp) if comp in WIDE_COMPONENTS else 0)
        for comp, idxs in kernel.comp_specs.items()
    }
    return KernelShape(
        comps=comps,
        columns=sorted(kernel.plan.columns),
        n_panes=int(kernel.n_panes),
        micro_batch=int(kernel.micro_batch),
        base_capacity=int(getattr(kernel, "_jitcert_base_capacity",
                                  kernel.capacity)),
        lead_rules=getattr(kernel, "n_rules", None),
        host_finalize_only=bool(getattr(kernel, "_host_finalize_only",
                                        False)),
        col_dtypes={k: v for k, v in sorted(
            getattr(kernel.plan, "col_dtypes", {}).items())
            if v != "float32"},
        touch=bool(getattr(kernel, "track_touch", False)),
    )


def shape_from_plan(plan, n_panes: int, micro_batch: int,
                    capacity: int, touch: bool = False) -> KernelShape:
    """KernelShape for a candidate rule's plan — no kernel construction,
    no jax import (QoS admission pricing path)."""
    from ..ops.aggspec import WIDE_COMPONENTS
    from ..ops.groupby import _wide_size

    comp_specs: Dict[str, List[int]] = {}
    for i, spec in enumerate(plan.specs):
        for comp in spec.components:
            comp_specs.setdefault(comp, []).append(i)
    comps = {
        comp: (len(idxs),
               _wide_size(comp) if comp in WIDE_COMPONENTS else 0)
        for comp, idxs in comp_specs.items()
    }
    return KernelShape(
        comps=comps, columns=sorted(plan.columns), n_panes=int(n_panes),
        micro_batch=int(micro_batch), base_capacity=int(capacity),
        host_finalize_only=any(s.kind == "heavy_hitters"
                               for s in plan.specs),
        col_dtypes={k: v for k, v in sorted(
            getattr(plan, "col_dtypes", {}).items()) if v != "float32"},
        touch=bool(touch),
    )


# ------------------------------------------------------------ derivations
def _derive_fold(ks: KernelShape, op: str, rule: Optional[str],
                 masked: bool = False, sharded: bool = False,
                 pane_vec_dtype: str = "uint8",
                 grows: int = MAX_GROWS) -> SiteCert:
    """fold / fold_masked / sharded fold_step[_vec] / multirule.fold:
    state(capacity ladder) x columns(mask subsets) x slots(dtype
    boundary) x row-gate x pane form."""
    sigs: List[str] = []
    deriv = [
        f"capacity ladder: {ks.base_capacity} x2^0..{grows} "
        "(ops/keytable.py doubling)",
        f"columns pad to micro_batch={ks.micro_batch} "
        "(runtime/ingest.py pad_col_for_device)",
    ]
    subsets, trunc = _mask_subsets(ks.columns)
    if sharded:
        subsets, trunc = [frozenset(ks.columns)], False
        deriv.append("sharded: validity masks always materialized "
                     "(static shard_map pytree)")
    else:
        deriv.append(f"validity-mask presence subsets: {len(subsets)}")
    if masked:
        row_gates = [_arr("bool", ks.micro_batch)]
        deriv.append("row gate: bool[mb] edge-refold mask")
    elif sharded:
        row_gates = [_arr("bool", ks.micro_batch)]
        deriv.append("row gate: bool[mb] row_valid (sharded)")
    else:
        row_gates = [_arr("int32")]
        deriv.append("row gate: scalar n_valid vs on-device iota")
    if masked:
        panes = [_arr("int32")]
    elif sharded and pane_vec_dtype == "int32_vec":
        panes = [_arr("int32", ks.micro_batch)]
    elif sharded:
        panes = [_arr("int32")]
    else:
        panes = [_arr("int32"), _arr(pane_vec_dtype, ks.micro_batch)]
        deriv.append("pane: scalar (processing time) or per-row uint8 "
                     "vector (event time; n_panes <= 255)")
    slot_dts = ("int32",) if sharded else _slot_dtypes()
    if not sharded:
        deriv.append("slots: uint16 under the 65,535 slot_dtype boundary "
                     "(legal at every step: cached pre-grow arrays stay "
                     "valid), int32 above it")
    if ks.col_dtypes:
        deriv.append(
            "expression-IR column dtypes: "
            + ", ".join(f"{k}={v}" for k, v in sorted(
                ks.col_dtypes.items()))
            + " (KernelPlan.col_dtypes — __sd_* dict codes / __ts32_* "
            "rebased event time)")
    if ks.touch:
        deriv.append("tiered state: uint32[capacity] touch column in the "
                     "state pytree (ops/tierstore.py)")
    for cap in _ladder(ks.base_capacity, grows):
        state = _state_leaves(ks.comps, ks.n_panes, cap, ks.lead_rules,
                              touch=ks.touch)
        for subset in subsets:
            cols = _col_leaves(ks.columns, ks.micro_batch, subset,
                               masks_always=sharded,
                               col_dtypes=ks.col_dtypes)
            for sd in slot_dts:
                for gate in row_gates:
                    for pane in panes:
                        sigs.append(_sig(
                            state + cols
                            + [_arr(sd, ks.micro_batch), gate, pane]))
    truncated = trunc or len(sigs) > ENUM_CAP
    # true cardinality by the product formula, independent of the
    # enumeration caps (2^n mask-presence subsets for n columns)
    n_subsets_true = 1 if sharded else (1 << len(ks.columns))
    full = ((grows + 1) * n_subsets_true * len(slot_dts)
            * len(row_gates) * len(panes))
    return SiteCert(op, rule, "_derive_fold",
                    {"base_capacity": ks.base_capacity, "grows": grows,
                     "micro_batch": ks.micro_batch, "n_panes": ks.n_panes,
                     "columns": ks.columns, "masked": masked,
                     "sharded": sharded, "lead_rules": ks.lead_rules,
                     "col_dtypes": dict(ks.col_dtypes),
                     "touch": ks.touch,
                     "comps": {c: list(v) for c, v in ks.comps.items()}},
                    frozenset(sigs[:ENUM_CAP]), deriv, truncated,
                    full_count=full)


def _derive_boundary(ks: KernelShape, op: str, rule: Optional[str],
                     tail: str, grows: int = MAX_GROWS) -> SiteCert:
    """State-plus-tail sites over the capacity ladder. `tail` is one of:
    static_all  — all-True static pane tuple (finalize/components:
                  every caller passes panes=None on the static route;
                  subsets go through the traced-mask twin),
    pane_mask   — traced bool[n_panes] (finalize_dyn / hh_finalize),
    pane_scalar — scalar pane index (reset_pane),
    shadow      — host-shadow components + scalar pane (absorb)."""
    sigs: List[str] = []
    deriv = [f"capacity ladder: {ks.base_capacity} x2^0..{grows}"]
    if ks.touch:
        deriv.append("tiered state: uint32[capacity] touch column in the "
                     "state pytree (ops/tierstore.py)")
    for cap in _ladder(ks.base_capacity, grows):
        state = _state_leaves(ks.comps, ks.n_panes, cap, ks.lead_rules,
                              touch=ks.touch)
        if tail == "static_all":
            sigs.append(_sig(state + ["True"] * ks.n_panes))
        elif tail == "pane_mask":
            sigs.append(_sig(state + [_arr("bool", ks.n_panes)]))
        elif tail == "pane_scalar":
            sigs.append(_sig(state + [_arr("int32")]))
        elif tail == "shadow":
            shadow: List[str] = []
            for comp in sorted(list(ks.comps) + ["act"]):
                if comp == "act":
                    dims: Tuple[int, ...] = (cap,)
                else:
                    k, wide = ks.comps[comp]
                    dims = (cap, k) + ((wide,) if wide else ())
                shadow.append(_arr("float32", *dims))
            sigs.append(_sig(state + shadow + [_arr("int32")]))
        else:  # pragma: no cover - derivation bug
            raise ValueError(f"unknown boundary tail {tail!r}")
    if tail == "static_all":
        deriv.append("pane mask: static all-True tuple (subset emits ride "
                     "the traced-mask twin; nodes_fused/panestore pass "
                     "panes=None here)")
    elif tail == "pane_mask":
        deriv.append(f"pane mask: traced bool[{ks.n_panes}] — one "
                     "executable per capacity, any pane subset")
    elif tail == "shadow":
        deriv.append("host-shadow components at state capacity + scalar "
                     "pane (checkpoint absorb)")
    return SiteCert(op, rule, "_derive_boundary",
                    {"base_capacity": ks.base_capacity, "grows": grows,
                     "n_panes": ks.n_panes, "tail": tail,
                     "lead_rules": ks.lead_rules, "touch": ks.touch,
                     "comps": {c: list(v) for c, v in ks.comps.items()}},
                    frozenset(sigs), deriv, len(sigs) > ENUM_CAP,
                    full_count=grows + 1)


def _ring_leaves(comps: Dict[str, Tuple[int, int]], capacity: int,
                 ring_slots: int) -> List[str]:
    """Signature leaves of a sliding-ring state pytree
    (ops/slidingring.py): running window totals (`tot_*`, [capacity,...])
    for add-combine components, two-stack back/front partials
    (`back_*` [capacity,...] + `front_*` [ring_slots, capacity,...]) for
    min/max-combine ones; dict keys sort."""
    from ..ops.slidingring import ADD_COMBINE

    entries: Dict[str, Tuple[int, ...]] = {}
    for comp in sorted(list(comps) + ["act"]):
        if comp == "act":
            dims: Tuple[int, ...] = ()
        else:
            k, wide = comps[comp]
            dims = (k,) + ((wide,) if wide else ())
        if comp in ADD_COMBINE:
            entries[f"tot_{comp}"] = (capacity,) + dims
        else:
            entries[f"back_{comp}"] = (capacity,) + dims
            entries[f"front_{comp}"] = (ring_slots, capacity) + dims
    return [_arr("float32", *entries[k]) for k in sorted(entries)]


def _derive_ring(ks: KernelShape, op: str, rule: Optional[str],
                 ring_slots: int, tail: str,
                 grows: int = MAX_GROWS) -> SiteCert:
    """slidingring advance/flip/query: ring state + pane state over the
    capacity ladder, with plan-time-fixed ring geometry. `tail` is one of:
    advance — scalar closed/evict slot indices + on flags,
    flip    — int32[R] age-ordered slot rotation + bool[R] validity,
    query   — body/front flags + front row index + QUERY_ADJ adjustment
              slot/weight/include vectors."""
    from ..ops.slidingring import QUERY_ADJ

    sigs: List[str] = []
    deriv = [
        f"capacity ladder: {ks.base_capacity} x2^0..{grows} "
        "(ops/keytable.py doubling; ring grows in lockstep)",
        f"ring slots fixed at plan time: {ring_slots} "
        "(ops/slidingring.py plan_ring_layout)",
        "components split by combine class: subtract-on-evict totals "
        "(n/s1/s2/hist/hh/act) vs two-stack front/back partials "
        "(mn/mx/hll)",
    ]
    for cap in _ladder(ks.base_capacity, grows):
        ring = _ring_leaves(ks.comps, cap, ring_slots)
        pane = _state_leaves(ks.comps, ks.n_panes, cap, touch=ks.touch)
        if tail == "advance":
            t = [_arr("int32"), _arr("bool"), _arr("int32"), _arr("bool")]
        elif tail == "flip":
            t = [_arr("int32", ring_slots), _arr("bool", ring_slots)]
        elif tail == "query":
            t = [_arr("bool"), _arr("bool"), _arr("int32"),
                 _arr("int32", QUERY_ADJ), _arr("float32", QUERY_ADJ),
                 _arr("bool", QUERY_ADJ)]
        else:  # pragma: no cover - derivation bug
            raise ValueError(f"unknown ring tail {tail!r}")
        sigs.append(_sig(ring + pane + t))
    if tail == "advance":
        deriv.append("tail: scalar closed/evict pane slots + on flags "
                     "(one executable per capacity)")
    elif tail == "flip":
        deriv.append(f"tail: int32[{ring_slots}] slot rotation + "
                     f"bool[{ring_slots}] validity (the amortized rebuild)")
    else:
        deriv.append(f"tail: body/front flags, front row, and "
                     f"{QUERY_ADJ} pane-slice adjustment slots "
                     "(constant-time trigger)")
    return SiteCert(op, rule, "_derive_ring",
                    {"base_capacity": ks.base_capacity, "grows": grows,
                     "ring_slots": ring_slots, "n_panes": ks.n_panes,
                     "tail": tail, "query_adj": QUERY_ADJ,
                     "touch": ks.touch,
                     "comps": {c: list(v) for c, v in ks.comps.items()}},
                    frozenset(sigs), deriv, len(sigs) > ENUM_CAP,
                    full_count=grows + 1)


def _tier_packed_w(comps: Dict[str, Tuple[int, int]], n_panes: int) -> int:
    """Packed-row width of the tier demote/promote block — mirrors
    ops/tierstore.py TierStore.blocks exactly (sorted components'
    per-pane blocks + the act block)."""
    w = n_panes  # act
    for _comp, (k, wide) in comps.items():
        w += n_panes * k * (wide or 1)
    return w


def _derive_tier(ks: KernelShape, op: str, rule: Optional[str],
                 demote_batch: int, tail: str,
                 grows: int = MAX_GROWS) -> SiteCert:
    """tierstore demote/promote (ops/tierstore.py): state pytree (touch
    column included) over the capacity ladder, plus the plan-time-fixed
    demote batch. `tail` is one of:
    demote  — int32[D] slot vector (gather + identity reset),
    promote — float32[D, W] packed rows + int32[D] slot vector
              (scatter-merge, absorb's combine algebra)."""
    packed_w = _tier_packed_w(ks.comps, ks.n_panes)
    sigs: List[str] = []
    deriv = [
        f"capacity ladder: {ks.base_capacity} x2^0..{grows}",
        f"demote batch fixed at plan time: D={demote_batch} "
        "(ops/tierstore.py TierLayout; slot vectors pad with duplicate "
        "real entries — identity under set/combine)",
        f"packed row width W={packed_w}: sorted components' per-pane "
        "blocks + the act block, C-order",
    ]
    for cap in _ladder(ks.base_capacity, grows):
        state = _state_leaves(ks.comps, ks.n_panes, cap,
                              touch=ks.touch)
        if tail == "demote":
            sigs.append(_sig(state + [_arr("int32", demote_batch)]))
        elif tail == "promote":
            sigs.append(_sig(
                state + [_arr("float32", demote_batch, packed_w),
                         _arr("int32", demote_batch)]))
        else:  # pragma: no cover - derivation bug
            raise ValueError(f"unknown tier tail {tail!r}")
    return SiteCert(op, rule, "_derive_tier",
                    {"base_capacity": ks.base_capacity, "grows": grows,
                     "n_panes": ks.n_panes, "tail": tail,
                     "demote_batch": demote_batch, "packed_w": packed_w,
                     "touch": ks.touch,
                     "comps": {c: list(v) for c, v in ks.comps.items()}},
                    frozenset(sigs), deriv, len(sigs) > ENUM_CAP,
                    full_count=grows + 1)


def _derive_sketch(op: str, rule: Optional[str], depth: int, width: int,
                   query_only: bool = False) -> SiteCert:
    """count-min update/query: the value batch pads to the next power of
    two (ops/sketches.py SKETCH_PAD_FLOOR), so the legal set is the pad
    ladder."""
    from ..ops.sketches import SKETCH_PAD_FLOOR

    sigs: List[str] = []
    b = SKETCH_PAD_FLOOR
    while b <= SKETCH_PAD_CAP:
        counts = _arr("float32", depth, width)
        if query_only:
            sigs.append(_sig([counts, _arr("float32", b)]))
        else:
            sigs.append(_sig([counts, _arr("float32", b),
                              _arr("float32", b)]))
        b <<= 1
    deriv = [
        f"value batches pad to powers of two "
        f"[{SKETCH_PAD_FLOOR}..{SKETCH_PAD_CAP}] "
        "(ops/sketches.py _pad_pow2; padded rows carry weight 0)",
        f"counts: float32[{depth},{width}] fixed at construction",
    ]
    return SiteCert(op, rule, "_derive_sketch",
                    {"depth": depth, "width": width,
                     "query_only": query_only},
                    frozenset(sigs), deriv, False,
                    full_count=len(sigs))


def _derive_join(op: str, rule: Optional[str],
                 resid_l: Dict[str, str], resid_r: Dict[str, str]
                 ) -> SiteCert:
    """joinring.match (ops/joinring.py): each side pads to the next
    power of two independently, so the legal set is the (PL, PR)
    pad-pair ladder. Leaf order is the call order: left slots/ts/valid,
    right slots/ts/valid, the two int32 band scalars, then each side's
    residual column dict (jax flattens dicts sorted by key). Residual
    columns are construction-frozen (the ON clause is plan text), so
    the set is closed — no mask subsets, no value dependence."""
    from ..ops.joinring import JOIN_PAD_CAP, JOIN_PAD_FLOOR

    pads: List[int] = []
    b = JOIN_PAD_FLOOR
    while b <= JOIN_PAD_CAP:
        pads.append(b)
        b <<= 1
    sigs: List[str] = []
    for pl in pads:
        for pr in pads:
            parts = [_arr("int32", pl), _arr("int32", pl),
                     _arr("bool", pl),
                     _arr("int32", pr), _arr("int32", pr),
                     _arr("bool", pr),
                     _arr("int32"), _arr("int32")]
            parts += [_arr(resid_l[c], pl) for c in sorted(resid_l)]
            parts += [_arr(resid_r[c], pr) for c in sorted(resid_r)]
            sigs.append(_sig(parts))
    deriv = [
        f"per-side pads: powers of two [{JOIN_PAD_FLOOR}..{JOIN_PAD_CAP}]"
        " (ops/joinring.py _pad_pow2; padded rows carry valid=False)",
        f"signature set = (PL, PR) pad pairs: {len(pads)}^2 = {len(sigs)}",
        "band bounds ride as int32 scalars (0-d), rebased per call",
        f"residual columns frozen at plan time: "
        f"L={sorted(resid_l)} R={sorted(resid_r)}",
    ]
    return SiteCert(op, rule, "_derive_join",
                    {"resid_l": dict(sorted(resid_l.items())),
                     "resid_r": dict(sorted(resid_r.items())),
                     "pad_floor": JOIN_PAD_FLOOR,
                     "pad_cap": JOIN_PAD_CAP},
                    frozenset(sigs), deriv, False, full_count=len(sigs))


def _derive_segscan(op: str, rule: Optional[str], tail: str,
                    base_capacity: int = 0,
                    grows: int = MAX_GROWS) -> SiteCert:
    """segscan.shift / segscan.sort (ops/segscan.py): micro-batches pad
    to the SEG_PAD_FLOOR..SEG_PAD_CAP power-of-two ladder. `shift`
    additionally carries the donated per-key partials (count, last
    value, has-last, running sum) on the key-capacity doubling ladder;
    `sort` is stateless (one complete collection per call)."""
    from ..ops.segscan import SEG_PAD_CAP, SEG_PAD_FLOOR

    mbs: List[int] = []
    b = SEG_PAD_FLOOR
    while b <= SEG_PAD_CAP:
        mbs.append(b)
        b <<= 1
    sigs: List[str] = []
    if tail == "sort":
        for mb in mbs:
            sigs.append(_sig([_arr("int32", mb), _arr("float32", mb),
                              _arr("bool", mb)]))
        params: Dict[str, Any] = {"tail": tail}
    elif tail == "shift":
        for cap in _ladder(base_capacity, grows):
            for mb in mbs:
                sigs.append(_sig([
                    _arr("int32", cap), _arr("float32", cap),
                    _arr("bool", cap), _arr("float32", cap),
                    _arr("int32", mb), _arr("float32", mb),
                    _arr("bool", mb)]))
        params = {"tail": tail, "base_capacity": base_capacity,
                  "grows": grows}
    else:  # pragma: no cover - derivation bug
        raise ValueError(f"unknown segscan tail {tail!r}")
    deriv = [
        f"micro-batches pad to powers of two "
        f"[{SEG_PAD_FLOOR}..{SEG_PAD_CAP}] (ops/segscan.py _pad_pow2; "
        "padded rows carry valid=False and segment to a ghost id)",
    ]
    if tail == "shift":
        deriv.append(
            f"carry partials (count/last/has/sum) on the key capacity "
            f"ladder: {base_capacity} x2^0..{grows}")
    return SiteCert(op, rule, "_derive_segscan", params,
                    frozenset(sigs), deriv, False, full_count=len(sigs))


# --------------------------------------------------- per-kernel dispatch
def _groupby_certs(kernel, prefix: str, rule: Optional[str]
                   ) -> List[SiteCert]:
    ks = _kernel_shape(kernel)
    certs = [
        _derive_fold(ks, f"{prefix}.fold", rule),
        _derive_fold(ks, f"{prefix}.fold_masked", rule, masked=True),
        _derive_boundary(ks, f"{prefix}.finalize", rule, "static_all"),
        _derive_boundary(ks, f"{prefix}.finalize_dyn", rule, "pane_mask"),
        _derive_boundary(ks, f"{prefix}.components", rule, "static_all"),
        _derive_boundary(ks, f"{prefix}.components_dyn", rule,
                         "pane_mask"),
        _derive_boundary(ks, f"{prefix}.reset_pane", rule, "pane_scalar"),
        _derive_boundary(ks, f"{prefix}.absorb", rule, "shadow"),
    ]
    if ks.host_finalize_only:
        certs.append(_derive_boundary(ks, f"{prefix}.hh_finalize", rule,
                                      "pane_mask"))
    return certs


def _multirule_certs(kernel, rule: Optional[str]) -> List[SiteCert]:
    ks = _kernel_shape(kernel)
    return [
        _derive_fold(ks, "multirule.fold", rule),
        _derive_boundary(ks, "multirule.finalize", rule, "static_all"),
        _derive_boundary(ks, "multirule.reset_pane", rule, "pane_scalar"),
    ]


def _sharded_certs(kernel, rule: Optional[str]) -> List[SiteCert]:
    ks = _kernel_shape(kernel)
    ks2 = KernelShape(**{**ks.__dict__})
    return [
        _derive_fold(ks, "sharded.fold_step", rule, sharded=True),
        _derive_fold(ks2, "sharded.fold_step_vec", rule, sharded=True,
                     pane_vec_dtype="int32_vec"),
        _derive_boundary(ks, "sharded.finalize", rule, "static_all"),
        _derive_boundary(ks, "sharded.finalize_dyn", rule, "pane_mask"),
        _derive_boundary(ks, "sharded.components", rule, "static_all"),
        _derive_boundary(ks, "sharded.reset_pane", rule, "pane_scalar"),
        _derive_boundary(ks, "sharded.absorb", rule, "shadow"),
    ]


def _sliding_ring_certs(kernel, rule: Optional[str]) -> List[SiteCert]:
    ks = _kernel_shape(kernel.gb)
    # the ring pins its OWN base capacity at registration (it is created
    # alongside the group-by kernel, but battery/admission constructions
    # may differ)
    ks.base_capacity = int(getattr(kernel, "_jitcert_base_capacity",
                                   kernel.capacity))
    slots = int(kernel.n_ring_panes)
    return [
        _derive_ring(ks, "slidingring.advance", rule, slots, "advance"),
        _derive_ring(ks, "slidingring.flip", rule, slots, "flip"),
        _derive_ring(ks, "slidingring.query", rule, slots, "query"),
    ]


def _tier_certs(kernel, rule: Optional[str]) -> List[SiteCert]:
    ks = _kernel_shape(kernel.gb)
    # the tier store pins its OWN base capacity at registration (it is
    # created alongside the group-by kernel, but battery/admission
    # constructions may differ)
    ks.base_capacity = int(getattr(kernel, "_jitcert_base_capacity",
                                   kernel.capacity))
    D = int(kernel.demote_batch)
    return [
        _derive_tier(ks, "tierstore.demote", rule, D, "demote"),
        _derive_tier(ks, "tierstore.promote", rule, D, "promote"),
    ]


def certificates_for(kernel, rule: Optional[str] = None) -> List[SiteCert]:
    """Derive every certificate a kernel object's jit sites are bound by.
    Dispatches on the same `watch_prefix` devwatch attribution uses."""
    prefix = getattr(kernel, "watch_prefix", None)
    if prefix == "slidingring":
        return _sliding_ring_certs(kernel, rule)
    if prefix == "tierstore":
        return _tier_certs(kernel, rule)
    if prefix == "multirule":
        return _multirule_certs(kernel, rule)
    if prefix == "sharded":
        return _sharded_certs(kernel, rule)
    if prefix == "sketch":
        return [
            _derive_sketch("sketch.update", rule, kernel.depth,
                           kernel.width),
            _derive_sketch("sketch.query", rule, kernel.depth,
                           kernel.width, query_only=True),
        ]
    if prefix == "joinring":
        dt = getattr(kernel, "col_dtypes", {}) or {}
        return [_derive_join(
            "joinring.match", rule,
            {c: dt.get(c, "float32") for c in kernel.resid_l},
            {c: dt.get(c, "float32") for c in kernel.resid_r})]
    if prefix == "segscan":
        base = int(getattr(kernel, "_jitcert_base_capacity",
                           getattr(kernel, "capacity", 0)))
        return [
            _derive_segscan("segscan.shift", rule, "shift", base),
            _derive_segscan("segscan.sort", rule, "sort"),
        ]
    if prefix == "groupby":
        return _groupby_certs(kernel, prefix, rule)
    raise ValueError(
        f"no jitcert derivation for kernel {type(kernel).__name__} "
        f"(watch_prefix={prefix!r}) — register one in "
        "ekuiper_tpu/observability/jitcert.py (docs/STATIC_ANALYSIS.md "
        "§ certifying a new jit site)")


#: the static coverage table the kuiperlint `cert-coverage` pass checks
#: watched_jit op names against: every op here has a derivation above.
SITE_DERIVATIONS: Dict[str, str] = {
    "groupby.fold": "_derive_fold",
    "groupby.fold_masked": "_derive_fold(masked)",
    "groupby.finalize": "_derive_boundary(static_all)",
    "groupby.finalize_dyn": "_derive_boundary(pane_mask)",
    "groupby.components": "_derive_boundary(static_all)",
    "groupby.components_dyn": "_derive_boundary(pane_mask)",
    "groupby.reset_pane": "_derive_boundary(pane_scalar)",
    "groupby.absorb": "_derive_boundary(shadow)",
    "groupby.hh_finalize": "_derive_boundary(pane_mask)",
    "multirule.fold": "_derive_fold(lead_rules)",
    "multirule.finalize": "_derive_boundary(static_all)",
    "multirule.reset_pane": "_derive_boundary(pane_scalar)",
    "sharded.fold_step": "_derive_fold(sharded)",
    "sharded.fold_step_vec": "_derive_fold(sharded, pane_vec)",
    "sharded.finalize": "_derive_boundary(static_all)",
    "sharded.finalize_dyn": "_derive_boundary(pane_mask)",
    "sharded.components": "_derive_boundary(static_all)",
    "sharded.reset_pane": "_derive_boundary(pane_scalar)",
    "sharded.absorb": "_derive_boundary(shadow)",
    "sketch.update": "_derive_sketch",
    "sketch.query": "_derive_sketch(query_only)",
    "slidingring.advance": "_derive_ring(advance)",
    "slidingring.flip": "_derive_ring(flip)",
    "slidingring.query": "_derive_ring(query)",
    "tierstore.demote": "_derive_tier(demote)",
    "tierstore.promote": "_derive_tier(promote)",
    "joinring.match": "_derive_join",
    "segscan.shift": "_derive_segscan(shift)",
    "segscan.sort": "_derive_segscan(sort)",
}


# --------------------------------------------------------------- registry
class _Registry:
    """Weakref index of live certified kernels, mirroring devwatch's
    ownership model: strong ownership stays with the kernel object; a
    collected kernel's certificates simply stop applying (its watches
    are gone from devwatch too)."""

    def __init__(self) -> None:
        import weakref

        self._weakref = weakref
        self._lock = threading.Lock()
        self._entries: List[Tuple[Any, Optional[str]]] = []  # (ref, rule)

    def register(self, kernel, rule: Optional[str]) -> None:
        with self._lock:
            ref = self._weakref.ref(kernel)
            # re-registration (subclass __init__ chains) replaces
            self._entries = [(r, ru) for (r, ru) in self._entries
                             if r() is not None and r() is not kernel]
            self._entries.append((ref, rule))

    def kernels(self) -> List[Tuple[Any, Optional[str]]]:
        with self._lock:
            refs = list(self._entries)
        return [(k, rule) for (r, rule) in refs
                if (k := r()) is not None]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


_registry = _Registry()


def registry() -> _Registry:
    return _registry


def register_kernel(kernel) -> None:
    """Called from kernel constructors (DeviceGroupBy and subclasses,
    CountMinSketch): binds the instance to its compile contract. Rule
    attribution rides the rule thread context, like devwatch."""
    from ..utils.rulelog import current_rule

    kernel._jitcert_base_capacity = int(getattr(kernel, "capacity", 0))
    _registry.register(kernel, current_rule())


def reset() -> None:
    """Test hook."""
    _registry.clear()


# ------------------------------------------------------------------- diff
def live_certificates() -> Dict[Tuple[str, str], Dict[str, Any]]:
    """(op, rule) -> {"signatures": set, "truncated": bool, "certs": n}
    across every live registered kernel. Derivation is a pure function
    of construction-frozen params (register_kernel pins the base
    capacity), so each kernel's certificates are derived ONCE and
    memoized on the instance — a diagnostics poller must not pay the
    full ladder×subset enumeration per /diagnostics/xla scrape."""
    out: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for kernel, rule in _registry.kernels():
        certs = getattr(kernel, "_jitcert_cert_cache", None)
        if certs is None:
            try:
                certs = certificates_for(kernel, rule)
            except Exception:
                continue
            try:
                kernel._jitcert_cert_cache = certs
            except Exception:
                pass  # slotted/frozen owner: derive per call
        for c in certs:
            acc = out.setdefault((c.op, rule or ""), {
                "signatures": set(), "truncated": False, "certs": 0})
            acc["signatures"] |= c.signatures
            acc["truncated"] = acc["truncated"] or c.truncated
            acc["certs"] += 1
    return out


def diff_live(max_findings: int = 64) -> Dict[str, Any]:
    """The runtime twin: devwatch's observed signature tables vs the
    registered certificates. An observed-but-uncertified signature is
    the report, not a counter — each finding carries the op, rule, and
    offending signature so the derivation (or the kernel) can be fixed."""
    from . import devwatch

    certs = live_certificates()
    findings: List[Dict[str, Any]] = []
    open_sites: List[Dict[str, Any]] = []
    observed_total = 0
    sites_observed = 0
    sites_uncovered = 0
    for w in devwatch.registry().watches():
        observed = w.signature_dump()
        if not observed:
            continue
        sites_observed += 1
        observed_total += len(observed)
        key = (w.op, w.rule or "")
        entry = certs.get(key)
        if entry is None:
            # rule-attribution drift (restart, engine-owned site): any
            # certificate for the same op still binds the shapes
            pooled = [v for (op, _r), v in certs.items() if op == w.op]
            if pooled:
                entry = {
                    "signatures": set().union(
                        *(p["signatures"] for p in pooled)),
                    "truncated": any(p["truncated"] for p in pooled),
                }
        if entry is None:
            sites_uncovered += 1
            for sig, compiles in observed.items():
                findings.append({
                    "op": w.op, "rule": w.rule or "",
                    "signature": sig, "compiles": compiles,
                    "reason": "no certificate registered for this site",
                })
            continue
        if entry["truncated"]:
            # open set: the site cannot be HELD to its certificate —
            # that is a visible coverage hole, never a silent pass
            # (clean only claims observed ⊆ certified for the sites the
            # diff actually enforced)
            open_sites.append({
                "op": w.op, "rule": w.rule or "",
                "observed": len(observed),
                "reason": "certificate truncated (enumeration cap) — "
                          "site not enforced",
            })
            continue
        for sig, compiles in sorted(observed.items()):
            if sig not in entry["signatures"]:
                findings.append({
                    "op": w.op, "rule": w.rule or "",
                    "signature": sig, "compiles": compiles,
                    "reason": "observed signature outside the certified "
                              "set",
                })
    findings.sort(key=lambda f: (f["op"], f["rule"], f["signature"]))
    overflow = max(len(findings) - max_findings, 0)
    return {
        "clean": not findings,
        "sites_observed": sites_observed,
        "sites_certified": len(certs),
        "sites_uncovered": sites_uncovered,
        "sites_open": len(open_sites),
        "open_sites": open_sites[:max_findings],
        "observed_signatures": observed_total,
        "certified_signatures": sum(
            len(v["signatures"]) for v in certs.values()),
        "uncertified": findings[:max_findings],
        "uncertified_overflow": overflow,
    }


# --------------------------------------------------- admission estimation
def estimate_plan_signatures(plan, n_panes: int, micro_batch: int,
                             capacity: int,
                             sliding_ring_slots: int = 0,
                             tier_demote_batch: int = 0) -> int:
    """Certified signature count a candidate device rule adds at its
    CONSTRUCTION capacity (growth steps respecialize later, paced by key
    cardinality, not admission) — the compile load admission prices
    instead of waiting for devwatch's live storm edge. Sums each cert's
    `full_count` (the product-formula cardinality), NOT the enumerated
    set: a wide-column rule whose subset enumeration truncates must
    price its TRUE 2^n surface, or the signature budget inverts —
    admitting the compile-heaviest rules while rejecting narrower
    ones. `sliding_ring_slots` > 0 prices a DABA sliding rule's extra
    surface (slidingring.advance/flip/query + the components_dyn
    fallback) so the budget cannot under-price sliding candidates;
    `tier_demote_batch` > 0 prices a tiered rule's demote/promote sites
    (the touch column changes every state signature, so the whole shape
    is derived with it)."""
    return sum(c.full_count for c in estimate_plan_certs(
        plan, n_panes, micro_batch, capacity,
        sliding_ring_slots=sliding_ring_slots,
        tier_demote_batch=tier_demote_batch))


def estimate_plan_certs(plan, n_panes: int, micro_batch: int,
                        capacity: int,
                        sliding_ring_slots: int = 0,
                        tier_demote_batch: int = 0) -> List[SiteCert]:
    """The cert OBJECTS behind estimate_plan_signatures. The AOT cache
    (runtime/aotcache.py) prices a candidate against their enumerated
    signature strings — certificate strings ARE cache-key material, so
    admission can tell certified-but-uncached signatures (real compile
    debt) from ones a fleet bake already persisted."""
    ks = shape_from_plan(plan, n_panes, micro_batch, capacity,
                         touch=tier_demote_batch > 0)
    certs = [
        _derive_fold(ks, "groupby.fold", None, grows=0),
        _derive_boundary(ks, "groupby.finalize", None, "static_all",
                         grows=0),
        _derive_boundary(ks, "groupby.finalize_dyn", None, "pane_mask",
                         grows=0),
        _derive_boundary(ks, "groupby.components", None, "static_all",
                         grows=0),
        _derive_boundary(ks, "groupby.reset_pane", None, "pane_scalar",
                         grows=0),
    ]
    if ks.host_finalize_only:
        certs.append(_derive_boundary(ks, "groupby.hh_finalize", None,
                                      "pane_mask", grows=0))
    elif sliding_ring_slots > 0:
        certs.append(_derive_boundary(ks, "groupby.components_dyn", None,
                                      "pane_mask", grows=0))
        for op, tail in (("slidingring.advance", "advance"),
                         ("slidingring.flip", "flip"),
                         ("slidingring.query", "query")):
            certs.append(_derive_ring(ks, op, None, sliding_ring_slots,
                                      tail, grows=0))
    if tier_demote_batch > 0 and not ks.host_finalize_only:
        certs.append(_derive_tier(ks, "tierstore.demote", None,
                                  tier_demote_batch, "demote", grows=0))
        certs.append(_derive_tier(ks, "tierstore.promote", None,
                                  tier_demote_batch, "promote", grows=0))
    return certs


def estimate_relational_certs(join_resid_l: Optional[Dict[str, str]] = None,
                              join_resid_r: Optional[Dict[str, str]] = None,
                              join: bool = False,
                              analytic_shift: bool = False,
                              analytic_sort: bool = False,
                              capacity: int = 4096) -> List[SiteCert]:
    """Admission-pricing twin for the relational tier (joinring/segscan).
    A lifted join prices the full (PL, PR) pad-pair surface — the pads
    track window data, not capacity, so the construction-time truth IS
    the whole ladder. Analytic sites price the micro-batch ladder
    (shift at construction capacity, grows=0 — growth respecializes
    later, paced by key cardinality, exactly like the group-by sites)."""
    certs: List[SiteCert] = []
    if join:
        certs.append(_derive_join("joinring.match", None,
                                  dict(join_resid_l or {}),
                                  dict(join_resid_r or {})))
    if analytic_shift:
        certs.append(_derive_segscan("segscan.shift", None, "shift",
                                     capacity, grows=0))
    if analytic_sort:
        certs.append(_derive_segscan("segscan.sort", None, "sort"))
    return certs


def estimate_relational_signatures(**kw) -> int:
    """Sum of `full_count` over estimate_relational_certs — the number
    a candidate relational rule adds to the QoS signature budget."""
    return sum(c.full_count for c in estimate_relational_certs(**kw))
