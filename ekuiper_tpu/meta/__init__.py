"""UI metadata for sources/sinks/functions (analogue of internal/meta —
the reference serves curated JSON files for its management console; here
the metadata derives from the live registries plus curated property hints,
so it can never drift from what the engine actually accepts)."""
from .catalog import (  # noqa: F401
    describe_function, describe_sink, describe_source, list_functions,
    list_sinks, list_sources)
