"""Connector + function metadata catalog.

list_* enumerate what the registries can actually build; describe_* add
curated property documentation (the `about`/`properties` shape the
reference's meta JSON files use) so a management UI can render config
forms. Unknown-but-registered connectors still describe with an empty
property list — metadata presence never gates usage."""
from __future__ import annotations

from typing import Any, Dict, List

from ..utils.infra import EngineError

_COMMON_SOURCE_PROPS = [
    {"name": "datasource", "type": "string", "hint": "topic/path/table"},
    {"name": "format", "type": "string", "default": "json",
     "hint": "json|binary|delimited|urlencoded|protobuf"},
    {"name": "confKey", "type": "string",
     "hint": "named config profile (source_conf overlay)"},
]

_SOURCE_PROPS: Dict[str, List[Dict[str, Any]]] = {
    "mqtt": [
        {"name": "server", "type": "string", "default": "tcp://127.0.0.1:1883"},
        {"name": "qos", "type": "int", "default": 1},
        {"name": "username", "type": "string"},
        {"name": "password", "type": "string", "secret": True},
    ],
    "httppull": [
        {"name": "url", "type": "string"},
        {"name": "interval", "type": "int", "default": 1000},
        {"name": "method", "type": "string", "default": "GET"},
    ],
    "httppush": [
        {"name": "endpoint", "type": "string"},
        {"name": "port", "type": "int", "default": 10081},
    ],
    "websocket": [
        {"name": "addr", "type": "string",
         "hint": "client mode ws://host:port/path; empty = server mode"},
        {"name": "port", "type": "int", "default": 10081},
    ],
    "redissub": [
        {"name": "addr", "type": "string", "default": "127.0.0.1:6379"},
        {"name": "channels", "type": "string"},
        {"name": "password", "type": "string", "secret": True},
        {"name": "db", "type": "int", "default": 0},
    ],
    "neuron": [
        {"name": "url", "type": "string", "default": "ipc://neuron-ekuiper"},
    ],
    "sql": [
        {"name": "url", "type": "string", "hint": "sqlite://<path>"},
        {"name": "interval", "type": "int", "default": 1000},
        {"name": "trackingColumn", "type": "string"},
    ],
    "file": [
        {"name": "path", "type": "string"},
        {"name": "fileType", "type": "string", "default": "json"},
        {"name": "interval", "type": "int", "default": 0},
    ],
    "memory": [{"name": "datasource", "type": "string", "hint": "topic"}],
    "edgex": [
        {"name": "protocol", "type": "string", "default": "redis",
         "hint": "message bus: redis | mqtt"},
        {"name": "addr", "type": "string", "default": "127.0.0.1:6379",
         "hint": "redis bus address"},
        {"name": "server", "type": "string",
         "hint": "mqtt bus, e.g. tcp://127.0.0.1:1883"},
        {"name": "topic", "type": "string", "default": "rules-events"},
        {"name": "messageType", "type": "string", "default": "event",
         "hint": "event | request"},
    ],
    "simulator": [
        {"name": "data", "type": "list"},
        {"name": "interval", "type": "int", "default": 1000},
        {"name": "loop", "type": "bool", "default": True},
    ],
}

_SINK_PROPS: Dict[str, List[Dict[str, Any]]] = {
    "mqtt": _SOURCE_PROPS["mqtt"] + [{"name": "topic", "type": "string"}],
    "rest": [
        {"name": "url", "type": "string"},
        {"name": "method", "type": "string", "default": "POST"},
        {"name": "headers", "type": "map"},
    ],
    "redis": [
        {"name": "addr", "type": "string", "default": "127.0.0.1:6379"},
        {"name": "key", "type": "string"},
        {"name": "field", "type": "string", "hint": "row field as key"},
        {"name": "channel", "type": "string", "hint": "PUBLISH instead"},
        {"name": "dataType", "type": "string", "default": "string"},
        {"name": "expiration", "type": "int"},
    ],
    "websocket": _SOURCE_PROPS["websocket"],
    "neuron": [
        {"name": "url", "type": "string", "default": "ipc://neuron-ekuiper"},
        {"name": "nodeName", "type": "string"},
        {"name": "groupName", "type": "string"},
        {"name": "tags", "type": "list"},
        {"name": "raw", "type": "bool", "default": False},
    ],
    "sql": [
        {"name": "url", "type": "string", "hint": "sqlite://<path>"},
        {"name": "table", "type": "string"},
        {"name": "fields", "type": "list"},
    ],
    "file": [{"name": "path", "type": "string"}],
    "memory": [{"name": "topic", "type": "string"}],
    "edgex": _SOURCE_PROPS["edgex"] + [
        {"name": "topicPrefix", "type": "string",
         "hint": "dynamic topic prefix/profile/device/source"},
        {"name": "contentType", "type": "string",
         "default": "application/json"},
        {"name": "deviceName", "type": "string", "default": "ekuiper"},
        {"name": "profileName", "type": "string",
         "default": "ekuiperProfile"},
        {"name": "sourceName", "type": "string"},
        {"name": "metadata", "type": "string",
         "hint": "field carrying event/reading meta overrides"},
        {"name": "dataField", "type": "string"},
    ],
    "influx": [
        {"name": "addr", "type": "string",
         "default": "http://127.0.0.1:8086"},
        {"name": "database", "type": "string"},
        {"name": "measurement", "type": "string"},
        {"name": "username", "type": "string"},
        {"name": "password", "type": "string"},
        {"name": "tags", "type": "map", "hint": "static or {{.field}}"},
        {"name": "tsFieldName", "type": "string"},
        {"name": "precision", "type": "string", "default": "ms"},
    ],
    "influx2": [
        {"name": "addr", "type": "string",
         "default": "http://127.0.0.1:8086"},
        {"name": "org", "type": "string"},
        {"name": "bucket", "type": "string"},
        {"name": "token", "type": "string"},
        {"name": "measurement", "type": "string"},
        {"name": "tags", "type": "map", "hint": "static or {{.field}}"},
        {"name": "tsFieldName", "type": "string"},
        {"name": "precision", "type": "string", "default": "ms"},
    ],
    "log": [],
    "nop": [],
}

_COMMON_SINK_PROPS = [
    {"name": "batchSize", "type": "int", "default": 0},
    {"name": "lingerInterval", "type": "int", "default": 0},
    {"name": "dataTemplate", "type": "string"},
    {"name": "fields", "type": "list"},
    {"name": "sendSingle", "type": "bool", "default": False},
    {"name": "format", "type": "string", "default": "json"},
    {"name": "compression", "type": "string"},
    {"name": "encryption", "type": "string"},
    {"name": "enableCache", "type": "bool", "default": False},
    {"name": "retryCount", "type": "int", "default": 0},
]


def list_sources() -> List[str]:
    from ..io import registry

    registry._ensure()
    return sorted(registry._sources.keys())


def list_sinks() -> List[str]:
    from ..io import registry

    registry._ensure()
    return sorted(registry._sinks.keys())


def describe_source(name: str) -> Dict[str, Any]:
    if name not in list_sources():
        raise EngineError(f"source {name} not found")
    return {
        "name": name,
        "about": {"description": f"{name} stream source"},
        "properties": _COMMON_SOURCE_PROPS + _SOURCE_PROPS.get(name, []),
        "lookup": _has_lookup(name),
    }


def describe_sink(name: str) -> Dict[str, Any]:
    if name not in list_sinks():
        raise EngineError(f"sink {name} not found")
    return {
        "name": name,
        "about": {"description": f"{name} sink"},
        "properties": _SINK_PROPS.get(name, []) + _COMMON_SINK_PROPS,
    }


def _has_lookup(name: str) -> bool:
    from ..io import registry

    return name in registry._lookups


def list_functions() -> Dict[str, List[str]]:
    """Function names grouped by kind (the reference groups by source file
    for its UI tabs)."""
    from ..functions import registry as fn

    fn._ensure_loaded()
    out: Dict[str, List[str]] = {}
    for name, fd in sorted(fn._registry.items()):
        out.setdefault(fd.ftype, []).append(name)
    return out


def describe_function(name: str) -> Dict[str, Any]:
    from ..functions import registry as fn

    fd = fn.lookup(name)
    if fd is None:
        raise EngineError(f"function {name} not found")
    return {
        "name": fd.name,
        "type": fd.ftype,
        "vectorized": fd.vexec is not None,
        "incremental": fd.inc_name or None,
        "stateful": fd.stateful,
    }
