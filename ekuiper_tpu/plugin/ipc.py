"""IPC socket layer for the portable-plugin boundary.

Analogue of the reference's nanomsg wrapper (pkg/nng/sock.go:37-148). Two
implementations of the same framed-transport semantics:

- native: ctypes bindings over native/ekipc.cpp (libekipc.so) — poll-based
  fan-in, 4-byte LE length framing over unix-domain or TCP sockets. Built
  on demand with `make -C native` (g++ is in the base image).
- pure-python fallback: same wire format, stdlib `socket` — used when the
  shared library can't be built (keeps tests hermetic).

Protocols (reference: connection.go:182-225 — host always LISTENS, worker
always DIALS):
  PAIR       bidirectional single peer — control + function channels
             (REQ/REP discipline is enforced by the callers)
  PUSH/PULL  one-way; PULL fans-in frames from N dialed peers
"""
from __future__ import annotations

import ctypes
import os
import socket as pysocket
import struct
import subprocess
import threading
import time
from typing import List, Optional, Tuple

from ..utils.infra import logger

PAIR, PUSH, PULL = 0, 1, 2

_ERR, _TIMEOUT, _CLOSED = -1, -2, -3


class IpcTimeout(Exception):
    pass


class IpcClosed(Exception):
    pass


# --------------------------------------------------------------------- native
_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_lib = None
_lib_tried = False
_lib_lock = threading.Lock()


_build_started = False


def _build_native() -> bool:
    """Compile libekipc.so into a scratch dir, then atomically install it so
    _load_native never CDLLs a half-written file. Runs in a background thread
    via ensure_native, never on a request path."""
    try:
        native = os.path.abspath(_NATIVE_DIR)
        scratch = f"build.tmp.{os.getpid()}"
        subprocess.run(
            ["make", "-C", native, f"BUILD={scratch}"],
            capture_output=True, timeout=120, check=True,
        )
        os.makedirs(os.path.join(native, "build"), exist_ok=True)
        os.replace(os.path.join(native, scratch, "libekipc.so"),
                   os.path.join(native, "build", "libekipc.so"))
        os.rmdir(os.path.join(native, scratch))
        return True
    except Exception as e:  # toolchain unavailable — fall back
        logger.warning("ekipc native build failed (%s); using pure-python ipc", e)
        return False


def ensure_native(background: bool = True) -> None:
    """Kick off (or finish) the native build. Called at manager/server init so
    the first plugin request never blocks on the compiler. Idempotent: only
    one build is ever started per process."""
    global _build_started
    so = os.path.abspath(os.path.join(_NATIVE_DIR, "build", "libekipc.so"))
    with _lib_lock:
        if os.path.exists(so) or _lib_tried or _build_started:
            return
        _build_started = True
    if background:
        threading.Thread(target=_build_native, daemon=True,
                         name="ekipc-build").start()
    else:
        _build_native()


def _load_native() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    with _lib_lock:
        if _lib_tried:
            return _lib
        so = os.path.abspath(os.path.join(_NATIVE_DIR, "build", "libekipc.so"))
        if not os.path.exists(so):
            # not built yet: use the pure fallback for now, but keep probing —
            # a background ensure_native build may finish later
            return None
        _lib_tried = True
        try:
            lib = ctypes.CDLL(so)
            lib.eks_new.restype = ctypes.c_int
            lib.eks_new.argtypes = [ctypes.c_int]
            lib.eks_listen.restype = ctypes.c_int
            lib.eks_listen.argtypes = [ctypes.c_int, ctypes.c_char_p]
            lib.eks_dial.restype = ctypes.c_int
            lib.eks_dial.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
            lib.eks_send.restype = ctypes.c_int
            lib.eks_send.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
            lib.eks_recv.restype = ctypes.c_int64
            lib.eks_recv.argtypes = [ctypes.c_int, ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)), ctypes.c_int]
            lib.eks_free_msg.argtypes = [ctypes.POINTER(ctypes.c_ubyte)]
            lib.eks_close.restype = ctypes.c_int
            lib.eks_close.argtypes = [ctypes.c_int]
            _lib = lib
        except Exception as e:
            logger.warning("ekipc load failed (%s); using pure-python ipc", e)
            _lib = None
        return _lib


class _NativeSocket:
    def __init__(self, proto: int) -> None:
        self._lib = _load_native()
        assert self._lib is not None
        self._h = self._lib.eks_new(proto)
        if self._h < 0:
            raise OSError("eks_new failed")

    def listen(self, url: str) -> None:
        if self._lib.eks_listen(self._h, url.encode()) != 0:
            raise OSError(f"listen {url} failed")

    def dial(self, url: str, timeout_ms: int = 5000) -> None:
        rc = self._lib.eks_dial(self._h, url.encode(), timeout_ms)
        if rc == _TIMEOUT:
            raise IpcTimeout(f"dial {url}")
        if rc != 0:
            raise OSError(f"dial {url} failed ({rc})")

    def send(self, data: bytes, timeout_ms: int = -1) -> None:
        rc = self._lib.eks_send(self._h, data, len(data), timeout_ms)
        if rc == _TIMEOUT:
            raise IpcTimeout("send")
        if rc == _CLOSED:
            raise IpcClosed("send")
        if rc != 0:
            raise OSError(f"send failed ({rc})")

    def recv(self, timeout_ms: int = -1) -> bytes:
        out = ctypes.POINTER(ctypes.c_ubyte)()
        n = self._lib.eks_recv(self._h, ctypes.byref(out), timeout_ms)
        if n == _TIMEOUT:
            raise IpcTimeout("recv")
        if n == _CLOSED:
            raise IpcClosed("recv")
        if n < 0:
            raise OSError(f"recv failed ({n})")
        try:
            return bytes(ctypes.cast(out, ctypes.POINTER(ctypes.c_ubyte * n)).contents) if n else b""
        finally:
            self._lib.eks_free_msg(out)

    def close(self) -> None:
        self._lib.eks_close(self._h)


# -------------------------------------------------------------- pure fallback
def _parse_url(url: str):
    if url.startswith("ipc://"):
        return ("unix", url[6:])
    if url.startswith("tcp://"):
        host, _, port = url[6:].rpartition(":")
        return ("tcp", (host, int(port)))
    raise ValueError(f"bad url {url}")


class _PySocket:
    """Stdlib implementation of the same semantics (fan-in PULL, PAIR)."""

    def __init__(self, proto: int) -> None:
        self.proto = proto
        self._listener: Optional[pysocket.socket] = None
        self._conns: List[Tuple[pysocket.socket, bytearray]] = []
        self._mu = threading.Lock()
        self._unlink: Optional[str] = None
        self._closed = False

    def listen(self, url: str) -> None:
        kind, addr = _parse_url(url)
        if kind == "unix":
            try:
                os.unlink(addr)
            except OSError:
                pass
            s = pysocket.socket(pysocket.AF_UNIX, pysocket.SOCK_STREAM)
            s.bind(addr)
            self._unlink = addr
        else:
            s = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_STREAM)
            s.setsockopt(pysocket.SOL_SOCKET, pysocket.SO_REUSEADDR, 1)
            s.bind(addr)
        s.listen(64)
        s.settimeout(0.05)
        self._listener = s

    def dial(self, url: str, timeout_ms: int = 5000) -> None:
        kind, addr = _parse_url(url)
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            try:
                fam = pysocket.AF_UNIX if kind == "unix" else pysocket.AF_INET
                s = pysocket.socket(fam, pysocket.SOCK_STREAM)
                s.connect(addr)
                s.settimeout(0.05)
                with self._mu:
                    self._conns.append((s, bytearray()))
                return
            except OSError:
                try:
                    s.close()
                except OSError:
                    pass
                if time.monotonic() >= deadline:
                    raise IpcTimeout(f"dial {url}")
                time.sleep(0.02)

    def _accept(self) -> None:
        if self._listener is None:
            return
        while True:
            try:
                c, _ = self._listener.accept()
                c.settimeout(0.05)
                with self._mu:
                    self._conns.append((c, bytearray()))
            except (pysocket.timeout, OSError):
                return

    def send(self, data: bytes, timeout_ms: int = -1) -> None:
        deadline = None if timeout_ms < 0 else time.monotonic() + timeout_ms / 1000.0
        while True:
            if self._closed:
                raise IpcClosed("send")
            self._accept()
            with self._mu:
                conn = self._conns[-1][0] if self._conns else None
            if conn is not None:
                break
            if deadline is not None and time.monotonic() >= deadline:
                raise IpcTimeout("send")
            time.sleep(0.01)
        frame = struct.pack("<I", len(data)) + data
        try:
            conn.sendall(frame)
        except OSError:
            raise IpcClosed("send")

    def recv(self, timeout_ms: int = -1) -> bytes:
        deadline = None if timeout_ms < 0 else time.monotonic() + timeout_ms / 1000.0
        while True:
            if self._closed:
                raise IpcClosed("recv")
            self._accept()
            with self._mu:
                conns = list(self._conns)
            for s, buf in conns:
                # complete frame already buffered?
                if len(buf) >= 4:
                    (ln,) = struct.unpack("<I", buf[:4])
                    if len(buf) >= 4 + ln:
                        payload = bytes(buf[4:4 + ln])
                        del buf[:4 + ln]
                        return payload
                try:
                    chunk = s.recv(65536)
                    if chunk:
                        buf.extend(chunk)
                        continue
                    # EOF
                    with self._mu:
                        self._conns = [(c, b) for c, b in self._conns if c is not s]
                    s.close()
                    if self.proto == PAIR and self._listener is None and not self._conns:
                        raise IpcClosed("recv")
                except pysocket.timeout:
                    pass
                except IpcClosed:
                    raise
                except OSError:
                    with self._mu:
                        self._conns = [(c, b) for c, b in self._conns if c is not s]
            if deadline is not None and time.monotonic() >= deadline:
                raise IpcTimeout("recv")

    def close(self) -> None:
        self._closed = True
        if self._listener is not None:
            self._listener.close()
        with self._mu:
            for s, _ in self._conns:
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()
        if self._unlink:
            try:
                os.unlink(self._unlink)
            except OSError:
                pass


# ------------------------------------------------------------------- factory
_FORCE_PURE = os.environ.get("EKUIPER_TPU_PURE_IPC") == "1"


def Socket(proto: int):
    """Create a PAIR/PUSH/PULL socket, preferring the native transport."""
    if not _FORCE_PURE and _load_native() is not None:
        return _NativeSocket(proto)
    return _PySocket(proto)


# Per-engine namespace token embedded in every ipc path so two engine
# instances (or parallel test runs) on one machine can't steal each other's
# endpoints. Worker processes inherit it through the environment, so both
# ends of a channel derive identical urls.
_IPC_NS = os.environ.setdefault("EKUIPER_TPU_IPC_NS", str(os.getpid()))


def _ipc_dir() -> str:
    """Mode-0700 per-instance runtime dir: unix sockets under it are only
    dialable by the engine's own uid (unlike the reference's world-readable
    ipc:///tmp/plugin_*.ipc endpoints)."""
    base = os.environ.get("EKUIPER_TPU_RUNTIME_DIR") or os.path.join(
        "/tmp", f"ektpu_{_IPC_NS}")
    os.makedirs(base, mode=0o700, exist_ok=True)
    # A pre-created/symlinked dir (pids are predictable) would hand the
    # endpoint to an attacker — verify rather than trust: must be a real
    # directory, owned by us, no group/other access.
    st = os.lstat(base)
    import stat as _stat
    if not _stat.S_ISDIR(st.st_mode):
        raise RuntimeError(f"ipc runtime dir {base} is not a directory")
    if st.st_uid != os.getuid():
        raise RuntimeError(f"ipc runtime dir {base} owned by uid {st.st_uid}")
    if st.st_mode & 0o077:
        os.chmod(base, 0o700)  # raises on failure — do not fall through
    return base


def ipc_url(name: str) -> str:
    """ipc://{runtime_dir}/{name}.ipc — reference url scheme (connection.go:56)
    with a per-instance 0700 directory instead of bare /tmp."""
    return f"ipc://{os.path.join(_ipc_dir(), name + '.ipc')}"
