"""Portable-plugin host manager — analogue of
internal/plugin/portable/ (manager.go, plugin_ins_manager.go:235).

Responsibilities:
- plugin registry: name -> {executable, sources, sinks, functions}, persisted
  in the KV store ("plugin" namespace) like the reference's plugin db
- process supervision: GetOrStartProcess semantics — spawn the worker,
  handshake over the control channel, serialize control commands, restart a
  dead worker on next use, KillAll on shutdown (server.go:329)
- binder wiring: declared symbols are registered into the io / function
  registries so rules can reference them like builtins (binder chain,
  internal/binder/factory.go:58-61)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..utils.infra import EngineError, logger
from . import ipc


@dataclass
class PluginMeta:
    name: str
    executable: str  # path to the worker entrypoint (python script)
    language: str = "python"
    version: str = ""
    sources: List[str] = field(default_factory=list)
    sinks: List[str] = field(default_factory=list)
    functions: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "executable": self.executable,
            "language": self.language, "version": self.version,
            "sources": self.sources, "sinks": self.sinks,
            "functions": self.functions,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PluginMeta":
        return PluginMeta(
            name=d["name"], executable=d["executable"],
            language=d.get("language", "python"), version=d.get("version", ""),
            sources=list(d.get("sources", [])), sinks=list(d.get("sinks", [])),
            functions=list(d.get("functions", [])),
        )


class PluginIns:
    """A running worker process + its control channel.

    Control discipline is strict request/reply under a mutex, matching the
    reference's per-plugin REQ/REP serialization (connection.go:139-148).
    """

    def __init__(self, meta: PluginMeta) -> None:
        self.meta = meta
        self.proc: Optional[subprocess.Popen] = None
        self.ctrl = None
        self._mu = threading.Lock()

    def start(self) -> None:
        url = ipc.ipc_url(f"plugin_{self.meta.name}")
        self.ctrl = ipc.Socket(ipc.PAIR)
        self.ctrl.listen(url)
        cmd = [sys.executable, self.meta.executable] if self.meta.language == "python" \
            else [self.meta.executable]
        env = dict(os.environ)
        repo_root = os.path.abspath(
            os.path.join(os.path.dirname(__file__), "..", ".."))
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        self.proc = subprocess.Popen(cmd, env=env)
        # handshake: worker dials and reports status (plugin_ins_manager.go:263)
        try:
            hello = json.loads(self.ctrl.recv(15_000))
        except Exception as e:
            self.kill()
            raise EngineError(f"plugin {self.meta.name} handshake failed: {e}")
        if hello.get("status") != "ok":
            self.kill()
            raise EngineError(f"plugin {self.meta.name} bad handshake: {hello}")
        logger.info("portable plugin %s started (pid %s)", self.meta.name, self.proc.pid)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def command(self, cmd: str, ctrl: Dict[str, Any], timeout_ms: int = 10_000) -> Any:
        with self._mu:
            if not self.alive():
                raise EngineError(f"plugin {self.meta.name} process is dead")
            try:
                self.ctrl.send(json.dumps({"cmd": cmd, "ctrl": ctrl}).encode(),
                               timeout_ms)
                reply = json.loads(self.ctrl.recv(timeout_ms))
            except Exception:
                # A timed-out reply would desynchronize the strict req/rep
                # channel (the late reply answers the NEXT command) — the only
                # safe recovery is to kill the worker; it respawns on next use.
                self.kill()
                raise
        if reply.get("state") != "ok":
            raise EngineError(
                f"plugin {self.meta.name} {cmd} failed: {reply.get('result')}")
        return reply.get("result")

    def kill(self) -> None:
        if self.ctrl is not None:
            try:
                self.ctrl.close()
            except Exception:
                pass
            self.ctrl = None
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=3)
        self.proc = None


class PortableManager:
    """Singleton plugin registry + instance supervisor."""

    _instance: Optional["PortableManager"] = None

    def __init__(self, store=None) -> None:
        ipc.ensure_native()  # build the C transport off the request path
        self._store_kv = store.kv("plugin") if store is not None else None
        self._metas: Dict[str, PluginMeta] = {}
        self._ins: Dict[str, PluginIns] = {}
        self._mu = threading.Lock()
        self._start_locks: Dict[str, threading.Lock] = {}  # per-plugin spawn lock
        if self._store_kv is not None:
            for name in self._store_kv.keys():
                try:
                    meta = PluginMeta.from_dict(json.loads(self._store_kv.get(name)))
                    self._metas[name] = meta
                    self._bind(meta)
                except Exception as e:
                    logger.warning("plugin %s restore failed: %s", name, e)

    # ---------------------------------------------------------------- registry
    @classmethod
    def global_instance(cls) -> "PortableManager":
        if cls._instance is None:
            cls._instance = PortableManager()
        return cls._instance

    @classmethod
    def set_global(cls, mgr: "PortableManager") -> None:
        cls._instance = mgr

    def register(self, meta: PluginMeta, overwrite: bool = False) -> None:
        with self._mu:
            if meta.name in self._metas and not overwrite:
                raise EngineError(f"plugin {meta.name} already registered")
            if not os.path.exists(meta.executable):
                raise EngineError(f"plugin executable {meta.executable} not found")
            self._metas[meta.name] = meta
            if self._store_kv is not None:
                self._store_kv.set(meta.name, json.dumps(meta.to_dict()))
        self._bind(meta)

    def _bind(self, meta: PluginMeta) -> None:
        from .portable import bind_symbols

        bind_symbols(self, meta)

    def get(self, name: str) -> Optional[PluginMeta]:
        return self._metas.get(name)

    def list(self) -> List[str]:
        return sorted(self._metas.keys())

    def delete(self, name: str) -> None:
        with self._mu:
            meta = self._metas.pop(name, None)
            if self._store_kv is not None:
                self._store_kv.delete(name)
            ins = self._ins.pop(name, None)
        if meta is not None:
            from .portable import unbind_symbols

            unbind_symbols(meta)
        if ins:
            ins.kill()

    # -------------------------------------------------------------- processes
    def _start_lock(self, name: str) -> threading.Lock:
        with self._mu:
            lock = self._start_locks.get(name)
            if lock is None:
                lock = self._start_locks[name] = threading.Lock()
            return lock

    def get_or_start(self, name: str) -> PluginIns:
        """GetOrStartProcess (plugin_ins_manager.go:235): reuse a live worker,
        restart a dead one. Spawns are serialized per plugin so concurrent
        callers can't kill an instance mid-handshake."""
        with self._start_lock(name):
            with self._mu:
                meta = self._metas.get(name)
                if meta is None:
                    raise EngineError(f"plugin {name} not installed")
                ins = self._ins.get(name)
            if ins is not None and ins.alive():
                return ins
            if ins is not None:
                ins.kill()
            ins = PluginIns(meta)
            ins.start()
            with self._mu:
                self._ins[name] = ins
            return ins

    def get_live(self, name: str) -> Optional[PluginIns]:
        """Live instance or None — never spawns (used by teardown paths)."""
        with self._mu:
            ins = self._ins.get(name)
        return ins if ins is not None and ins.alive() else None

    def kill_all(self) -> None:
        with self._mu:
            ins_list = list(self._ins.values())
            self._ins.clear()
        for ins in ins_list:
            ins.kill()
