"""Host-side portable symbol wrappers — analogue of
internal/plugin/portable/runtime/{function,source,sink}.go.

PortableFunc   SQL function backed by a plugin worker; strict req/rep over a
               PAIR channel, cached per symbol and hot-restartable
               (function.go:29-41,106-134)
PortableSource io.Source: host listens PULL, worker pushes JSON tuples
               (connection.go:182-200)
PortableSink   io.Sink: host pushes rows, worker pulls (connection.go:225)

Channel naming matches the SDK side (sdk/runtime.py): the host picks the
meta (ruleId/opId/instanceId) so both ends derive the same ipc url.
"""
from __future__ import annotations

import json
import threading
import uuid
from typing import Any, Dict, List, Optional

from ..utils.infra import EngineError, logger
from . import ipc


class PortableFunc:
    """Callable façade used by the function registry. One instance per symbol,
    shared across rules (reference: cached singleton, function.go:29-41)."""

    def __init__(self, manager, plugin_name: str, symbol: str) -> None:
        self.manager = manager
        self.plugin_name = plugin_name
        self.symbol = symbol
        self._sock = None
        self._ins = None  # the PluginIns the channel was built against
        self._mu = threading.Lock()

    def _ensure(self) -> None:
        ins = self.manager.get_or_start(self.plugin_name)
        if self._sock is not None and ins is self._ins and ins.alive():
            return
        # worker was (re)started — rebuild the data channel and re-announce
        # the symbol (hot reload semantics, function.go:29-41)
        if self._sock is not None:
            try:
                self._sock.close()
            except Exception:
                pass
            self._sock = None
        sock = ipc.Socket(ipc.PAIR)
        sock.listen(ipc.ipc_url(f"func_{self.symbol}"))
        try:
            ins.command("start", {
                "symbolName": self.symbol, "pluginType": "function", "meta": {},
            })
        except Exception:
            sock.close()
            raise
        self._sock = sock
        self._ins = ins

    def _req(self, func: str, args: List[Any], timeout_ms: int = 10_000) -> Any:
        with self._mu:
            payload = json.dumps({"func": func, "args": args},
                                 default=str).encode()
            for attempt in (0, 1):
                self._ensure()
                try:
                    self._sock.send(payload, timeout_ms)
                    reply = json.loads(self._sock.recv(timeout_ms))
                    break
                except (ipc.IpcClosed, ipc.IpcTimeout, OSError):
                    # peer died mid-call: drop the channel; one respawn retry
                    try:
                        self._sock.close()
                    except Exception:
                        pass
                    self._sock = None
                    self._ins = None
                    if attempt:
                        raise
        if reply.get("state") != "ok":
            raise EngineError(f"portable func {self.symbol}: {reply.get('result')}")
        return reply.get("result")

    def exec(self, *args: Any) -> Any:
        return self._req("Exec", list(args) + [{"ruleId": "", "opId": ""}])

    def validate(self, args: List[Any]) -> Any:
        return self._req("Validate", args)

    def is_aggregate(self) -> bool:
        return bool(self._req("IsAggregate", []))

    def close(self) -> None:
        with self._mu:
            if self._sock is not None:
                self._sock.close()
                self._sock = None


class PortableSource:
    """io.Source contract over a plugin worker."""

    def __init__(self, manager, plugin_name: str, symbol: str) -> None:
        self.manager = manager
        self.plugin_name = plugin_name
        self.symbol = symbol
        self.datasource = ""
        self.props: Dict[str, Any] = {}
        self._meta = {"ruleId": uuid.uuid4().hex[:8], "opId": self.symbol,
                      "instanceId": 0}
        self._sock = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.datasource = datasource or ""
        self.props = props or {}

    def _announce(self) -> "object":
        """(Re)start the symbol on the worker; returns the live PluginIns."""
        ins = self.manager.get_or_start(self.plugin_name)
        ins.command("start", {
            "symbolName": self.symbol, "pluginType": "source",
            "meta": self._meta, "dataSource": self.datasource,
            "config": self.props,
        })
        return ins

    def open(self, ingest) -> None:
        tag = f"{self._meta['ruleId']}_{self._meta['opId']}_{self._meta['instanceId']}"
        self._sock = ipc.Socket(ipc.PULL)
        self._sock.listen(ipc.ipc_url(f"source_{tag}"))
        ins = self._announce()

        def loop() -> None:
            worker = ins
            idle_ms = 0
            while not self._stop.is_set():
                try:
                    raw = self._sock.recv(500)
                    idle_ms = 0
                except ipc.IpcTimeout:
                    idle_ms += 500
                    # supervise: if the worker died, respawn and re-announce
                    # (reference restarts plugin processes on demand,
                    # plugin_ins_manager.go:235)
                    if idle_ms >= 1000 and not worker.alive():
                        try:
                            worker = self._announce()
                            idle_ms = 0
                        except Exception as e:
                            logger.warning("portable source %s respawn failed: %s",
                                           self.symbol, e)
                    continue
                except (ipc.IpcClosed, OSError):
                    break
                try:
                    ingest(json.loads(raw))
                except Exception as e:
                    logger.warning("portable source %s ingest error: %s",
                                   self.symbol, e)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"psrc-{self.symbol}")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        ins = self.manager.get_live(self.plugin_name)  # never spawn on teardown
        if ins is not None:
            try:
                ins.command("stop", {"symbolName": self.symbol,
                                     "pluginType": "source", "meta": self._meta})
            except Exception:
                pass
        if self._sock is not None:
            self._sock.close()


class PortableSink:
    """io.Sink contract over a plugin worker."""

    def __init__(self, manager, plugin_name: str, symbol: str) -> None:
        self.manager = manager
        self.plugin_name = plugin_name
        self.symbol = symbol
        self.props: Dict[str, Any] = {}
        self._meta = {"ruleId": uuid.uuid4().hex[:8], "opId": self.symbol,
                      "instanceId": 0}
        self._sock = None

    def configure(self, props: Dict[str, Any]) -> None:
        self.props = props or {}

    def connect(self) -> None:
        tag = f"{self._meta['ruleId']}_{self._meta['opId']}_{self._meta['instanceId']}"
        self._sock = ipc.Socket(ipc.PUSH)
        self._sock.listen(ipc.ipc_url(f"sink_{tag}"))
        ins = self.manager.get_or_start(self.plugin_name)
        ins.command("start", {
            "symbolName": self.symbol, "pluginType": "sink",
            "meta": self._meta, "config": self.props,
        })

    def collect(self, item: Any) -> None:
        if self._sock is None:
            self.connect()
        self._sock.send(json.dumps(item, default=str).encode(), 5000)

    def close(self) -> None:
        ins = self.manager.get_live(self.plugin_name)  # never spawn on teardown
        if ins is not None:
            try:
                ins.command("stop", {"symbolName": self.symbol,
                                     "pluginType": "sink", "meta": self._meta})
            except Exception:
                pass
        if self._sock is not None:
            self._sock.close()
            self._sock = None


# symbols each plugin actually bound (builtins shadow plugin names, so this
# can be a subset of the declared lists) — consulted on uninstall
_bound: Dict[str, Dict[str, List[str]]] = {}


def bind_symbols(manager, meta) -> None:
    """Register a plugin's declared symbols into the io / function registries
    (binder chain: builtin first, then portable — factory.go:58-61)."""
    from ..functions import registry as func_registry
    from ..io import registry as io_registry

    bound = _bound.setdefault(meta.name, {"functions": [], "sources": [],
                                          "sinks": []})
    for sym in meta.functions:
        if func_registry.lookup(sym) is not None:
            continue  # builtins win, like the weight-ordered binder chain
        pf = PortableFunc(manager, meta.name, sym)
        func_registry.register_def(func_registry.FunctionDef(
            name=sym.lower(), ftype=func_registry.SCALAR,
            exec=(lambda args, ctx, _pf=pf: _pf.exec(*args)),
        ))
        bound["functions"].append(sym.lower())
    for sym in meta.sources:
        if io_registry.has_source(sym):
            continue  # builtin connectors win too
        io_registry.register_source(
            sym, lambda _m=manager, _p=meta.name, _s=sym: PortableSource(_m, _p, _s))
        bound["sources"].append(sym.lower())
    for sym in meta.sinks:
        if io_registry.has_sink(sym):
            continue
        io_registry.register_sink(
            sym, lambda _m=manager, _p=meta.name, _s=sym: PortableSink(_m, _p, _s))
        bound["sinks"].append(sym.lower())


def unbind_symbols(meta) -> None:
    """Drop exactly the entries this plugin bound (never builtins or another
    plugin's) so names resolve to 'unknown' again."""
    from ..functions import registry as func_registry
    from ..io import registry as io_registry

    bound = _bound.pop(meta.name, None)
    if bound is None:
        return
    for sym in bound["functions"]:
        func_registry.unregister(sym)
    for sym in bound["sources"]:
        io_registry.unregister_source(sym)
    for sym in bound["sinks"]:
        io_registry.unregister_sink(sym)
