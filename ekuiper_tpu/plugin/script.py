"""Script functions — analogue of the reference's embedded JavaScript UDFs
(internal/plugin/js/function.go:21-40, managed via rpc_script.go; scripts
stored in KV and hot-loaded per call).

Divergence note: the reference embeds goja (a Go JS interpreter). This host
is Python, so runtime-defined scripts are Python — same capability (define/
update SQL functions at runtime without recompiling or restarting), same
management surface. A script must define

    def exec(args, ctx):   # -> value
        ...

or be a single expression over `args`.

SECURITY NOTE: scripts are TRUSTED CODE, exactly like plugins. They run
in-process with a curated builtin namespace for hygiene (to catch honest
mistakes), but CPython offers no real sandbox — a malicious script can
escape via attribute traversal. The reference's goja JS runtime is actually
isolated; this host is not. Only expose the /scripts management surface to
operators who are already trusted to install plugins. For untrusted code,
run it out-of-process via the portable-plugin worker path (plugin/manager.py).
"""
from __future__ import annotations

import json
import math
import threading
from typing import Any, Dict, List, Optional

from ..functions import registry as func_registry
from ..utils.infra import EngineError, logger

_SAFE_BUILTINS = {
    "abs": abs, "all": all, "any": any, "bool": bool, "dict": dict,
    "divmod": divmod, "enumerate": enumerate, "filter": filter,
    "float": float, "format": format, "int": int, "len": len, "list": list,
    "map": map, "max": max, "min": min, "pow": pow, "range": range,
    "repr": repr, "reversed": reversed, "round": round, "set": set,
    "sorted": sorted, "str": str, "sum": sum, "tuple": tuple, "zip": zip,
    "isinstance": isinstance, "Exception": Exception, "ValueError": ValueError,
}


def _compile_script(name: str, source: str):
    """-> callable(args, ctx). Accepts a def-exec script or one expression."""
    env: Dict[str, Any] = {
        "__builtins__": _SAFE_BUILTINS, "math": math, "json": json,
    }
    try:
        # expression form first: "args[0] * 2" is also a valid statement, so
        # the order matters — a bare expression must not execute at compile
        code = compile(source, f"<script:{name}>", "eval")
        return lambda args, ctx, _c=code, _e=env: eval(_c, _e, {"args": args, "ctx": ctx})  # noqa: S307
    except SyntaxError:
        code = compile(source, f"<script:{name}>", "exec")
        exec(code, env)  # noqa: S102 — trusted code; curated builtins only for hygiene
    fn = env.get("exec")
    if not callable(fn):
        raise EngineError(f"script {name} must define exec(args, ctx) "
                          "or be a single expression")
    return fn


class ScriptManager:
    """CRUD + function-registry binding for scripts (rpc_script.go:27-64)."""

    _instance: Optional["ScriptManager"] = None

    def __init__(self, store=None) -> None:
        self._kv = store.kv("script") if store is not None else None
        self._cache: Dict[str, Any] = {}  # name -> compiled fn
        self._mu = threading.Lock()
        if self._kv is not None:
            for name in self._kv.keys():
                try:
                    self._bind(name, json.loads(self._kv.get(name)))
                except Exception as e:
                    logger.warning("script %s restore failed: %s", name, e)

    @classmethod
    def global_instance(cls) -> "ScriptManager":
        if cls._instance is None:
            cls._instance = ScriptManager()
        return cls._instance

    @classmethod
    def set_global(cls, mgr: "ScriptManager") -> None:
        cls._instance = mgr

    # ----------------------------------------------------------------- CRUD
    def create(self, spec: Dict[str, Any], overwrite: bool = False) -> None:
        """spec: {"id": name, "description": ..., "script": source,
        "isAgg": bool} — the reference's script json shape."""
        name = spec.get("id", "")
        if not name or not spec.get("script"):
            raise EngineError("script needs id and script fields")
        if not overwrite and self.get(name) is not None:
            raise EngineError(f"script {name} already exists")
        _compile_script(name, spec["script"])  # validate before persisting
        if self._kv is not None:
            self._kv.set(name, json.dumps(spec))
        self._bind(name, spec)

    def _bind(self, name: str, spec: Dict[str, Any]) -> None:
        fn = _compile_script(name, spec["script"])
        with self._mu:
            self._cache[name.lower()] = fn
        ftype = (func_registry.AGGREGATE if spec.get("isAgg")
                 else func_registry.SCALAR)

        def call(args: List[Any], ctx, _name=name.lower()) -> Any:
            with self._mu:
                f = self._cache.get(_name)
            if f is None:
                raise EngineError(f"script {_name} dropped")
            return f(args, ctx)

        func_registry.register_def(func_registry.FunctionDef(
            name=name.lower(), ftype=ftype, exec=call))

    def get(self, name: str) -> Optional[Dict[str, Any]]:
        if self._kv is None:
            return None
        raw, ok = self._kv.get_ok(name)
        return json.loads(raw) if ok else None

    def list(self) -> List[str]:
        return sorted(self._kv.keys()) if self._kv is not None else []

    def update(self, spec: Dict[str, Any]) -> None:
        self.create(spec, overwrite=True)

    def delete(self, name: str) -> None:
        if self._kv is not None:
            self._kv.delete(name)
        with self._mu:
            self._cache.pop(name.lower(), None)
        func_registry.unregister(name)


class ScriptOpNode:
    """Inline script operator for graph rules
    (reference: internal/topo/operator/script_operator.go).

    The script defines exec(msg, meta) -> dict | list[dict] | None
    (None drops the message). Implemented lazily to avoid a hard dependency
    from the planner module."""

    def __new__(cls, name: str, source: str, is_agg: bool = False, **kw):
        from ..runtime.node import Node

        class _Impl(Node):
            def __init__(self) -> None:
                super().__init__(name, op_type="op", **kw)
                self.fn = _compile_graph_script(name, source)

            def process(self, item: Any) -> None:
                from ..data.batch import ColumnBatch
                from ..data.rows import Row, Tuple as RowTuple

                if isinstance(item, ColumnBatch):
                    srcs = item.to_tuples()
                elif isinstance(item, Row):
                    srcs = [item]
                elif isinstance(item, dict):
                    srcs = [RowTuple(message=item)]
                else:
                    self.emit(item)
                    return
                for src in srcs:
                    meta = getattr(src, "metadata", None) or {}
                    res = self.fn(src.message if isinstance(src, RowTuple)
                                  else src.all_values(), dict(meta))
                    if res is None:
                        continue
                    for msg_out in res if isinstance(res, list) else [res]:
                        # wrap dicts as Rows so downstream operator nodes
                        # (filter/pick/switch) process them instead of
                        # passing an unknown type through; keep the source
                        # tuple's timestamp/metadata/emitter so event-time
                        # windows downstream still bucket correctly
                        if isinstance(msg_out, dict):
                            msg_out = RowTuple(
                                message=msg_out,
                                emitter=getattr(src, "emitter", ""),
                                timestamp=getattr(src, "timestamp", 0),
                                metadata=meta,
                            )
                        self.emit(msg_out)

        return _Impl()


def _compile_graph_script(name: str, source: str):
    env: Dict[str, Any] = {
        "__builtins__": _SAFE_BUILTINS, "math": math, "json": json,
    }
    code = compile(source, f"<script-op:{name}>", "exec")
    exec(code, env)  # noqa: S102
    fn = env.get("exec")
    if not callable(fn):
        raise EngineError(f"script op {name} must define exec(msg, meta)")
    return fn
