"""Batched homogeneous rules — one compiled device program serving N rules.

The reference's fan-out benchmark runs 300 rules over one shared MQTT stream,
each rule a goroutine pipeline applying its own filter (BASELINE.md row 5;
reference test/benchmark/multiple_rules/). The TPU-native equivalent batches
homogeneous rules on a LEADING RULE AXIS: rules that differ only in literal
constants (thresholds etc.) canonicalize to one kernel plan whose literals
become per-rule parameters, the group-by state becomes
{comp: (R, n_panes, capacity, k)}, and `jax.vmap` over the rule axis turns
the single-rule fold into one XLA program folding every rule at once.

What this buys vs N independent pipelines:
- ONE ingest + decode + key-encode per batch (shared, host)
- ONE H2D upload per batch (the batch is broadcast across the rule axis)
- ONE device program launch per batch, one finalize/transfer per window
- per-rule cost on device is a scatter-add slice — MXU/VPU-friendly and
  compiled once, not R interpreter loops

Homogeneity contract (`build_rule_batch` validates): identical SELECT
fields, window, GROUP BY dims, source, and HAVING; WHERE clauses must be
structurally identical with numeric literals free to differ per rule.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..ops.aggspec import KernelPlan, extract_kernel_plan
from ..ops.groupby import DeviceGroupBy, _INIT, apply_int_semantics
from ..sql import ast

PARAM_PREFIX = "__param_"


# ------------------------------------------------------- canonicalization
def _canonicalize_expr(expr: Optional[ast.Expr],
                       params: List[float]) -> Optional[ast.Expr]:
    """Replace numeric literals with per-rule parameter refs, appending each
    literal's value to `params` in placeholder order."""
    if expr is None:
        return None
    sub = lambda e: _canonicalize_expr(e, params)  # noqa: E731
    if isinstance(expr, (ast.IntegerLiteral, ast.NumberLiteral)):
        idx = len(params)
        params.append(float(expr.val))
        return ast.FieldRef(name=f"{PARAM_PREFIX}{idx}")
    if isinstance(expr, ast.BinaryExpr):
        return ast.BinaryExpr(expr.op, sub(expr.lhs), sub(expr.rhs))
    if isinstance(expr, ast.UnaryExpr):
        return ast.UnaryExpr(expr.op, sub(expr.expr))
    if isinstance(expr, ast.BetweenExpr):
        return ast.BetweenExpr(sub(expr.value), sub(expr.lo), sub(expr.hi),
                               expr.negate)
    if isinstance(expr, ast.CaseExpr):
        return ast.CaseExpr(
            sub(expr.value) if expr.value is not None else None,
            [ast.WhenClause(sub(w.cond), sub(w.result)) for w in expr.whens],
            sub(expr.else_expr) if expr.else_expr is not None else None,
        )
    # anything else (field refs, string/bool literals, calls, IN lists) must
    # match exactly across rules — returned as-is
    return expr


@dataclass
class RuleBatchSpec:
    """Canonical template + per-rule parameters for a homogeneous group."""

    stmt: ast.SelectStatement  # canonical statement (params substituted)
    plan: KernelPlan  # kernel plan compiled from the canonical statement
    param_names: List[str]
    params: np.ndarray  # (R, P) float32
    rule_ids: List[str]


def build_rule_batch(
    rule_ids: List[str], stmts: List[ast.SelectStatement],
) -> RuleBatchSpec:
    """Validate homogeneity and build the canonical parameterized plan.
    Raises ValueError when the statements cannot batch."""
    if not stmts:
        raise ValueError("empty rule group")
    canon_keys = []
    param_rows: List[List[float]] = []
    canon_stmt = None
    for stmt in stmts:
        params: List[float] = []
        cond = _canonicalize_expr(stmt.condition, params)
        key = (
            repr(stmt.fields), repr(stmt.window), repr(stmt.dimensions),
            repr(cond), repr(stmt.having), repr(stmt.sources),
            repr(stmt.sorts),
        )
        canon_keys.append(key)
        param_rows.append(params)
        if canon_stmt is None:
            canon_stmt = ast.SelectStatement(
                fields=stmt.fields, sources=stmt.sources, joins=stmt.joins,
                condition=cond, dimensions=stmt.dimensions,
                window=stmt.window, having=stmt.having, sorts=stmt.sorts,
                limit=stmt.limit,
            )
    if len(set(canon_keys)) != 1:
        raise ValueError(
            "rules are not homogeneous: statements must be identical up to "
            "numeric literals in WHERE")
    if len({len(p) for p in param_rows}) != 1:
        raise ValueError("rules have differing parameter counts")
    plan = extract_kernel_plan(canon_stmt)
    if plan is None:
        raise ValueError("rule group is not device-eligible")
    if any(s.kind == "heavy_hitters" for s in plan.specs):
        # hh finalize is a host-side top-k recovery, not part of the vmapped
        # device finalize program — such rules run as individual fused nodes
        raise ValueError("heavy_hitters rules do not batch")
    n_params = len(param_rows[0])
    param_names = [f"{PARAM_PREFIX}{i}" for i in range(n_params)]
    # params are injected at fold time, not uploaded as batch columns
    plan.columns -= set(param_names)
    return RuleBatchSpec(
        stmt=canon_stmt, plan=plan, param_names=param_names,
        params=np.asarray(param_rows, dtype=np.float32).reshape(
            len(stmts), n_params),
        rule_ids=list(rule_ids),
    )


# ------------------------------------------------------------ batched kernel
class BatchedGroupBy(DeviceGroupBy):
    """DeviceGroupBy with a leading rule axis: state
    {comp: (R, n_panes, capacity, k)}, one vmapped fold/finalize program for
    all R rules. The key table, batch upload, and launch are shared; only
    the per-rule filter parameters differ along the axis."""

    supports_prefinalize = False  # group emits are fetched in one transfer
    watch_prefix = "multirule"

    def __init__(self, spec: RuleBatchSpec, capacity: int = 16384,
                 n_panes: int = 1, micro_batch: int = 4096) -> None:
        import jax

        self.n_rules = len(spec.rule_ids)
        self.param_names = spec.param_names
        self.rule_ids = spec.rule_ids
        super().__init__(spec.plan, capacity=capacity, n_panes=n_panes,
                         micro_batch=micro_batch)
        import jax.numpy as jnp

        self._params = jnp.asarray(spec.params)  # (R, P)
        from ..runtime.aotcache import aot_jit

        self._fold = aot_jit(self._batched_fold_impl,
                                 op="multirule.fold", donate_argnums=(0,))
        self._finalize = aot_jit(self._batched_finalize_impl,
                                     op="multirule.finalize",
                                     kind="boundary",
                                     static_argnums=(1,))
        self._reset_pane = aot_jit(self._batched_reset_impl,
                                       op="multirule.reset_pane",
                                       kind="boundary",
                                       donate_argnums=(0,))

    # state ------------------------------------------------------------
    def init_state(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ..ops.aggspec import WIDE_COMPONENTS
        from ..ops.groupby import _wide_size

        state: Dict[str, Any] = {}
        for comp, spec_idxs in self.comp_specs.items():
            shape = (self.n_rules, self.n_panes, self.capacity, len(spec_idxs))
            if comp in WIDE_COMPONENTS:
                shape = shape + (_wide_size(comp),)
            state[comp] = jnp.full(shape, _INIT[comp], dtype=jnp.float32)
        state["act"] = jnp.zeros(
            (self.n_rules, self.n_panes, self.capacity), dtype=jnp.float32)
        return state

    def grow(self, state: Dict[str, Any], new_capacity: int) -> Dict[str, Any]:
        import jax.numpy as jnp

        out: Dict[str, Any] = {}
        for comp, arr in state.items():
            np_arr = np.asarray(arr)
            pad_shape = list(np_arr.shape)
            pad_shape[2] = new_capacity - np_arr.shape[2]  # capacity axis
            pad = np.full(pad_shape, _INIT[comp], dtype=np_arr.dtype)
            out[comp] = jnp.asarray(np.concatenate([np_arr, pad], axis=2))
        self.capacity = new_capacity
        return out

    # fold -------------------------------------------------------------
    def _batched_fold_impl(self, state, cols, slots, n_valid, pane_idx):
        import jax

        def one_rule(st, par):
            c = dict(cols)
            for i, name in enumerate(self.param_names):
                c[name] = par[i]  # scalar broadcasts against row columns
                c["__valid_" + name] = None
            return DeviceGroupBy._fold_impl(self, st, c, slots, n_valid,
                                            pane_idx)

        return jax.vmap(one_rule, in_axes=(0, 0))(state, self._params)

    # finalize ----------------------------------------------------------
    def _batched_finalize_impl(self, state, pane_mask_tuple):
        import jax

        return jax.vmap(
            lambda st: DeviceGroupBy._finalize_impl(self, st, pane_mask_tuple)
        )(state)

    def _slice_keys(self, n_keys: int) -> int:
        """Device-side transfer cut: round the live-key count up to a power
        of two (floor 1024) so the (R, S+1, K) result ships K≈n_keys floats
        instead of full capacity — at R=63 rules the full-capacity transfer
        is 4x the bytes for a quarter-full table — while the rounded shape
        set stays bounded (one slice executable per power of two)."""
        if n_keys >= self.capacity:
            return self.capacity
        k = 1024
        while k < n_keys:
            k <<= 1
        return min(k, self.capacity)

    def finalize_begin(self, state: Dict[str, Any], n_keys: int,
                       panes: Optional[List[int]] = None):
        """Dispatch the stacked finalize and return the (R, S+1, K) DEVICE
        array (K = rounded n_keys) — the async boundary path hands this to
        the emit worker, which fetches and slices host-side."""
        pane_mask = np.zeros(self.n_panes, dtype=np.bool_)
        if panes is None:
            pane_mask[:] = True
        else:
            pane_mask[panes] = True
        dev = self._finalize(state, tuple(pane_mask.tolist()))
        return dev[:, :, : self._slice_keys(n_keys)]

    def finalize(
        self, state: Dict[str, Any], n_keys: int,
        panes: Optional[List[int]] = None,
    ) -> Tuple[List[np.ndarray], np.ndarray]:
        """Per-spec value arrays of shape (R, n_keys) + act (R, n_keys) —
        ONE device launch, ONE transfer for the whole rule group."""
        stacked = np.asarray(self.finalize_begin(state, n_keys, panes))
        outs = [stacked[:, i, :n_keys] for i in range(len(self.plan.specs))]
        act = stacked[:, -1, :n_keys]
        outs = apply_int_semantics(self.plan.specs, outs)
        return outs, act

    # reset -------------------------------------------------------------
    def _batched_reset_impl(self, state, pane_idx):
        import jax

        return jax.vmap(
            lambda st: DeviceGroupBy._reset_pane_impl(self, st, pane_idx)
        )(state)
