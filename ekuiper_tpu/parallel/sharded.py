"""Sharded GROUP BY aggregation step — the multi-chip form of ops/groupby.py.

SPMD layout over a Mesh(("rows", "keys")):

- event batch columns + slot ids: sharded over "rows" (data parallel);
- per-key partial state (capacity axis): sharded over "keys" — each device
  owns capacity/K contiguous slots;
- fold (shard_map): every device folds ITS row shard into a local partial
  for ITS key range (rows whose slot falls outside the local range mask
  out), then `psum` over "rows" merges the row-shards. No gather of raw
  events ever happens — only the (capacity/K, n_specs) partials move, and
  only across the rows axis;
- finalize: local finalize per key shard, `all_gather` over "keys" at
  window triggers only.

This mirrors the scaling-book recipe: pick the mesh, shard the state/batch,
let XLA insert the collectives, keep them on ICI.

The same code drives the 256-rule fan-out config: rules are batched on a
leading axis and vmapped, so one compiled program serves all homogeneous
rules per step (reference analogue: subtopo shared-source fan-out,
internal/topo/subtopo_pool.go:34).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..ops.aggspec import KernelPlan
from ..ops.groupby import _INIT

COMPONENTS = ("n", "s1", "s2", "mn", "mx")


class ShardedGroupBy:
    """Multi-chip group-by aggregation over a ("rows", "keys") mesh.

    State layout: {comp: (capacity, n_specs_for_comp)} with capacity sharded
    over "keys". Batch layout: cols (N,), slots (N,) sharded over "rows".
    """

    def __init__(
        self, plan: KernelPlan, mesh, capacity: int = 16384,
        micro_batch: int = 4096,
    ) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.plan = plan
        self.mesh = mesh
        self.capacity = capacity
        self.micro_batch = micro_batch
        self.n_keys_shards = mesh.shape["keys"]
        self.n_row_shards = mesh.shape["rows"]
        if capacity % self.n_keys_shards != 0:
            raise ValueError("capacity must divide evenly across the keys axis")
        if micro_batch % self.n_row_shards != 0:
            raise ValueError(
                f"micro_batch {micro_batch} must divide evenly across the "
                f"rows axis ({self.n_row_shards} shards)"
            )
        self.comp_specs: Dict[str, List[int]] = {}
        for i, spec in enumerate(plan.specs):
            for comp in spec.components:
                self.comp_specs.setdefault(comp, []).append(i)

        from ..ops.aggspec import WIDE_COMPONENTS

        self.state_sharding = {
            comp: NamedSharding(
                mesh,
                P("keys", None, None) if comp in WIDE_COMPONENTS else P("keys", None),
            )
            for comp in self.comp_specs
        }
        self.state_sharding["act"] = NamedSharding(mesh, P("keys"))
        self.batch_sharding = NamedSharding(mesh, P("rows"))

        self._fold = self._build_fold()
        self._finalize = self._build_finalize()

    # ------------------------------------------------------------------ state
    def init_state(self):
        import jax
        import jax.numpy as jnp

        from ..ops.aggspec import WIDE_COMPONENTS
        from ..ops.groupby import _wide_size

        def mk(comp):
            if comp == "act":
                shape = (self.capacity,)
            else:
                shape = (self.capacity, len(self.comp_specs[comp]))
                if comp in WIDE_COMPONENTS:
                    shape = shape + (_wide_size(comp),)
            return jax.device_put(
                jnp.full(shape, _INIT[comp], dtype=jnp.float32),
                self.state_sharding[comp],
            )

        state = {comp: mk(comp) for comp in self.comp_specs}
        state["act"] = mk("act")
        return state

    # ------------------------------------------------------------------- fold
    def _build_fold(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        try:
            from jax import shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map

        comp_specs = self.comp_specs
        plan = self.plan
        cap_per_shard = self.capacity // self.n_keys_shards

        def local_fold(state, cols, slots, row_valid):
            """Runs per device: fold my row shard into my key range, then
            psum partials across the rows axis."""
            kidx = jax.lax.axis_index("keys")
            offset = kidx * cap_per_shard
            local = slots - offset
            in_range = jnp.logical_and(local >= 0, local < cap_per_shard)
            base = jnp.logical_and(row_valid, in_range)
            if plan.filter is not None:
                base = jnp.logical_and(base, plan.filter(cols))
            local = jnp.clip(local, 0, cap_per_shard - 1)

            per_spec = []
            for spec in plan.specs:
                if spec.arg is None:
                    v = jnp.ones_like(base, dtype=jnp.float32)
                    m = base
                else:
                    v = spec.arg(cols).astype(jnp.float32)
                    m = jnp.logical_and(base, jnp.logical_not(jnp.isnan(v)))
                if spec.filter is not None:
                    m = jnp.logical_and(m, spec.filter(cols))
                per_spec.append((v, m))

            out = {}
            act_add = jnp.zeros((cap_per_shard,), jnp.float32).at[local].add(
                base.astype(jnp.float32)
            )
            out["act"] = state["act"] + jax.lax.psum(act_add, "rows")
            for comp, spec_idxs in comp_specs.items():
                arr = state[comp]
                adds = []
                for k, si in enumerate(spec_idxs):
                    v, m = per_spec[si]
                    mf = m.astype(jnp.float32)
                    if comp == "n":
                        col = jnp.zeros((cap_per_shard,), jnp.float32).at[local].add(mf)
                        col = jax.lax.psum(col, "rows")
                        adds.append(arr[:, k] + col)
                    elif comp == "s1":
                        col = jnp.zeros((cap_per_shard,), jnp.float32).at[local].add(
                            jnp.where(m, v, 0.0)
                        )
                        adds.append(arr[:, k] + jax.lax.psum(col, "rows"))
                    elif comp == "s2":
                        col = jnp.zeros((cap_per_shard,), jnp.float32).at[local].add(
                            jnp.where(m, v * v, 0.0)
                        )
                        adds.append(arr[:, k] + jax.lax.psum(col, "rows"))
                    elif comp == "mn":
                        col = jnp.full((cap_per_shard,), jnp.inf, jnp.float32).at[
                            local
                        ].min(jnp.where(m, v, jnp.inf))
                        col = jax.lax.pmin(col, "rows")
                        adds.append(jnp.minimum(arr[:, k], col))
                    elif comp == "mx":
                        col = jnp.full((cap_per_shard,), -jnp.inf, jnp.float32).at[
                            local
                        ].max(jnp.where(m, v, -jnp.inf))
                        col = jax.lax.pmax(col, "rows")
                        adds.append(jnp.maximum(arr[:, k], col))
                    elif comp == "hll":
                        from ..ops.sketches import hll_parts

                        reg, rho = hll_parts(v)
                        wide = jnp.zeros(
                            (cap_per_shard, arr.shape[-1]), jnp.float32
                        ).at[local, reg].max(jnp.where(m, rho, 0.0))
                        wide = jax.lax.pmax(wide, "rows")
                        adds.append(jnp.maximum(arr[:, k, :], wide))
                    elif comp == "hist":
                        from ..ops.sketches import hist_bin

                        b = hist_bin(v)
                        wide = jnp.zeros(
                            (cap_per_shard, arr.shape[-1]), jnp.float32
                        ).at[local, b].add(mf)
                        adds.append(arr[:, k, :] + jax.lax.psum(wide, "rows"))
                out[comp] = jnp.stack(adds, axis=1)
            return out

        from ..ops.aggspec import WIDE_COMPONENTS

        state_specs = {
            comp: P("keys", None, None) if comp in WIDE_COMPONENTS
            else P("keys", None)
            for comp in comp_specs
        }
        state_specs["act"] = P("keys")

        def step(state, cols, slots, row_valid):
            return shard_map(
                local_fold,
                mesh=self.mesh,
                in_specs=(
                    state_specs,
                    {name: P("rows") for name in cols},
                    P("rows"),
                    P("rows"),
                ),
                out_specs=state_specs,
            )(state, cols, slots, row_valid)

        import jax

        return jax.jit(step, donate_argnums=(0,))

    def fold(self, state, cols: Dict[str, np.ndarray], slots: np.ndarray):
        """Host entry: pad to micro_batch (divisible by row shards), upload
        with shardings, run the SPMD step."""
        import jax
        import jax.numpy as jnp

        from ..ops.aggspec import materialize_hll_columns

        n = len(slots)
        mb = self.micro_batch
        cols = materialize_hll_columns(self.plan.columns, cols, n)
        for start in range(0, max(n, 1), mb):
            end = min(start + mb, n)
            cnt = end - start
            if cnt <= 0:
                break
            pad = mb - cnt
            dev_cols = {}
            for name in self.plan.columns:
                arr = np.asarray(cols[name][start:end], dtype=np.float32)
                if pad:
                    arr = np.pad(arr, (0, pad))
                dev_cols[name] = jax.device_put(arr, self.batch_sharding)
            s = slots[start:end].astype(np.int32)
            if pad:
                s = np.pad(s, (0, pad))
            rv = np.zeros(mb, dtype=np.bool_)
            rv[:cnt] = True
            state = self._fold(
                state,
                dev_cols,
                jax.device_put(s, self.batch_sharding),
                jax.device_put(rv, self.batch_sharding),
            )
        return state

    # --------------------------------------------------------------- finalize
    def _build_finalize(self):
        import jax
        import jax.numpy as jnp

        comp_specs = self.comp_specs
        plan = self.plan

        def fin(state):
            from ..ops.groupby import DeviceGroupBy

            outs = []
            for i, spec in enumerate(plan.specs):
                c = {
                    comp: state[comp][:, comp_specs[comp].index(i)]
                    for comp in spec.components
                }
                outs.append(DeviceGroupBy._final_value(spec, c))
            outs.append(state["act"])
            # stacked single output; XLA all_gathers the sharded capacity axis
            return jnp.stack(outs, axis=0)

        return jax.jit(fin)

    def finalize(self, state, n_keys: int) -> Tuple[List[np.ndarray], np.ndarray]:
        from ..ops.groupby import apply_int_semantics

        stacked = np.asarray(self._finalize(state))
        outs = [stacked[i][:n_keys] for i in range(len(self.plan.specs))]
        act = stacked[-1][:n_keys]
        outs = apply_int_semantics(self.plan.specs, outs)
        return outs, act

    def observe_dtypes(self, columns: Dict[str, np.ndarray]) -> None:
        from ..ops.groupby import observe_int_inputs

        observe_int_inputs(self.plan.specs, columns)

    def reset(self, state):
        """Zero the window partials in place (jitted, donated) — no host
        round trip or re-allocation on the per-trigger hot path."""
        import jax
        import jax.numpy as jnp

        if not hasattr(self, "_reset"):
            def do_reset(st):
                return {
                    comp: jnp.full_like(arr, _INIT[comp])
                    for comp, arr in st.items()
                }

            self._reset = jax.jit(do_reset, donate_argnums=(0,))
        return self._reset(state)
