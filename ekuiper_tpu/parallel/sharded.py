"""Sharded GROUP BY aggregation — the multi-chip form of ops/groupby.py.

SPMD layout over a Mesh(("rows", "keys")):

- event batch columns + slot ids + validity masks: sharded over "rows"
  (data parallel);
- per-key partial state (n_panes, capacity, k): capacity axis sharded over
  "keys" — each device owns capacity/K contiguous slots;
- fold (shard_map): every device folds ITS row shard into a local partial
  for ITS key range (rows whose slot falls outside the local range mask
  out), then one `psum`/`pmin`/`pmax` per state component merges the
  row-shards. No gather of raw events ever happens — only the
  (capacity/K, k) partials move, and only across the rows axis;
- finalize: inherited from DeviceGroupBy (pane-mask merge + final values);
  XLA all_gathers the sharded capacity axis only at window triggers.

ShardedGroupBy subclasses DeviceGroupBy so pane semantics (hopping
windows), per-column validity masks, grow(), checkpointing, and the
finalize math are all the *same code* as the single-chip path — parity by
construction. Only state placement and the fold step differ.

This mirrors the scaling-book recipe: pick the mesh, shard the state/batch,
let XLA insert the collectives, keep them on ICI.

Reference analogue: the process-level scale-out of
internal/topo/subtopo_pool.go:34 (N rules sharing source fan-out) becomes a
device mesh here; the cross-worker merge the reference never needs (each Go
rule is single-process) is the psum over "rows".
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..ops.aggspec import KernelPlan, WIDE_COMPONENTS
from ..ops.groupby import DeviceGroupBy, _INIT


class ShardedGroupBy(DeviceGroupBy):
    """Multi-chip group-by aggregation over a ("rows", "keys") mesh.

    State layout matches DeviceGroupBy: {comp: (n_panes, capacity, k[, R])},
    act (n_panes, capacity), with capacity sharded over "keys". Batch
    layout: cols/valid/slots (N,) sharded over "rows".
    """

    watch_prefix = "sharded"

    # finalize runs collective gathers across the mesh; the pre-issued
    # emit pipeline (ops/prefinalize.py) is single-chip only for now
    supports_prefinalize = False

    def __init__(
        self, plan: KernelPlan, mesh, capacity: int = 16384,
        n_panes: int = 1, micro_batch: int = 4096,
        track_touch: bool = False,
    ) -> None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self.n_keys_shards = int(mesh.shape["keys"])
        self.n_row_shards = int(mesh.shape["rows"])
        # round capacity / micro_batch up to even divisibility across shards
        K, R = self.n_keys_shards, self.n_row_shards
        capacity = -(-int(capacity) // K) * K
        micro_batch = -(-int(micro_batch) // R) * R
        super().__init__(plan, capacity=capacity, n_panes=n_panes,
                         micro_batch=micro_batch, track_touch=track_touch)
        self.mesh_tag = f"{R}x{K}"
        self.state_sharding = {
            comp: NamedSharding(
                mesh,
                P(None, "keys", None, None) if comp in WIDE_COMPONENTS
                else P(None, "keys", None),
            )
            for comp in self.comp_specs
        }
        self.state_sharding["act"] = NamedSharding(mesh, P(None, "keys"))
        if track_touch:
            # tiered-state recency column (ops/tierstore.py): (capacity,)
            # uint32, key axis 0 — same key-range partitioning as the
            # pane state, so a later sharded tier reads local slices
            self.state_sharding["touch"] = NamedSharding(mesh, P("keys"))
        self.batch_sharding = NamedSharding(mesh, P("rows"))
        self.scalar_sharding = NamedSharding(mesh, P())
        # meshes spanning processes can't device_put host data onto
        # non-addressable devices; global arrays assemble from each
        # process's local slice instead (docs/DISTRIBUTED.md)
        import jax

        self.multiprocess = any(
            d.process_index != jax.process_index()
            for d in np.asarray(mesh.devices).flat)
        # the zero-copy ingest-prep upload stage (runtime/ingest.py) can
        # pre-place batch columns/slots with this kernel's row sharding —
        # single-process meshes only (multi-host data arrives as local
        # slices through _put)
        self.accepts_device_inputs = not self.multiprocess
        self._fold = self._build_fold()  # replaces the single-chip jit
        # per-row pane-vector variant (event-time multi-bucket batches);
        # built lazily — most rules never need it
        self._fold_vec = None
        self._all_true = None  # cached device ones-mask (common no-null case)
        # per-shard observability (kuiper_shard_* families): rows folded
        # into each shard's key range, counted host-side off the slot
        # vector (one bincount per batch), plus a key-occupancy hint the
        # driving node refreshes from its KeyTable
        self.shard_rows = np.zeros(K, dtype=np.int64)
        self.n_keys_hint = 0
        from ..utils.rulelog import current_rule

        _registry.register(self, current_rule())
        # retired-kernel rollup (the devwatch retire_dead discipline):
        # when this kernel is collected — rule dropped, or replaced by a
        # restore onto a different mesh — its accrued per-shard rows fold
        # into the module counters so kuiper_shard_rows_total stays
        # monotonic across 8->1->8 restore cycles. The finalize captures
        # shard_rows itself (note_rows mutates it in place), so the
        # callback always sees the final counts.
        import weakref as _weakref

        _weakref.finalize(
            self, _note_retired, _gen[0], current_rule(), self.shard_rows)

    def _put(self, arr, sharding):
        """Host→mesh placement that also works when the mesh spans
        processes: each process contributes its local slice of `arr`
        (callers pass process-local data in multi-host mode)."""
        import jax

        if self.multiprocess:
            return jax.make_array_from_process_local_data(sharding, arr)
        return jax.device_put(arr, sharding)

    # ------------------------------------------------------------------ state
    def init_state(self) -> Dict[str, Any]:
        import jax

        return {
            comp: self._put(arr, self.state_sharding[comp])
            for comp, arr in super().init_state().items()
        }

    def grow(self, state: Dict[str, Any], new_capacity: int) -> Dict[str, Any]:
        """Double the key capacity, preserving partials. The host roundtrip
        re-distributes slots to their new owner shard (global slot s lives on
        shard s // (capacity/K), so ranges shift when capacity grows)."""
        import jax

        new_capacity = -(-int(new_capacity) // self.n_keys_shards) * self.n_keys_shards
        out: Dict[str, Any] = {}
        for comp, arr in state.items():
            np_arr = np.asarray(arr)
            # the touch column is (capacity,), not pane-scoped — key axis
            # 0 there, axis 1 everywhere else; its uint32 dtype rides
            # np_arr.dtype (ops/groupby.py grew the same special case)
            key_axis = 0 if comp == "touch" else 1
            pad_shape = list(np_arr.shape)
            pad_shape[key_axis] = new_capacity - np_arr.shape[key_axis]
            pad = np.full(pad_shape, _INIT[comp], dtype=np_arr.dtype)
            out[comp] = self._put(
                np.concatenate([np_arr, pad], axis=key_axis),
                self.state_sharding[comp]
            )
        self.capacity = new_capacity
        return out

    def state_from_host(self, host: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Host partials -> mesh-sharded device state. Mesh-size-change
        tolerant: a checkpoint taken on a different shard count (incl.
        the single-chip kernel, K=1) may carry a capacity that does not
        divide this mesh's K — pad the key axis up to divisibility with
        each component's identity (the extra slots are unassigned; the
        KeyTable's dense slot ids are placement-independent, so every
        restored slot keeps its key). The uint32 touch column keeps its
        dtype (np.asarray preserves it; host_from_partials already
        typed it)."""
        import jax

        K = self.n_keys_shards
        out: Dict[str, Any] = {}
        cap = None
        for k, v in host.items():
            np_arr = np.asarray(v)
            key_axis = 0 if k == "touch" else 1
            c = np_arr.shape[key_axis]
            rounded = -(-int(c) // K) * K
            if rounded != c:
                pad_shape = list(np_arr.shape)
                pad_shape[key_axis] = rounded - c
                pad = np.full(pad_shape, _INIT.get(k, 0.0),
                              dtype=np_arr.dtype)
                np_arr = np.concatenate([np_arr, pad], axis=key_axis)
            cap = rounded if cap is None else max(cap, rounded)
            sharding = self.state_sharding.get(k)
            if sharding is None:
                # a checkpoint component this kernel form doesn't track
                # (host_from_partials should have dropped it) — replicate
                # rather than crash the restore
                sharding = self.scalar_sharding
            out[k] = self._put(np_arr, sharding)
        if cap is not None:
            self.capacity = int(cap)
        return out

    # ------------------------------------------------------------------- fold
    def _build_fold(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        try:
            from jax import shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map

        comp_specs = self.comp_specs
        plan = self.plan

        def local_fold(state, cols, slots, row_valid, pane_idx):
            """Runs per device: fold my row shard into my key range, then
            merge partials across the rows axis with one collective per
            state component."""
            cap_per_shard = state["act"].shape[1]
            kidx = jax.lax.axis_index("keys")
            offset = (kidx * cap_per_shard).astype(slots.dtype)
            local = slots - offset
            in_range = jnp.logical_and(local >= 0, local < cap_per_shard)
            base = jnp.logical_and(row_valid, in_range)
            if plan.filter is not None:
                base = jnp.logical_and(base, plan.filter(cols))
            local = jnp.clip(local, 0, cap_per_shard - 1)

            # same per-spec value/mask derivation as the single-chip fold:
            # per-column validity masks compose into per-spec masks
            per_spec: List[Tuple[Any, Any]] = []
            for spec in plan.specs:
                if spec.arg is None:
                    v = jnp.ones_like(base, dtype=jnp.float32)
                    m = base
                else:
                    v = spec.arg(cols).astype(jnp.float32)
                    m = base
                    for col in spec.arg.columns:
                        vm = cols.get("__valid_" + col)
                        if vm is not None:
                            m = jnp.logical_and(m, vm)
                    m = jnp.logical_and(m, jnp.logical_not(jnp.isnan(v)))
                if spec.filter is not None:
                    m = jnp.logical_and(m, spec.filter(cols))
                per_spec.append((v, m))

            out = {}
            act_add = jnp.zeros((cap_per_shard,), jnp.float32).at[local].add(
                base.astype(jnp.float32)
            )
            out["act"] = state["act"].at[pane_idx].add(
                jax.lax.psum(act_add, "rows")
            )
            if "touch" in state:
                # tier recency signal (ops/tierstore.py): per-slot touched-
                # row count, key axis sharded like the pane state — each
                # device's row shard contributes, one psum merges
                t_add = jnp.zeros((cap_per_shard,), jnp.uint32).at[local].add(
                    base.astype(jnp.uint32))
                out["touch"] = state["touch"] + jax.lax.psum(t_add, "rows")
            for comp, spec_idxs in comp_specs.items():
                arr = state[comp]
                parts = []
                for si in spec_idxs:
                    v, m = per_spec[si]
                    mf = m.astype(jnp.float32)
                    if comp == "n":
                        parts.append(
                            jnp.zeros((cap_per_shard,), jnp.float32)
                            .at[local].add(mf)
                        )
                    elif comp == "s1":
                        parts.append(
                            jnp.zeros((cap_per_shard,), jnp.float32)
                            .at[local].add(jnp.where(m, v, 0.0))
                        )
                    elif comp == "s2":
                        parts.append(
                            jnp.zeros((cap_per_shard,), jnp.float32)
                            .at[local].add(jnp.where(m, v * v, 0.0))
                        )
                    elif comp == "mn":
                        parts.append(
                            jnp.full((cap_per_shard,), jnp.inf, jnp.float32)
                            .at[local].min(jnp.where(m, v, jnp.inf))
                        )
                    elif comp == "mx":
                        parts.append(
                            jnp.full((cap_per_shard,), -jnp.inf, jnp.float32)
                            .at[local].max(jnp.where(m, v, -jnp.inf))
                        )
                    elif comp == "hll":
                        from ..ops.sketches import hll_parts

                        reg, rho = hll_parts(v)
                        parts.append(
                            jnp.zeros((cap_per_shard, arr.shape[-1]), jnp.float32)
                            .at[local, reg].max(jnp.where(m, rho, 0.0))
                        )
                    elif comp == "hist":
                        from ..ops.sketches import hist_bin

                        b = hist_bin(v)
                        parts.append(
                            jnp.zeros((cap_per_shard, arr.shape[-1]), jnp.float32)
                            .at[local, b].add(mf)
                        )
                stacked = jnp.stack(parts, axis=1)  # (cap, k[, R])
                if comp in ("n", "s1", "s2", "hist"):
                    merged = jax.lax.psum(stacked, "rows")
                    out[comp] = arr.at[pane_idx].add(merged)
                elif comp == "mn":
                    merged = jax.lax.pmin(stacked, "rows")
                    out[comp] = arr.at[pane_idx].min(merged)
                else:  # mx, hll merge by max
                    merged = jax.lax.pmax(stacked, "rows")
                    out[comp] = arr.at[pane_idx].max(merged)
            return out

        state_specs = {
            comp: P(None, "keys", None, None) if comp in WIDE_COMPONENTS
            else P(None, "keys", None)
            for comp in comp_specs
        }
        state_specs["act"] = P(None, "keys")
        if self.track_touch:
            state_specs["touch"] = P("keys")
        cols_specs: Dict[str, Any] = {}
        for name in plan.columns:
            cols_specs[name] = P("rows")
            cols_specs["__valid_" + name] = P("rows")

        def step(state, cols, slots, row_valid, pane_idx):
            return shard_map(
                local_fold,
                mesh=self.mesh,
                in_specs=(state_specs, cols_specs, P("rows"), P("rows"), P()),
                out_specs=state_specs,
            )(state, cols, slots, row_valid, pane_idx)

        from ..runtime.aotcache import aot_jit

        return aot_jit(step, op=self._watch_op("fold_step"),
                           donate_argnums=(0,))

    def _build_fold_vec(self):
        """Per-row pane-vector fold (event-time multi-bucket batches under
        the mesh): each device scatters its row shard into (n_panes,
        local_capacity) partials, one collective per component merges the
        rows axis, and the full-shape merge folds into the state."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        try:
            from jax import shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map

        comp_specs = self.comp_specs
        plan = self.plan
        n_panes = self.n_panes

        def local_fold(state, cols, slots, row_valid, pane_vec):
            cap_per_shard = state["act"].shape[1]
            kidx = jax.lax.axis_index("keys")
            offset = (kidx * cap_per_shard).astype(slots.dtype)
            local = slots - offset
            in_range = jnp.logical_and(local >= 0, local < cap_per_shard)
            base = jnp.logical_and(row_valid, in_range)
            if plan.filter is not None:
                base = jnp.logical_and(base, plan.filter(cols))
            local = jnp.clip(local, 0, cap_per_shard - 1)
            pv = pane_vec.astype(jnp.int32)

            per_spec: List[Tuple[Any, Any]] = []
            for spec in plan.specs:
                if spec.arg is None:
                    v = jnp.ones_like(base, dtype=jnp.float32)
                    m = base
                else:
                    v = spec.arg(cols).astype(jnp.float32)
                    m = base
                    for col in spec.arg.columns:
                        vm = cols.get("__valid_" + col)
                        if vm is not None:
                            m = jnp.logical_and(m, vm)
                    m = jnp.logical_and(m, jnp.logical_not(jnp.isnan(v)))
                if spec.filter is not None:
                    m = jnp.logical_and(m, spec.filter(cols))
                per_spec.append((v, m))

            out = {}
            act_add = (jnp.zeros((n_panes, cap_per_shard), jnp.float32)
                       .at[pv, local].add(base.astype(jnp.float32)))
            out["act"] = state["act"] + jax.lax.psum(act_add, "rows")
            if "touch" in state:
                t_add = jnp.zeros((cap_per_shard,), jnp.uint32).at[local].add(
                    base.astype(jnp.uint32))
                out["touch"] = state["touch"] + jax.lax.psum(t_add, "rows")
            for comp, spec_idxs in comp_specs.items():
                arr = state[comp]
                parts = []
                for si in spec_idxs:
                    v, m = per_spec[si]
                    mf = m.astype(jnp.float32)
                    if comp == "n":
                        parts.append(
                            jnp.zeros((n_panes, cap_per_shard), jnp.float32)
                            .at[pv, local].add(mf))
                    elif comp == "s1":
                        parts.append(
                            jnp.zeros((n_panes, cap_per_shard), jnp.float32)
                            .at[pv, local].add(jnp.where(m, v, 0.0)))
                    elif comp == "s2":
                        parts.append(
                            jnp.zeros((n_panes, cap_per_shard), jnp.float32)
                            .at[pv, local].add(jnp.where(m, v * v, 0.0)))
                    elif comp == "mn":
                        parts.append(
                            jnp.full((n_panes, cap_per_shard), jnp.inf,
                                     jnp.float32)
                            .at[pv, local].min(jnp.where(m, v, jnp.inf)))
                    elif comp == "mx":
                        parts.append(
                            jnp.full((n_panes, cap_per_shard), -jnp.inf,
                                     jnp.float32)
                            .at[pv, local].max(jnp.where(m, v, -jnp.inf)))
                    elif comp == "hll":
                        from ..ops.sketches import hll_parts

                        reg, rho = hll_parts(v)
                        parts.append(
                            jnp.full((n_panes, cap_per_shard, arr.shape[-1]),
                                     -jnp.inf, jnp.float32)
                            .at[pv, local, reg].max(jnp.where(m, rho, 0.0)))
                    elif comp == "hist":
                        from ..ops.sketches import hist_bin

                        b = hist_bin(v)
                        parts.append(
                            jnp.zeros((n_panes, cap_per_shard, arr.shape[-1]),
                                      jnp.float32)
                            .at[pv, local, b].add(mf))
                stacked = jnp.stack(parts, axis=2)  # (P, cap, k[, R])
                if comp in ("n", "s1", "s2", "hist"):
                    out[comp] = arr + jax.lax.psum(stacked, "rows")
                elif comp == "mn":
                    out[comp] = jnp.minimum(
                        arr, jax.lax.pmin(stacked, "rows"))
                else:  # mx, hll merge by max (-inf fill is identity)
                    out[comp] = jnp.maximum(
                        arr, jax.lax.pmax(stacked, "rows"))
            return out

        state_specs = {
            comp: P(None, "keys", None, None) if comp in WIDE_COMPONENTS
            else P(None, "keys", None)
            for comp in comp_specs
        }
        state_specs["act"] = P(None, "keys")
        if self.track_touch:
            state_specs["touch"] = P("keys")
        cols_specs: Dict[str, Any] = {}
        for name in plan.columns:
            cols_specs[name] = P("rows")
            cols_specs["__valid_" + name] = P("rows")

        def step(state, cols, slots, row_valid, pane_vec):
            return shard_map(
                local_fold,
                mesh=self.mesh,
                in_specs=(state_specs, cols_specs, P("rows"), P("rows"),
                          P("rows")),
                out_specs=state_specs,
            )(state, cols, slots, row_valid, pane_vec)

        from ..runtime.aotcache import aot_jit

        return aot_jit(step, op=self._watch_op("fold_step_vec"),
                           donate_argnums=(0,))

    def fold(
        self,
        state: Dict[str, Any],
        cols: Dict[str, np.ndarray],
        slots: np.ndarray,
        valid: Optional[Dict[str, np.ndarray]] = None,
        pane_idx: int = 0,
        n_rows: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Host entry: chunk/pad to the static micro_batch, upload with
        row shardings, run the SPMD step. Signature matches DeviceGroupBy
        so FusedWindowAggNode drives either interchangeably (n_rows is the
        pre-padded-inputs convention — the mesh-aware ingest prep hands
        columns/slots already padded AND placed with this kernel's row
        sharding, single-chunk by contract; host arrays re-pad here)."""
        import jax
        import jax.numpy as jnp

        from ..ops.aggspec import materialize_hll_columns

        n = n_rows if n_rows is not None else len(slots)
        mb = self.micro_batch
        valid = valid or {}
        cols = materialize_hll_columns(self.plan.columns, cols, n)
        if isinstance(slots, np.ndarray):
            # per-shard row accounting (kuiper_shard_rows_total) off the
            # host slot vector; the prep path's device slots are counted
            # by the driving node (it still holds the host vector)
            self.note_rows(slots, n)
        pane_vec = pane_idx if isinstance(pane_idx, np.ndarray) else None
        if pane_vec is not None and self._fold_vec is None:
            self._fold_vec = self._build_fold_vec()
        pane = None if pane_vec is not None else self._put(
            jnp.asarray(pane_idx, dtype=jnp.int32), self.scalar_sharding
        )
        # pre-padded device inputs (runtime/ingest.py pad_*_for_device
        # with this kernel's shardings): single-chunk by contract — use
        # them as-is, fill absent masks with the cached all-true buffer
        has_dev = isinstance(slots, jax.Array) or any(
            isinstance(cols.get(name), jax.Array)
            for name in self.plan.columns)
        if has_dev:
            assert n <= mb, "pre-uploaded device inputs must be one chunk"
            if n <= 0:
                return state
            dev_cols = {}
            for name in self.plan.columns:
                c = cols[name]
                if isinstance(c, jax.Array):
                    dev_cols[name] = c
                else:
                    arr = np.asarray(c[:n], dtype=np.float32)
                    if n < mb:
                        arr = np.pad(arr, (0, mb - n))
                    dev_cols[name] = self._put(arr, self.batch_sharding)
                vm = valid.get(name)
                if isinstance(vm, jax.Array):
                    dev_cols["__valid_" + name] = vm
                elif vm is not None:
                    m = np.asarray(vm[:n], dtype=np.bool_)
                    if n < mb:
                        m = np.pad(m, (0, mb - n))
                    dev_cols["__valid_" + name] = self._put(
                        m, self.batch_sharding)
                else:
                    if self._all_true is None:
                        self._all_true = self._put(
                            np.ones(mb, dtype=np.bool_),
                            self.batch_sharding)
                    dev_cols["__valid_" + name] = self._all_true
            if isinstance(slots, jax.Array):
                s_dev = slots
            else:
                s = np.asarray(slots[:n], dtype=np.int32)
                if n < mb:
                    s = np.pad(s, (0, mb - n))
                s_dev = self._put(s, self.batch_sharding)
            rv = np.zeros(mb, dtype=np.bool_)
            rv[:n] = True
            rv_dev = self._put(rv, self.batch_sharding)
            if pane_vec is not None:
                pv = np.asarray(pane_vec[:n], dtype=np.int32)
                if n < mb:
                    pv = np.pad(pv, (0, mb - n))
                return self._fold_vec(
                    state, dev_cols, s_dev, rv_dev,
                    self._put(pv, self.batch_sharding))
            return self._fold(state, dev_cols, s_dev, rv_dev, pane)
        for start in range(0, max(n, 1), mb):
            end = min(start + mb, n)
            cnt = end - start
            if cnt <= 0:
                break
            pad = mb - cnt
            dev_cols = {}
            for name in self.plan.columns:
                arr = np.asarray(cols[name][start:end], dtype=np.float32)
                if pad:
                    arr = np.pad(arr, (0, pad))
                dev_cols[name] = self._put(arr, self.batch_sharding)
                # masks are always materialized (all-true when absent) so the
                # shard_map pytree structure is static across batches; the
                # all-true mask is one cached device buffer, not a per-batch
                # host allocation + upload
                vmask = valid.get(name)
                if vmask is not None:
                    vm = np.asarray(vmask[start:end], dtype=np.bool_)
                    if pad:
                        vm = np.pad(vm, (0, pad))
                    dev_cols["__valid_" + name] = self._put(
                        vm, self.batch_sharding
                    )
                else:
                    if self._all_true is None:
                        self._all_true = self._put(
                            np.ones(mb, dtype=np.bool_), self.batch_sharding
                        )
                    dev_cols["__valid_" + name] = self._all_true
            s = np.asarray(slots[start:end], dtype=np.int32)
            if pad:
                s = np.pad(s, (0, pad))
            rv = np.zeros(mb, dtype=np.bool_)
            rv[:cnt] = True
            if pane_vec is not None:
                pv = np.asarray(pane_vec[start:end], dtype=np.int32)
                if pad:
                    pv = np.pad(pv, (0, pad))  # padded rows masked by rv
                state = self._fold_vec(
                    state,
                    dev_cols,
                    self._put(s, self.batch_sharding),
                    self._put(rv, self.batch_sharding),
                    self._put(pv, self.batch_sharding),
                )
            else:
                state = self._fold(
                    state,
                    dev_cols,
                    self._put(s, self.batch_sharding),
                    self._put(rv, self.batch_sharding),
                    pane,
                )
        return state

    # finalize / reset_pane / state_to_host / observe_dtypes inherited from
    # DeviceGroupBy: they are plain jit over the (sharded) state arrays, so
    # the whole finalize (pane merge + final values) runs LOCAL per shard —
    # XLA keeps the capacity axis sharded end-to-end and the only cross-
    # shard movement is the host-side assembly of the per-shard result
    # slices at the final np.asarray device->host transfer (the "host-side
    # merge at window boundaries" of docs/DISTRIBUTED.md).

    # ------------------------------------------------------- observability
    def note_rows(self, slots: np.ndarray, n: Optional[int] = None,
                  n_keys: Optional[int] = None) -> None:
        """Accrue per-shard fold rows off a HOST slot vector (the shard of
        slot s is s // (capacity/K)). One bincount per batch — the
        kuiper_shard_rows_total source. `n_keys` refreshes the occupancy
        hint (the driving node's KeyTable count)."""
        if n is not None:
            slots = slots[:n]
        if n_keys is not None:
            self.n_keys_hint = int(n_keys)
        if len(slots) == 0:
            return
        K = self.n_keys_shards
        cap_per_shard = max(self.capacity // K, 1)
        shard = np.minimum(
            np.asarray(slots, dtype=np.int64) // cap_per_shard, K - 1)
        self.shard_rows += np.bincount(shard, minlength=K)[:K]

    def shard_stats(self, state: Optional[Dict[str, Any]] = None
                    ) -> List[Dict[str, Any]]:
        """Per-shard view for metrics/diagnostics/bench: rows folded into
        each shard's key range, key slots it owns (from the occupancy
        hint), and its share of the state bytes. Pure host math — never
        syncs the device."""
        K = self.n_keys_shards
        cap_per_shard = max(self.capacity // K, 1)
        state_bytes = 0
        if state is not None:
            state_bytes = sum(int(getattr(a, "nbytes", 0) or 0)
                              for a in state.values())
        out = []
        for i in range(K):
            keys = min(max(self.n_keys_hint - i * cap_per_shard, 0),
                       cap_per_shard)
            out.append({
                "shard": i,
                "rows": int(self.shard_rows[i]),
                "keys": int(keys),
                "slots": cap_per_shard,
                "state_bytes": state_bytes // K,
            })
        return out

    def collective_bytes_per_fold(self) -> int:
        """Estimated cross-chip bytes ONE fold step moves per chip: the
        psum/pmin/pmax merge over the "rows" axis reduces each chip's
        (n_panes, capacity/K, k) component partials, which a ring
        all-reduce ships as ~2*(R-1)/R of the slice bytes. R == 1 meshes
        fold with no collective at all (key-sharded state is chip-local),
        so the estimate is exactly 0 there. Wide sketch components carry
        their trailing dim. Host math only — meshwatch's
        collective-vs-compute split divides this by the ICI bandwidth
        class to price kernwatch's sampled device time."""
        R = self.n_row_shards
        if R <= 1:
            return 0
        from ..ops.groupby import _wide_size

        K = max(self.n_keys_shards, 1)
        cap_per_shard = max(self.capacity // K, 1)
        elems = self.n_panes * cap_per_shard  # the "act" activity mask
        for comp, spec_idxs in self.comp_specs.items():
            w = _wide_size(comp) if comp in WIDE_COMPONENTS else 1
            elems += self.n_panes * cap_per_shard * len(spec_idxs) * w
        return int(2 * (R - 1) / R * elems * 4)  # float32 partials


# ----------------------------------------------------------- shard registry
# weakref index of live sharded kernels for the kuiper_shard_* families
# (utils/weakreg.py — THE shared ownership model, also tierstore's)
import threading as _threading

from ..utils.weakreg import WeakRegistry as _Registry

_registry = _Registry()

# rows rolled up from collected kernels, keyed (rule, shard). The
# generation counter guards against finalizers from a previous test
# epoch landing after reset() — a late GC must not resurrect counts.
_retired_lock = _threading.Lock()
_retired_rows: Dict[Tuple[str, int], int] = {}
_gen = [0]


def _note_retired(gen: int, rule: Optional[str], shard_rows) -> None:
    """weakref.finalize callback — fold a dead kernel's shard rows into
    the module rollup (GC thread; keep it lock-tight and exception-free)."""
    with _retired_lock:
        if gen != _gen[0]:
            return
        label = rule or "__engine__"
        for i, n in enumerate(shard_rows):
            if n:
                key = (label, i)
                _retired_rows[key] = _retired_rows.get(key, 0) + int(n)


def retired_rows() -> Dict[Tuple[str, int], int]:
    """Snapshot of the retired-kernel rollup ((rule, shard) -> rows)."""
    with _retired_lock:
        return dict(_retired_rows)


def registry() -> _Registry:
    return _registry


def reset() -> None:
    """Test hook."""
    _registry.clear()
    with _retired_lock:
        _gen[0] += 1
        _retired_rows.clear()


def render_prometheus(out: List[str], esc) -> None:
    """Append the per-shard serving families to a /metrics scrape."""
    fams = (
        ("kuiper_shard_rows_total", "counter",
         "rows folded into each mesh shard's key range",
         lambda st: st["rows"]),
        ("kuiper_shard_keys", "gauge",
         "key slots occupied in each mesh shard's range",
         lambda st: st["keys"]),
    )
    kernels = _registry.items()
    for name, mtype, help_txt, fn in fams:
        out.append(f"# TYPE {name} {mtype}")
        out.append(f"# HELP {name} {help_txt}")
        # aggregate per (rule, shard) label pair: duplicate sample lines
        # would fail the whole Prometheus scrape. The rows counter seeds
        # from the retired-kernel rollup so it never regresses when a
        # restore replaces the kernel.
        agg: Dict[Tuple[str, int], int] = {}
        if name == "kuiper_shard_rows_total":
            agg.update(retired_rows())
        for kernel, rule in kernels:
            label = rule or "__engine__"
            try:
                for st in kernel.shard_stats():
                    key = (label, st["shard"])
                    agg[key] = agg.get(key, 0) + int(fn(st))
            except Exception:
                continue
        for (label, shard), v in sorted(agg.items()):
            out.append(f'{name}{{rule="{esc(label)}",shard="{shard}"}} {v}')


def diagnostics() -> List[Dict[str, Any]]:
    """Per-kernel shard state for GET /diagnostics + kuiperdiag."""
    rows = []
    for kernel, rule in _registry.items():
        rows.append({
            "rule": rule or "__engine__",
            "mesh": kernel.mesh_tag,
            "capacity": int(kernel.capacity),
            "shards": kernel.shard_stats(),
        })
    return rows
