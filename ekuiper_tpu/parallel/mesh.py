"""Device mesh construction — the scale-out substrate.

The reference scales by process-level fan-out (N rules × M goroutines, plugin
worker processes over nanomsg IPC — SURVEY §5); the TPU-native equivalent is
a jax.sharding.Mesh with two logical axes:

- "rows": data parallelism over incoming event batches (the analogue of the
  reference's shared-source fan-out);
- "keys": GROUP BY key-axis sharding — each device owns a contiguous slot
  range of the per-key aggregation state (the analogue obligation SURVEY §5
  names "sequence parallel" for this workload).

Collectives ride ICI: per-batch partial folds merge with psum over "rows";
emits all_gather over "keys" only at window triggers.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def make_mesh(
    rows: int = 1, keys: Optional[int] = None, devices: Optional[Sequence] = None,
):
    """Build a Mesh with axes ("rows", "keys"). Defaults to putting all
    devices on the keys axis (state capacity is usually the scale limit)."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if keys is None:
        keys = n // rows
    if rows * keys != n:
        raise ValueError(f"mesh {rows}x{keys} != {n} devices")
    arr = np.asarray(devs).reshape(rows, keys)
    return Mesh(arr, ("rows", "keys"))
