"""Device mesh construction — the scale-out substrate.

The reference scales by process-level fan-out (N rules × M goroutines, plugin
worker processes over nanomsg IPC — SURVEY §5); the TPU-native equivalent is
a jax.sharding.Mesh with two logical axes:

- "rows": data parallelism over incoming event batches (the analogue of the
  reference's shared-source fan-out);
- "keys": GROUP BY key-axis sharding — each device owns a contiguous slot
  range of the per-key aggregation state (the analogue obligation SURVEY §5
  names "sequence parallel" for this workload).

Collectives ride ICI: per-batch partial folds merge with psum over "rows";
emits all_gather over "keys" only at window triggers.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np


def make_mesh(
    rows: int = 1, keys: Optional[int] = None, devices: Optional[Sequence] = None,
):
    """Build a Mesh with axes ("rows", "keys"). Defaults to putting all
    devices on the keys axis (state capacity is usually the scale limit)."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if keys is None:
        keys = n // rows
    if rows * keys != n:
        raise ValueError(f"mesh {rows}x{keys} != {n} devices")
    arr = np.asarray(devs).reshape(rows, keys)
    return Mesh(arr, ("rows", "keys"))


def ensure_devices(n: int):
    """Return at least n jax devices, provisioning virtual CPU devices when
    the host has fewer physical chips.

    Order of preference: real devices of the default platform; an existing
    CPU backend with >= n devices; a fresh CPU backend forced to n devices
    via the jax_num_cpu_devices config (only possible before the CPU
    backend initializes — tests/conftest.py and the dryrun subprocess set
    JAX_PLATFORMS=cpu + --xla_force_host_platform_device_count up front).

    This function NEVER resets initialized backends: a running rule's
    state lives on those backends, and clearing them invalidates every
    live device array process-wide (it also broke the driver dryrun twice
    — a cleared TPU client re-initialized into a libtpu version mismatch).
    Callers that need an n-device mesh the current process cannot provide
    must run in a fresh subprocess instead (see __graft_entry__.
    dryrun_multichip)."""
    import jax

    if n < 1:
        raise ValueError(f"need a positive device count, got {n}")
    devs = jax.devices()
    if len(devs) >= n:
        return devs[:n]
    try:
        cpus = jax.devices("cpu")
        if len(cpus) >= n:
            return cpus[:n]
    except RuntimeError:
        pass
    # the probes above initialized the backends, so the CPU device count is
    # locked in for this process — more devices can only come from a fresh
    # process configured up front
    raise RuntimeError(
        f"host has {len(devs)} devices and the jax backend is already "
        f"initialized; cannot provision {n} virtual CPU devices in-process "
        f"— run in a subprocess with JAX_PLATFORMS=cpu and "
        f"--xla_force_host_platform_device_count={n}"
    )


def mesh_cfg_from_env() -> Optional[Dict[str, Any]]:
    """Parse the deployment-wide KUIPER_MESH env into a mesh config dict:
    "RxK" (rows x keys), a bare shard count K (keys axis), or "auto"
    (all local devices on the keys axis, resolved at mesh-build time).
    Unset / "0" / "off" / "none" -> None. Parse errors return None with
    nothing raised — a malformed env var must not take rule planning
    down; the planner logs the single-chip fallback it causes."""
    raw = os.environ.get("KUIPER_MESH", "").strip().lower()
    if not raw or raw in ("0", "off", "none", "1"):
        return None
    if raw == "auto":
        return {"auto": True}
    try:
        if "x" in raw:
            rows_s, keys_s = raw.split("x", 1)
            rows, keys = int(rows_s), int(keys_s)
        else:
            rows, keys = 1, int(raw)
    except ValueError:
        return None
    if rows < 1 or keys < 1 or rows * keys < 2:
        return None
    return {"rows": rows, "keys": keys}


def resolve_auto_cfg(cfg: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Turn an {"auto": True} config into a concrete {"rows", "keys"}
    using the devices this process can already see (never provisions or
    resets backends). None when the host has fewer than 2 devices —
    auto sharding on a single chip is just the single-chip kernel."""
    if not cfg.get("auto"):
        return cfg
    import jax

    n = len(jax.devices())
    if n < 2:
        return None
    return {"rows": 1, "keys": n}


def mesh_from_options(mesh_cfg: dict):
    """Build a mesh from a rule's planOptimizeStrategy.mesh option, e.g.
    {"rows": 2, "keys": 4}. Uses existing devices only (real chips, or the
    virtual CPU mesh the test/dryrun environment pre-provisions) — planning
    a rule never resets jax backends out from under running rules."""
    rows = int(mesh_cfg.get("rows", 1))
    keys = int(mesh_cfg.get("keys", 1))
    if rows < 1 or keys < 1:
        raise ValueError(f"mesh axes must be positive, got {rows}x{keys}")
    devices = ensure_devices(rows * keys)
    return make_mesh(rows=rows, keys=keys, devices=devices)
