"""KV storage — analogue of eKuiper's internal/pkg/store (sqlite default,
memory for tests; reference: internal/pkg/store/, pkg/kv).

Namespaced key→value tables (JSON-serialized values) over sqlite or an
in-memory dict. Used for stream/table/rule definitions, rule state/checkpoints,
keyed state and schema registry — same division of labor as the reference.
"""
from __future__ import annotations

import json
import os
import sqlite3
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple


class KV:
    """One namespace (table) of the store."""

    def set(self, key: str, value: Any) -> None:
        raise NotImplementedError

    def setnx(self, key: str, value: Any) -> bool:
        raise NotImplementedError

    def get_ok(self, key: str) -> Tuple[Any, bool]:
        """(value, found) — mirrors the reference kv.Get so a stored null is
        distinguishable from an absent key."""
        raise NotImplementedError

    def get(self, key: str) -> Optional[Any]:
        v, _ = self.get_ok(key)
        return v

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def keys(self) -> List[str]:
        raise NotImplementedError

    def items(self) -> Iterator[Tuple[str, Any]]:
        for k in self.keys():
            v, ok = self.get_ok(k)
            if ok:
                yield k, v

    def clean(self) -> None:
        for k in self.keys():
            self.delete(k)


class MemoryKV(KV):
    def __init__(self) -> None:
        self._data: Dict[str, str] = {}
        self._lock = threading.RLock()

    def set(self, key: str, value: Any) -> None:
        with self._lock:
            self._data[key] = json.dumps(value)

    def setnx(self, key: str, value: Any) -> bool:
        with self._lock:
            if key in self._data:
                return False
            self._data[key] = json.dumps(value)
            return True

    def get_ok(self, key: str) -> Tuple[Any, bool]:
        with self._lock:
            if key not in self._data:
                return None, False
            return json.loads(self._data[key]), True

    def delete(self, key: str) -> bool:
        with self._lock:
            if key not in self._data:
                return False
            del self._data[key]
            return True

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._data.keys())


class SqliteKV(KV):
    def __init__(self, conn: sqlite3.Connection, lock: threading.RLock, table: str) -> None:
        self._conn = conn
        self._lock = lock
        # namespace strings may start with digits or contain punctuation
        # (rule ids appear in checkpoint namespaces) — sanitize AND prefix so
        # the identifier is always valid unquoted SQL
        self._table = "ns_" + "".join(
            c if c.isalnum() or c == "_" else "_" for c in table
        )
        with self._lock:
            self._conn.execute(
                f"CREATE TABLE IF NOT EXISTS {self._table} (k TEXT PRIMARY KEY, v TEXT)"
            )
            self._conn.commit()

    def set(self, key: str, value: Any) -> None:
        with self._lock:
            self._conn.execute(
                f"INSERT OR REPLACE INTO {self._table} (k, v) VALUES (?, ?)",
                (key, json.dumps(value)),
            )
            self._conn.commit()

    def setnx(self, key: str, value: Any) -> bool:
        with self._lock:
            cur = self._conn.execute(
                f"INSERT OR IGNORE INTO {self._table} (k, v) VALUES (?, ?)",
                (key, json.dumps(value)),
            )
            self._conn.commit()
            return cur.rowcount > 0

    def get_ok(self, key: str) -> Tuple[Any, bool]:
        with self._lock:
            cur = self._conn.execute(
                f"SELECT v FROM {self._table} WHERE k = ?", (key,)
            )
            row = cur.fetchone()
            return (None, False) if row is None else (json.loads(row[0]), True)

    def delete(self, key: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                f"DELETE FROM {self._table} WHERE k = ?", (key,)
            )
            self._conn.commit()
            return cur.rowcount > 0

    def keys(self) -> List[str]:
        with self._lock:
            cur = self._conn.execute(f"SELECT k FROM {self._table}")
            return [r[0] for r in cur.fetchall()]


class RedisKV(KV):
    """Redis-backed namespace (analogue of the reference's redis storage
    backend, internal/pkg/store/redis) — one redis hash per namespace,
    values json-encoded, over the engine's own RESP client."""

    def __init__(self, client, namespace: str) -> None:
        self._cli = client
        self._ns = f"ekuiper:{namespace}"

    def set(self, key: str, value: Any) -> None:
        self._cli.command("HSET", self._ns, key, json.dumps(value))

    def setnx(self, key: str, value: Any) -> bool:
        return bool(self._cli.command(
            "HSETNX", self._ns, key, json.dumps(value)))

    def get_ok(self, key: str) -> Tuple[Any, bool]:
        raw = self._cli.command("HGET", self._ns, key)
        if raw is None:
            return None, False
        return json.loads(raw), True

    def delete(self, key: str) -> bool:
        return bool(self._cli.command("HDEL", self._ns, key))

    def keys(self) -> List[str]:
        raw = self._cli.command("HKEYS", self._ns) or []
        return sorted(k.decode() if isinstance(k, bytes) else k for k in raw)

    def items(self):
        # one HGETALL round trip instead of HKEYS + N HGETs
        raw = self._cli.command("HGETALL", self._ns) or []
        it = iter(raw)
        for k, v in zip(it, it):
            yield (k.decode() if isinstance(k, bytes) else k, json.loads(v))

    def clean(self) -> None:
        self._cli.command("DEL", self._ns)


class Store:
    """Store root: hands out namespaced KV tables
    (analogue of store.SetupWithConfig, internal/server/server.go:183)."""

    def __init__(self, kind: str = "memory", path: str = "data") -> None:
        self.kind = kind
        self._lock = threading.RLock()
        self._namespaces: Dict[str, KV] = {}
        self._conn: Optional[sqlite3.Connection] = None
        self._redis = None
        if kind == "sqlite":
            os.makedirs(path, exist_ok=True)
            self._conn = sqlite3.connect(
                os.path.join(path, "ekuiper_tpu.db"), check_same_thread=False
            )
        elif kind == "redis":
            # path = "host:port[/db]" (reference redis storage backend)
            from ..io.redis_io import RespClient

            addr, _, db = path.partition("/")
            host, _, port = addr.partition(":")
            self._redis = RespClient(host or "127.0.0.1",
                                     int(port or 6379), db=int(db or 0))
            self._redis.connect()
        elif kind != "memory":
            raise ValueError(
                f"unknown store kind {kind!r} (want sqlite|memory|redis)")

    def kv(self, namespace: str) -> KV:
        with self._lock:
            kv = self._namespaces.get(namespace)
            if kv is None:
                if self._conn is not None:
                    kv = SqliteKV(self._conn, self._lock, namespace)
                elif self._redis is not None:
                    kv = RedisKV(self._redis, namespace)
                else:
                    kv = MemoryKV()
                self._namespaces[namespace] = kv
            return kv

    def drop(self, namespace: str) -> None:
        with self._lock:
            # materialize first so sqlite-persisted data from a previous
            # process is actually deleted, not just the in-memory handle
            kv = self._namespaces.pop(namespace, None) or self.kv(namespace)
            self._namespaces.pop(namespace, None)
            kv.clean()

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None
            if self._redis is not None:
                self._redis.close()
                self._redis = None


_store: Optional[Store] = None
_store_lock = threading.Lock()


def setup(kind: str = "memory", path: str = "data") -> Store:
    global _store
    with _store_lock:
        _store = Store(kind, path)
        return _store


def get_store() -> Store:
    global _store
    with _store_lock:
        if _store is None:
            _store = Store("memory")
        return _store
