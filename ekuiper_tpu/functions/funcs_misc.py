"""Misc functions — analogue of internal/binder/function/funcs_misc.go (37 funcs):
hashing, casts, json path, uuid, metadata, window info, keyed state.
"""
from __future__ import annotations

import binascii
import hashlib
import json
import uuid
from typing import Any, List

from ..data import cast
from ..utils import timex
from .registry import SCALAR, register


@register("bypass", SCALAR)
def f_bypass(args, ctx):
    return args[0] if args else None


@register("props", SCALAR)
def f_props(args, ctx):
    return None  # rule properties lookup; populated via ctx in runtime


@register("cast", SCALAR)
def f_cast(args, ctx):
    """cast(value, "bigint"|"float"|"string"|"boolean"|"bytea"|"datetime")"""
    v, t = args[0], cast.to_string(args[1]).lower()
    if v is None:
        return None
    if t == "bigint":
        return cast.to_int(v)
    if t == "float":
        return cast.to_float(v)
    if t == "string":
        return cast.to_string(v)
    if t == "boolean":
        return cast.to_bool(v)
    if t == "bytea":
        return cast.to_bytes(v)
    if t == "datetime":
        return cast.to_datetime_ms(v)
    raise ValueError(f"unknown cast target type {t}")


@register("convert_tz", SCALAR)
def f_convert_tz(args, ctx):
    import datetime as _dt
    import zoneinfo

    if args[0] is None:
        return None
    ms = cast.to_datetime_ms(args[0])
    tz = zoneinfo.ZoneInfo(cast.to_string(args[1]))
    d = _dt.datetime.fromtimestamp(ms / 1000.0, tz=tz)
    # return wall-clock in target zone as epoch-ms-like naive value
    naive = d.replace(tzinfo=_dt.timezone.utc)
    return int(naive.timestamp() * 1000)


@register("to_seconds", SCALAR)
def f_to_seconds(args, ctx):
    return None if args[0] is None else cast.to_datetime_ms(args[0]) // 1000


@register("to_json", SCALAR)
def f_to_json(args, ctx):
    return json.dumps(args[0])


@register("parse_json", SCALAR)
def f_parse_json(args, ctx):
    if args[0] is None or args[0] == "null":
        return None
    return json.loads(cast.to_string(args[0]))


@register("chr", SCALAR)
def f_chr(args, ctx):
    v = args[0]
    if v is None:
        return None
    if isinstance(v, str):
        return v[0] if v else None
    return chr(cast.to_int(v))


@register("encode", SCALAR)
def f_encode(args, ctx):
    import base64

    if cast.to_string(args[1]).lower() != "base64":
        raise ValueError("encode only supports base64")
    v = args[0]
    data = v if isinstance(v, bytes) else cast.to_string(v).encode()
    return base64.b64encode(data).decode()


@register("decode", SCALAR)
def f_decode(args, ctx):
    import base64

    if cast.to_string(args[1]).lower() != "base64":
        raise ValueError("decode only supports base64")
    return base64.b64decode(cast.to_string(args[0]))


@register("trunc", SCALAR)
def f_trunc(args, ctx):
    if args[0] is None:
        return None
    d = cast.to_int(args[1])
    f = cast.to_float(args[0])
    scale = 10 ** d
    return int(f * scale) / scale


def _hash(name: str, algo):
    @register(name, SCALAR)
    def f(args, ctx):
        if args[0] is None:
            return None
        return algo(cast.to_string(args[0]).encode()).hexdigest()

    return f


_hash("md5", hashlib.md5)
_hash("sha1", hashlib.sha1)
_hash("sha256", hashlib.sha256)
_hash("sha384", hashlib.sha384)
_hash("sha512", hashlib.sha512)


@register("crc32", SCALAR)
def f_crc32(args, ctx):
    if args[0] is None:
        return None
    return binascii.crc32(cast.to_string(args[0]).encode()) & 0xFFFFFFFF


@register("isnull", SCALAR)
def f_isnull(args, ctx):
    return args[0] is None


@register("coalesce", SCALAR)
def f_coalesce(args, ctx):
    for a in args:
        if a is not None:
            return a
    return None


@register("newuuid", SCALAR)
def f_newuuid(args, ctx):
    return str(uuid.uuid4())


@register("tstamp", SCALAR)
def f_tstamp(args, ctx):
    return timex.now_ms()


@register("rule_id", SCALAR)
def f_rule_id(args, ctx):
    return ctx.rule_id if ctx else ""


@register("rule_start", SCALAR)
def f_rule_start(args, ctx):
    return ctx.get_state("__rule_start", 0) if ctx else 0


@register("mqtt", SCALAR)
def f_mqtt(args, ctx):
    """mqtt(topic|messageid) — metadata of the mqtt source message."""
    if ctx is None or ctx.row is None:
        return None
    key = args[0] if isinstance(args[0], str) else cast.to_string(args[0])
    meta = getattr(ctx.row, "metadata", None)
    return None if meta is None else meta.get(key)


@register("meta", SCALAR)
def f_meta(args, ctx):
    if ctx is None or ctx.row is None:
        return None
    key = cast.to_string(args[0])
    meta = getattr(ctx.row, "metadata", None)
    if meta is None:
        return None
    # dotted path into metadata
    cur: Any = meta
    for part in key.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return None
    return cur


@register("cardinality", SCALAR)
def f_cardinality(args, ctx):
    v = args[0]
    if v is None:
        return 0
    if isinstance(v, (list, tuple, dict)):
        return len(v)
    raise ValueError("cardinality expects array or object")


# ------------------------------------------------------------------ json path
def json_path_eval(data: Any, path: str) -> List[Any]:
    """Minimal eKuiper-compatible json path: $.a.b[0], [*], bare names."""
    if path.startswith("$"):
        path = path[1:]
    cur: List[Any] = [data]
    token = ""
    i = 0
    tokens: List[Any] = []
    while i < len(path):
        c = path[i]
        if c == ".":
            if token:
                tokens.append(token)
                token = ""
            i += 1
        elif c == "[":
            if token:
                tokens.append(token)
                token = ""
            j = path.find("]", i)
            if j < 0:
                raise ValueError(f"bad json path {path}")
            inner = path[i + 1:j].strip()
            if inner == "*":
                tokens.append(("*",))
            elif inner.startswith('"') or inner.startswith("'"):
                tokens.append(inner[1:-1])
            else:
                tokens.append(("idx", int(inner)))
            i = j + 1
        else:
            token += c
            i += 1
    if token:
        tokens.append(token)
    for t in tokens:
        nxt: List[Any] = []
        for item in cur:
            if isinstance(t, str):
                if isinstance(item, dict) and t in item:
                    nxt.append(item[t])
            elif t[0] == "*":
                if isinstance(item, (list, tuple)):
                    nxt.extend(item)
                elif isinstance(item, dict):
                    nxt.extend(item.values())
            elif t[0] == "idx":
                if isinstance(item, (list, tuple)) and -len(item) <= t[1] < len(item):
                    nxt.append(item[t[1]])
        cur = nxt
    return cur


@register("json_path_query", SCALAR)
def f_json_path_query(args, ctx):
    if args[0] is None:
        return None
    return json_path_eval(args[0], cast.to_string(args[1]))


@register("json_path_query_first", SCALAR)
def f_json_path_query_first(args, ctx):
    if args[0] is None:
        return None
    out = json_path_eval(args[0], cast.to_string(args[1]))
    return out[0] if out else None


@register("json_path_exists", SCALAR)
def f_json_path_exists(args, ctx):
    if args[0] is None:
        return False
    try:
        return len(json_path_eval(args[0], cast.to_string(args[1]))) > 0
    except ValueError:
        return False


# ------------------------------------------------------------ window info
@register("window_start", SCALAR)
def f_window_start(args, ctx):
    return ctx.window_range.window_start if ctx and ctx.window_range else 0


@register("window_end", SCALAR)
def f_window_end(args, ctx):
    return ctx.window_range.window_end if ctx and ctx.window_range else 0


@register("window_trigger", SCALAR)
def f_window_trigger(args, ctx):
    return ctx.trigger_time if ctx else 0


@register("event_time", SCALAR)
def f_event_time(args, ctx):
    if ctx and ctx.row is not None:
        return getattr(ctx.row, "timestamp", 0)
    return 0


@register("delay", SCALAR)
def f_delay(args, ctx):
    """delay(ms, value) — sleeps then returns value (reference parity)."""
    timex.sleep(cast.to_int(args[0]))
    return args[1]


@register("get_keyed_state", SCALAR)
def f_get_keyed_state(args, ctx):
    """get_keyed_state(key, type, default) — global cross-rule state
    (reference: internal/keyedstate/kv.go:28-36)."""
    if ctx is None or ctx.keyed_state is None:
        return args[2] if len(args) > 2 else None
    v, ok = ctx.keyed_state.get_ok(cast.to_string(args[0]))
    return v if ok else (args[2] if len(args) > 2 else None)


@register("hex2dec", SCALAR)
def f_hex2dec(args, ctx):
    if args[0] is None:
        return None
    s = cast.to_string(args[0])
    return int(s, 16)


@register("dec2hex", SCALAR)
def f_dec2hex(args, ctx):
    if args[0] is None:
        return None
    return hex(cast.to_int(args[0]))
