"""Datetime functions — analogue of internal/binder/function/funcs_datetime.go
(25 registrations). All times are epoch-ms internally (the engine-wide
representation); formatting uses Go-style reference layouts translated to
strftime where needed, but the common format tokens (YYYY-MM-dd etc.) follow
the reference's java-style patterns.
"""
from __future__ import annotations

import datetime as _dt
from typing import Any, Optional

from ..data import cast
from ..utils import timex
from .registry import SCALAR, register

_EPOCH = _dt.timezone.utc


def _dt_of(v: Any) -> _dt.datetime:
    ms = cast.to_datetime_ms(v)
    return _dt.datetime.fromtimestamp(ms / 1000.0, tz=_EPOCH)


# longest-match-first single-pass scan: sequential str.replace would corrupt
# earlier outputs (e.g. 'a'->'%p' rewriting the '%a' emitted for EEE)
_JAVA_TOKENS = [
    ("YYYY", "%Y"), ("yyyy", "%Y"), ("MMMM", "%B"), ("EEEE", "%A"),
    ("SSS", "%f"), ("MMM", "%b"), ("EEE", "%a"),
    ("YY", "%y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"), ("DD", "%d"),
    ("HH", "%H"), ("hh", "%I"), ("mm", "%M"), ("ss", "%S"), ("zz", "%Z"),
    ("a", "%p"), ("Z", "%z"),
]


def java_to_strftime(fmt: str) -> str:
    out = []
    i = 0
    while i < len(fmt):
        for token, repl in _JAVA_TOKENS:
            if fmt.startswith(token, i):
                out.append(repl)
                i += len(token)
                break
        else:
            c = fmt[i]
            out.append("%%" if c == "%" else c)
            i += 1
    return "".join(out)


def _now_ms() -> int:
    return timex.now_ms()


def _reg_now(name: str):
    @register(name, SCALAR)
    def f_now(args, ctx):
        # now(fsp)/current_timestamp return datetime; engine keeps epoch ms
        return _now_ms()

    return f_now


for _n in ("now", "current_timestamp", "local_time", "local_timestamp"):
    _reg_now(_n)


@register("cur_date", SCALAR)
def f_cur_date(args, ctx):
    d = _dt.datetime.fromtimestamp(_now_ms() / 1000.0, tz=_EPOCH)
    midnight = d.replace(hour=0, minute=0, second=0, microsecond=0)
    return int(midnight.timestamp() * 1000)


register("current_date", SCALAR)(f_cur_date)


@register("cur_time", SCALAR)
def f_cur_time(args, ctx):
    d = _dt.datetime.fromtimestamp(_now_ms() / 1000.0, tz=_EPOCH)
    return d.strftime("%H:%M:%S")


register("current_time", SCALAR)(f_cur_time)


@register("format_time", SCALAR)
def f_format_time(args, ctx):
    if args[0] is None:
        return None
    d = _dt_of(args[0])
    return d.strftime(java_to_strftime(cast.to_string(args[1])))


@register("date_calc", SCALAR)
def f_date_calc(args, ctx):
    """date_calc(date, duration_str) — duration like "1h", "-30m", "24h"."""
    if args[0] is None or args[1] is None:
        return None
    ms = cast.to_datetime_ms(args[0])
    return ms + _parse_duration_ms(cast.to_string(args[1]))


def _parse_duration_ms(s: str) -> int:
    units = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000, "d": 86_400_000}
    s = s.strip()
    sign = 1
    if s.startswith("-"):
        sign, s = -1, s[1:]
    total = 0
    num = ""
    i = 0
    while i < len(s):
        c = s[i]
        if c.isdigit() or c == ".":
            num += c
            i += 1
        else:
            u = s[i:i + 2] if s[i:i + 2] == "ms" else c
            i += len(u)
            if u not in units:
                raise ValueError(f"unknown duration unit {u!r} in {s!r}")
            total += float(num) * units[u]
            num = ""
    return sign * int(total)


@register("date_diff", SCALAR)
def f_date_diff(args, ctx):
    if args[0] is None or args[1] is None:
        return None
    return cast.to_datetime_ms(args[1]) - cast.to_datetime_ms(args[0])


@register("day_name", SCALAR)
def f_day_name(args, ctx):
    return None if args[0] is None else _dt_of(args[0]).strftime("%A")


@register("day_of_month", SCALAR)
def f_day_of_month(args, ctx):
    return None if args[0] is None else _dt_of(args[0]).day


register("day", SCALAR)(f_day_of_month)


@register("day_of_week", SCALAR)
def f_day_of_week(args, ctx):
    # reference: Sunday=1 .. Saturday=7
    return None if args[0] is None else (_dt_of(args[0]).weekday() + 1) % 7 + 1


@register("day_of_year", SCALAR)
def f_day_of_year(args, ctx):
    return None if args[0] is None else _dt_of(args[0]).timetuple().tm_yday


@register("from_days", SCALAR)
def f_from_days(args, ctx):
    if args[0] is None:
        return None
    days = cast.to_int(args[0])
    return days * 86_400_000


@register("from_unix_time", SCALAR)
def f_from_unix_time(args, ctx):
    return None if args[0] is None else cast.to_int(args[0]) * 1000


@register("hour", SCALAR)
def f_hour(args, ctx):
    return None if args[0] is None else _dt_of(args[0]).hour


@register("minute", SCALAR)
def f_minute(args, ctx):
    return None if args[0] is None else _dt_of(args[0]).minute


@register("second", SCALAR)
def f_second(args, ctx):
    return None if args[0] is None else _dt_of(args[0]).second


@register("microsecond", SCALAR)
def f_microsecond(args, ctx):
    return None if args[0] is None else _dt_of(args[0]).microsecond


@register("month", SCALAR)
def f_month(args, ctx):
    return None if args[0] is None else _dt_of(args[0]).month


@register("month_name", SCALAR)
def f_month_name(args, ctx):
    return None if args[0] is None else _dt_of(args[0]).strftime("%B")


@register("last_day", SCALAR)
def f_last_day(args, ctx):
    if args[0] is None:
        return None
    d = _dt_of(args[0])
    nxt = (d.replace(day=28) + _dt.timedelta(days=4)).replace(day=1)
    last = nxt - _dt.timedelta(days=1)
    return int(last.replace(hour=0, minute=0, second=0, microsecond=0).timestamp() * 1000)


@register("year", SCALAR)
def f_year(args, ctx):
    return None if args[0] is None else _dt_of(args[0]).year
