"""Incremental aggregates — analogue of internal/binder/function/funcs_inc_agg.go:43-147.

These are the streaming-partial forms the planner's incremental-agg rewrite
targets (reference: planner.go:910-999) and the exact semantics the TPU
group-by kernel implements natively: per-key device partials folded per
micro-batch, finalized at window trigger. Each registers an Accumulator
(init/step/merge/result); `merge` is the cross-shard combine used when the
key axis is sharded over a mesh (psum-style tree merge).

The row-path exec folds one value into ctx.state — used by the host fallback
WindowIncAggOperator for types the device kernel doesn't handle (strings,
objects).
"""
from __future__ import annotations

from typing import Any

from ..data import cast
from .registry import AGGREGATE, Accumulator, FunctionDef, register_def


def _mk(name: str, acc: Accumulator) -> None:
    def exec_fold(args, ctx):
        state = ctx.get_state("acc")
        if state is None:
            state = acc.init()
        # via the aggregate evaluator path args[0] is the group's value list;
        # via the IncAgg operator it is a single value per call
        values = args[0] if isinstance(args[0], list) else [args[0]]
        for v in values:
            state = acc.step(state, v)
        ctx.put_state("acc", state)
        return acc.result(state)

    register_def(
        FunctionDef(name=name, ftype=AGGREGATE, exec=exec_fold, stateful=True, acc=acc)
    )


def _num(v: Any) -> float:
    return cast.to_float(v)


# count: state = n
_mk("inc_count", Accumulator(
    init=lambda: 0,
    step=lambda s, v: s + (0 if v is None else 1),
    result=lambda s: s,
    merge=lambda a, b: a + b,
))

# sum: state = (sum, has_any, all_int)
_mk("inc_sum", Accumulator(
    init=lambda: (0, False, True),
    step=lambda s, v: s if v is None else (
        s[0] + (v if isinstance(v, (int, float)) and not isinstance(v, bool) else _num(v)),
        True,
        s[2] and isinstance(v, int) and not isinstance(v, bool),
    ),
    result=lambda s: None if not s[1] else (int(s[0]) if s[2] else float(s[0])),
    merge=lambda a, b: (a[0] + b[0], a[1] or b[1], a[2] and b[2]),
))

# avg: state = (sum, count, all_int)
_mk("inc_avg", Accumulator(
    init=lambda: (0.0, 0, True),
    step=lambda s, v: s if v is None else (
        s[0] + _num(v), s[1] + 1,
        s[2] and isinstance(v, int) and not isinstance(v, bool),
    ),
    result=lambda s: None if s[1] == 0 else (
        int(s[0]) // s[1] if s[2] else s[0] / s[1]
    ),
    merge=lambda a, b: (a[0] + b[0], a[1] + b[1], a[2] and b[2]),
))


def _cmp_step(keep_gt: int):
    def step(s, v):
        if v is None:
            return s
        if s is None or cast.compare(v, s) == keep_gt:
            return v
        return s

    return step


def _cmp_merge(keep_gt: int):
    def merge(a, b):
        if a is None:
            return b
        if b is None:
            return a
        return b if cast.compare(b, a) == keep_gt else a

    return merge


_mk("inc_max", Accumulator(
    init=lambda: None, step=_cmp_step(1), result=lambda s: s, merge=_cmp_merge(1),
))
_mk("inc_min", Accumulator(
    init=lambda: None, step=_cmp_step(-1), result=lambda s: s, merge=_cmp_merge(-1),
))

_mk("inc_collect", Accumulator(
    init=lambda: [],
    step=lambda s, v: s + [v],
    result=lambda s: s,
    merge=lambda a, b: a + b,
))


def _merge_agg_step(s, v):
    if isinstance(v, dict):
        s = dict(s)
        s.update(v)
    return s


_mk("inc_merge_agg", Accumulator(
    init=lambda: {},
    step=_merge_agg_step,
    result=lambda s: s,
    merge=lambda a, b: {**a, **b},
))

# last_value(ignore_null=True semantics for the inc form)
_mk("inc_last_value", Accumulator(
    init=lambda: None,
    step=lambda s, v: v if v is not None else s,
    result=lambda s: s,
    merge=lambda a, b: b if b is not None else a,
))

# Welford-form variance partials: state = (count, sum, sum_sq)
# (numerically fine in f64 host-side; the device kernel uses the same
# (n, s1, s2) triple so shard merges are a simple add)
_mk("inc_stddev", Accumulator(
    init=lambda: (0, 0.0, 0.0),
    step=lambda s, v: s if v is None else (s[0] + 1, s[1] + _num(v), s[2] + _num(v) ** 2),
    result=lambda s: None if s[0] == 0 else max(s[2] / s[0] - (s[1] / s[0]) ** 2, 0.0) ** 0.5,
    merge=lambda a, b: (a[0] + b[0], a[1] + b[1], a[2] + b[2]),
))
_mk("inc_stddevs", Accumulator(
    init=lambda: (0, 0.0, 0.0),
    step=lambda s, v: s if v is None else (s[0] + 1, s[1] + _num(v), s[2] + _num(v) ** 2),
    result=lambda s: None if s[0] < 2 else max(
        (s[2] - s[1] ** 2 / s[0]) / (s[0] - 1), 0.0
    ) ** 0.5,
    merge=lambda a, b: (a[0] + b[0], a[1] + b[1], a[2] + b[2]),
))
