"""Aggregate functions — analogue of internal/binder/function/funcs_agg.go:29-371.

Each aggregate's row-path exec takes (args, ctx) where args[0] is the list of
the group's values for the aggregated expression (None values excluded by the
caller, matching the reference's null handling). The TPU path never calls
these per-group python implementations for the fused kernels — sum/count/avg/
min/max/stddev/var fold into device partials (ops/groupby.py); these remain
for host fallback, small groups, and exotic aggregates.
"""
from __future__ import annotations

import statistics
from typing import Any, List

from ..data import cast
from .registry import AGGREGATE, register


def _nums(values: List[Any]) -> List[float]:
    return [cast.to_float(v) for v in values if v is not None]


@register("avg", AGGREGATE, inc_name="inc_avg")
def f_avg(args, ctx):
    vals = [v for v in args[0] if v is not None]
    if not vals:
        return None
    if all(isinstance(v, int) and not isinstance(v, bool) for v in vals):
        return sum(vals) // len(vals)  # integer avg matches reference semantics
    return sum(cast.to_float(v) for v in vals) / len(vals)


@register("count", AGGREGATE, inc_name="inc_count")
def f_count(args, ctx):
    return sum(1 for v in args[0] if v is not None)


@register("sum", AGGREGATE, inc_name="inc_sum")
def f_sum(args, ctx):
    vals = [v for v in args[0] if v is not None]
    if not vals:
        return None
    if all(isinstance(v, int) and not isinstance(v, bool) for v in vals):
        return sum(vals)
    return sum(cast.to_float(v) for v in vals)


@register("max", AGGREGATE, inc_name="inc_max")
def f_max(args, ctx):
    vals = [v for v in args[0] if v is not None]
    if not vals:
        return None
    best = vals[0]
    for v in vals[1:]:
        if cast.compare(v, best) == 1:
            best = v
    return best


@register("min", AGGREGATE, inc_name="inc_min")
def f_min(args, ctx):
    vals = [v for v in args[0] if v is not None]
    if not vals:
        return None
    best = vals[0]
    for v in vals[1:]:
        if cast.compare(v, best) == -1:
            best = v
    return best


@register("collect", AGGREGATE, inc_name="inc_collect")
def f_collect(args, ctx):
    return list(args[0])


@register("merge_agg", AGGREGATE, inc_name="inc_merge_agg")
def f_merge_agg(args, ctx):
    """Merge all map values of the group into one object (last wins)."""
    out = {}
    for v in args[0]:
        if isinstance(v, dict):
            out.update(v)
    return out


@register("deduplicate", AGGREGATE)
def f_deduplicate(args, ctx):
    """deduplicate(col, all) — reference returns the deduplicated rows of the
    window; with all=false returns just the latest row if new else empty."""
    values, keep_all = args[0], args[1] if len(args) > 1 else True
    all_vals = bool(keep_all[0]) if isinstance(keep_all, list) and keep_all else bool(keep_all)
    seen = set()
    out = []
    for v in values:
        marker = repr(v)
        if marker not in seen:
            seen.add(marker)
            out.append(v)
    if all_vals:
        return out
    if values and repr(values[-1]) not in {repr(v) for v in values[:-1]}:
        return [values[-1]]
    return []


def _variance(values: List[Any], sample: bool) -> Any:
    nums = _nums(values)
    if len(nums) == 0:
        return None
    if len(nums) == 1:
        return 0.0 if not sample else None
    fn = statistics.variance if sample else statistics.pvariance
    return float(fn(nums))


@register("stddev", AGGREGATE, inc_name="inc_stddev")
def f_stddev(args, ctx):
    v = _variance(args[0], sample=False)
    return None if v is None else float(v) ** 0.5


@register("stddevs", AGGREGATE, inc_name="inc_stddevs")
def f_stddevs(args, ctx):
    v = _variance(args[0], sample=True)
    return None if v is None else float(v) ** 0.5


@register("var", AGGREGATE)
def f_var(args, ctx):
    return _variance(args[0], sample=False)


@register("vars", AGGREGATE)
def f_vars(args, ctx):
    return _variance(args[0], sample=True)


@register("median", AGGREGATE)
def f_median(args, ctx):
    nums = _nums(args[0])
    if not nums:
        return None
    return float(statistics.median(nums))


def _percentile(values: List[Any], frac: float, cont: bool) -> Any:
    if not 0.0 <= frac <= 1.0:
        raise ValueError(f"percentile fraction must be in [0, 1], got {frac}")
    nums = sorted(_nums(values))
    if not nums:
        return None
    if len(nums) == 1:
        return nums[0]
    idx = frac * (len(nums) - 1)
    if cont:
        lo = int(idx)
        hi = min(lo + 1, len(nums) - 1)
        w = idx - lo
        return nums[lo] * (1 - w) + nums[hi] * w
    return nums[min(int(round(idx + 0.5)) if idx % 1 else int(idx), len(nums) - 1)]


@register("percentile_cont", AGGREGATE)
def f_percentile_cont(args, ctx):
    frac = cast.to_float(args[1][0] if isinstance(args[1], list) else args[1])
    return _percentile(args[0], frac, cont=True)


@register("percentile_disc", AGGREGATE)
def f_percentile_disc(args, ctx):
    frac = cast.to_float(args[1][0] if isinstance(args[1], list) else args[1])
    return _percentile(args[0], frac, cont=False)


@register("last_value", AGGREGATE, inc_name="inc_last_value")
def f_last_value(args, ctx):
    values = args[0]
    ignore_null = True
    if len(args) > 1:
        second = args[1]
        ignore_null = bool(second[0]) if isinstance(second, list) and second else bool(second)
    if ignore_null:
        for v in reversed(values):
            if v is not None:
                return v
        return None
    return values[-1] if values else None
