"""Accumulator functions — analogue of internal/binder/function/funcs_acc.go:
acc_sum/acc_count/acc_avg/acc_max/acc_min. Running accumulation across rows
of the stream (not window-scoped); state persists in rule state and resets
when the OVER (WHEN ...) condition fires.
"""
from __future__ import annotations

from ..data import cast
from .registry import SCALAR, register


def _acc(ctx, key, default):
    v = ctx.get_state("acc:" + key)
    return default if v is None else v


@register("acc_sum", SCALAR, stateful=True)
def f_acc_sum(args, ctx):
    total = _acc(ctx, "sum", 0.0)
    if args[0] is not None:
        total += cast.to_float(args[0])
        ctx.put_state("acc:sum", total)
    return total


@register("acc_count", SCALAR, stateful=True)
def f_acc_count(args, ctx):
    n = _acc(ctx, "count", 0)
    if args[0] is not None:
        n += 1
        ctx.put_state("acc:count", n)
    return n


@register("acc_avg", SCALAR, stateful=True)
def f_acc_avg(args, ctx):
    s = _acc(ctx, "avg_sum", 0.0)
    n = _acc(ctx, "avg_n", 0)
    if args[0] is not None:
        s += cast.to_float(args[0])
        n += 1
        ctx.put_state("acc:avg_sum", s)
        ctx.put_state("acc:avg_n", n)
    return s / n if n else None


@register("acc_max", SCALAR, stateful=True)
def f_acc_max(args, ctx):
    best = ctx.get_state("acc:max")
    if args[0] is not None and (best is None or cast.compare(args[0], best) == 1):
        best = args[0]
        ctx.put_state("acc:max", best)
    return best


@register("acc_min", SCALAR, stateful=True)
def f_acc_min(args, ctx):
    best = ctx.get_state("acc:min")
    if args[0] is not None and (best is None or cast.compare(args[0], best) == -1):
        best = args[0]
        ctx.put_state("acc:min", best)
    return best
