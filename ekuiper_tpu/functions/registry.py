"""Built-in function registry — analogue of eKuiper's single `builtins` map
(reference: internal/binder/function/function.go:34-36) plus the binder
factory chain (internal/binder/factory.go:24-61).

Each function registers with metadata the planner needs:
- `ftype`: scalar | aggregate | analytic | srf (set-returning) | window
- `exec`: row-path implementation (python values)
- `vexec`: optional vectorized implementation over numpy/jnp columns — the
  TPU fast path; the expression compiler uses it when every node in an
  expression tree is vectorizable
- `val`: optional argument validator
- `inc_name`: for aggregates with an incremental (streaming partial)
  counterpart, its name (reference: funcs_inc_agg.go pairing)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

SCALAR = "scalar"
AGGREGATE = "aggregate"
ANALYTIC = "analytic"
SRF = "srf"
WINDOW_FUNC = "window"


@dataclass
class Accumulator:
    """Streaming-partial protocol for incremental aggregates
    (reference: funcs_inc_agg.go — WindowIncAggOperator pairing).

    init() -> state; step(state, value) -> state; merge(a, b) -> state
    (cross-shard combine over ICI); result(state) -> final value.
    """

    init: Callable[[], Any]
    step: Callable[[Any, Any], Any]
    result: Callable[[Any], Any]
    merge: Optional[Callable[[Any, Any], Any]] = None


@dataclass
class FunctionDef:
    name: str
    ftype: str
    exec: Callable[..., Any]
    vexec: Optional[Callable[..., Any]] = None
    val: Optional[Callable[[List[Any]], Optional[str]]] = None
    inc_name: str = ""
    # analytic/stateful functions get per-call-instance state
    stateful: bool = False
    # incremental-aggregate accumulator (inc_* functions)
    acc: Optional[Accumulator] = None


_registry: Dict[str, FunctionDef] = {}
_providers: List[Callable[[str], Optional[FunctionDef]]] = []


def register(
    name: str,
    ftype: str = SCALAR,
    vexec: Optional[Callable[..., Any]] = None,
    val: Optional[Callable[[List[Any]], Optional[str]]] = None,
    inc_name: str = "",
    stateful: bool = False,
) -> Callable[[Callable[..., Any]], Callable[..., Any]]:
    def wrap(fn: Callable[..., Any]) -> Callable[..., Any]:
        _registry[name.lower()] = FunctionDef(
            name=name.lower(), ftype=ftype, exec=fn, vexec=vexec, val=val,
            inc_name=inc_name, stateful=stateful,
        )
        return fn

    return wrap


def register_def(fd: FunctionDef) -> None:
    _registry[fd.name.lower()] = fd


def unregister(name: str) -> None:
    """Remove a function (plugin uninstall)."""
    _registry.pop(name.lower(), None)


def add_provider(provider: Callable[[str], Optional[FunctionDef]]) -> None:
    """Later-chance providers: plugins, external services, JS — the ordered
    factory chain of the reference binder."""
    _providers.append(provider)


def lookup(name: str) -> Optional[FunctionDef]:
    _ensure_loaded()
    fd = _registry.get(name.lower())
    if fd is not None:
        return fd
    for provider in _providers:
        fd = provider(name.lower())
        if fd is not None:
            return fd
    return None


def exists(name: str) -> bool:
    return lookup(name) is not None


def is_aggregate(name: str) -> bool:
    fd = lookup(name)
    return fd is not None and fd.ftype == AGGREGATE


def is_analytic(name: str) -> bool:
    fd = lookup(name)
    return fd is not None and fd.ftype == ANALYTIC


def is_srf(name: str) -> bool:
    fd = lookup(name)
    return fd is not None and fd.ftype == SRF


def all_names() -> List[str]:
    _ensure_loaded()
    return sorted(_registry.keys())


_loaded = False


def _ensure_loaded() -> None:
    """Import builtin modules on first lookup (they self-register)."""
    global _loaded
    if _loaded:
        return
    _loaded = True
    from . import (  # noqa: F401
        funcs_acc,
        funcs_agg,
        funcs_analytic,
        funcs_array,
        funcs_datetime,
        funcs_ext,
        funcs_global_state,
        funcs_inc_agg,
        funcs_math,
        funcs_misc,
        funcs_obj,
        funcs_sketch,
        funcs_srf,
        funcs_str,
        funcs_window,
    )
