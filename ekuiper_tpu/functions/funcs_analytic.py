"""Analytic functions — analogue of internal/binder/function/funcs_analytic.go:
lag, latest, changed_col, had_changed. Stateful per call instance and per
partition (the `partition by` extra args, reference: internal/xsql/valuer.go:447).

State layout: ctx.state[partition_key] holds the per-partition value, where
partition_key is "" when no PARTITION BY is present. The AnalyticFuncsOp
computes these per-row *before* filtering (reference:
internal/topo/operator/analyticfuncs_operator.go).
"""
from __future__ import annotations

from typing import Any

from .registry import ANALYTIC, register


def _pstate(ctx, partition: str) -> dict:
    st = ctx.get_state("p:" + partition)
    if st is None:
        st = {}
        ctx.put_state("p:" + partition, st)
    return st


@register("lag", ANALYTIC, stateful=True)
def f_lag(args, ctx, partition: str = "", update: bool = True):
    """lag(col[, index[, default]]) — value from `index` rows ago.
    update=False (OVER WHEN false): peek without recording the row."""
    val = args[0]
    index = int(args[1]) if len(args) > 1 and args[1] is not None else 1
    default = args[2] if len(args) > 2 else None
    st = _pstate(ctx, partition)
    hist = st.setdefault("hist", [])
    out = hist[-index] if len(hist) >= index else default
    if update:
        hist.append(val)
        if len(hist) > index:
            del hist[: len(hist) - index]
    return out


@register("latest", ANALYTIC, stateful=True)
def f_latest(args, ctx, partition: str = "", update: bool = True):
    """latest(col[, default]) — most recent non-null value."""
    val = args[0]
    default = args[1] if len(args) > 1 else None
    st = _pstate(ctx, partition)
    if not update:
        return st.get("latest", default)
    if val is not None:
        st["latest"] = val
        return val
    return st.get("latest", default)


@register("changed_col", ANALYTIC, stateful=True)
def f_changed_col(args, ctx, partition: str = "", update: bool = True):
    """changed_col(ignore_null, col) — col value if changed since last row else null."""
    ignore_null, val = bool(args[0]), args[1]
    st = _pstate(ctx, partition)
    if not update:
        return None
    if val is None and ignore_null:
        return None
    prev_set = "prev" in st
    prev = st.get("prev")
    st["prev"] = val
    if not prev_set or prev != val:
        return val
    return None


@register("had_changed", ANALYTIC, stateful=True)
def f_had_changed(args, ctx, partition: str = "", update: bool = True):
    """had_changed(ignore_null, col1[, col2...]) — true if any col changed."""
    ignore_null = bool(args[0])
    st = _pstate(ctx, partition)
    if not update:
        return False
    changed = False
    for i, val in enumerate(args[1:]):
        key = f"hc{i}"
        if val is None and ignore_null:
            continue
        prev_set = key in st
        prev = st.get(key)
        st[key] = val
        if not prev_set or prev != val:
            changed = True
    return changed
