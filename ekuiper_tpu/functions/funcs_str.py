"""String functions — analogue of internal/binder/function/funcs_str.go (20 funcs).

String columns live host-side (object dtype); these run on the host path. A
few get numpy vexec via vectorized object ops where profitable.
"""
from __future__ import annotations

import re
from typing import Any

from ..data import cast
from .registry import SCALAR, register


def _s(v: Any) -> str:
    return cast.to_string(v)


@register("concat", SCALAR)
def f_concat(args, ctx):
    return "".join(_s(a) for a in args if a is not None)


@register("endswith", SCALAR)
def f_endswith(args, ctx):
    if args[0] is None or args[1] is None:
        return False
    return _s(args[0]).endswith(_s(args[1]))


@register("startswith", SCALAR)
def f_startswith(args, ctx):
    if args[0] is None or args[1] is None:
        return False
    return _s(args[0]).startswith(_s(args[1]))


@register("indexof", SCALAR)
def f_indexof(args, ctx):
    if args[0] is None or args[1] is None:
        return None
    return _s(args[0]).find(_s(args[1]))


@register("length", SCALAR)
def f_length(args, ctx):
    v = args[0]
    if v is None:
        return None
    if isinstance(v, (list, dict)):
        return len(v)
    return len(_s(v))


@register("numbytes", SCALAR)
def f_numbytes(args, ctx):
    v = args[0]
    return None if v is None else len(_s(v).encode("utf-8"))


@register("lower", SCALAR)
def f_lower(args, ctx):
    v = args[0]
    return None if v is None else _s(v).lower()


@register("upper", SCALAR)
def f_upper(args, ctx):
    v = args[0]
    return None if v is None else _s(v).upper()


@register("lpad", SCALAR)
def f_lpad(args, ctx):
    if args[0] is None:
        return None
    return " " * cast.to_int(args[1]) + _s(args[0])


@register("rpad", SCALAR)
def f_rpad(args, ctx):
    if args[0] is None:
        return None
    return _s(args[0]) + " " * cast.to_int(args[1])


@register("ltrim", SCALAR)
def f_ltrim(args, ctx):
    v = args[0]
    return None if v is None else _s(v).lstrip()


@register("rtrim", SCALAR)
def f_rtrim(args, ctx):
    v = args[0]
    return None if v is None else _s(v).rstrip()


@register("trim", SCALAR)
def f_trim(args, ctx):
    v = args[0]
    return None if v is None else _s(v).strip()


@register("reverse", SCALAR)
def f_reverse(args, ctx):
    v = args[0]
    return None if v is None else _s(v)[::-1]


@register("regexp_matches", SCALAR)
def f_regexp_matches(args, ctx):
    if args[0] is None or args[1] is None:
        return False
    return re.search(_s(args[1]), _s(args[0])) is not None


@register("regexp_replace", SCALAR)
def f_regexp_replace(args, ctx):
    if any(a is None for a in args[:3]):
        return None
    return re.sub(_s(args[1]), _s(args[2]), _s(args[0]))


@register("regexp_substr", SCALAR)
def f_regexp_substr(args, ctx):
    if args[0] is None or args[1] is None:
        return None
    m = re.search(_s(args[1]), _s(args[0]))
    return None if m is None else m.group(0)


@register("substring", SCALAR)
def f_substring(args, ctx):
    """substring(str, start [, end]) — start inclusive, end exclusive
    (reference semantics: 0-based)."""
    if args[0] is None:
        return None
    s = _s(args[0])
    start = cast.to_int(args[1])
    if start < 0:
        raise ValueError("substring start must be non-negative")
    if len(args) > 2 and args[2] is not None:
        end = cast.to_int(args[2])
        if end < start:
            raise ValueError("substring end must be >= start")
        return s[start:end]
    return s[start:]


@register("split_value", SCALAR)
def f_split_value(args, ctx):
    """split_value(str, sep, index)"""
    if any(a is None for a in args[:3]):
        return None
    parts = _s(args[0]).split(_s(args[1]))
    idx = cast.to_int(args[2])
    if idx >= len(parts) or idx < -len(parts):
        raise ValueError(f"split_value index {idx} out of range")
    return parts[idx]


@register("format", SCALAR)
def f_format(args, ctx):
    """format(number, decimals) — fixed-point formatting."""
    if args[0] is None:
        return None
    d = cast.to_int(args[1]) if len(args) > 1 else 0
    return f"{cast.to_float(args[0]):.{d}f}"
