"""Math functions — analogue of internal/binder/function/funcs_math.go (33 funcs).

Every function has both a row-path exec and a vectorized `vexec` over numpy
arrays; the expression compiler (sql/compiler.py) uses vexec to build whole-
batch computations that XLA fuses on the VPU.
"""
from __future__ import annotations

import math
import random
from typing import Any

import numpy as np

from ..data import cast
from .registry import SCALAR, register


def _unary(name: str, fn, np_fn, int_passthrough: bool = False):
    def exec_fn(args, ctx):
        v = args[0]
        if v is None:
            return None
        if int_passthrough and isinstance(v, int) and not isinstance(v, bool):
            return fn(v)
        return fn(cast.to_float(v))

    exec_fn.__name__ = f"f_{name}"
    register(name, SCALAR, vexec=np_fn)(exec_fn)


def _abs(v):
    return abs(v)


_unary("abs", _abs, np.abs, int_passthrough=True)
_unary("acos", math.acos, np.arccos)
_unary("asin", math.asin, np.arcsin)
_unary("atan", math.atan, np.arctan)
_unary("cos", math.cos, np.cos)
_unary("cosh", math.cosh, np.cosh)
_unary("sin", math.sin, np.sin)
_unary("sinh", math.sinh, np.sinh)
_unary("tan", math.tan, np.tan)
_unary("tanh", math.tanh, np.tanh)
_unary("exp", math.exp, np.exp)
_unary("ln", math.log, np.log)
_unary("sqrt", math.sqrt, np.sqrt)
_unary("radians", math.radians, np.radians)
_unary("degrees", math.degrees, np.degrees)


@register("log", SCALAR, vexec=lambda *a: np.log10(a[0]) if len(a) == 1 else np.log(a[1]) / np.log(a[0]))
def f_log(args, ctx):
    """log(x) = base-10; log(b, x) = base-b (reference semantics)."""
    if any(a is None for a in args):
        return None
    if len(args) == 1:
        return math.log10(cast.to_float(args[0]))
    return math.log(cast.to_float(args[1]), cast.to_float(args[0]))


@register("cot", SCALAR, vexec=lambda x: 1.0 / np.tan(x))
def f_cot(args, ctx):
    v = args[0]
    return None if v is None else 1.0 / math.tan(cast.to_float(v))


@register("atan2", SCALAR, vexec=np.arctan2)
def f_atan2(args, ctx):
    if args[0] is None or args[1] is None:
        return None
    return math.atan2(cast.to_float(args[0]), cast.to_float(args[1]))


def _ceil_exec(args, ctx):
    v = args[0]
    if v is None:
        return None
    if isinstance(v, int) and not isinstance(v, bool):
        return v
    return float(math.ceil(cast.to_float(v)))


register("ceil", SCALAR, vexec=np.ceil)(_ceil_exec)
register("ceiling", SCALAR, vexec=np.ceil)(_ceil_exec)


@register("floor", SCALAR, vexec=np.floor)
def f_floor(args, ctx):
    v = args[0]
    if v is None:
        return None
    if isinstance(v, int) and not isinstance(v, bool):
        return v
    return float(math.floor(cast.to_float(v)))


@register("round", SCALAR, vexec=np.round)
def f_round(args, ctx):
    v = args[0]
    if v is None:
        return None
    if isinstance(v, int) and not isinstance(v, bool):
        return v
    # reference rounds half away from zero
    f = cast.to_float(v)
    return float(math.floor(f + 0.5) if f >= 0 else math.ceil(f - 0.5))


@register("power", SCALAR, vexec=np.power)
def f_power(args, ctx):
    if args[0] is None or args[1] is None:
        return None
    x, y = args[0], args[1]
    if (
        isinstance(x, int) and isinstance(y, int)
        and not isinstance(x, bool) and not isinstance(y, bool) and y >= 0
    ):
        return x ** y
    return cast.to_float(x) ** cast.to_float(y)


register("pow", SCALAR, vexec=np.power)(f_power)


@register("mod", SCALAR, vexec=np.mod)
def f_mod(args, ctx):
    if args[0] is None or args[1] is None:
        return None
    x, y = args[0], args[1]
    if (
        isinstance(x, int) and isinstance(y, int)
        and not isinstance(x, bool) and not isinstance(y, bool)
    ):
        return math.fmod(x, y).__trunc__()
    return math.fmod(cast.to_float(x), cast.to_float(y))


@register("sign", SCALAR, vexec=np.sign)
def f_sign(args, ctx):
    v = args[0]
    if v is None:
        return None
    f = cast.to_float(v)
    return 1 if f > 0 else (-1 if f < 0 else 0)


@register("pi", SCALAR, vexec=lambda: np.float32(math.pi))
def f_pi(args, ctx):
    return math.pi


@register("rand", SCALAR)
def f_rand(args, ctx):
    return random.random()


@register("bitand", SCALAR, vexec=np.bitwise_and)
def f_bitand(args, ctx):
    if args[0] is None or args[1] is None:
        return None
    return cast.to_int(args[0], cast.STRICT) & cast.to_int(args[1], cast.STRICT)


@register("bitor", SCALAR, vexec=np.bitwise_or)
def f_bitor(args, ctx):
    if args[0] is None or args[1] is None:
        return None
    return cast.to_int(args[0], cast.STRICT) | cast.to_int(args[1], cast.STRICT)


@register("bitxor", SCALAR, vexec=np.bitwise_xor)
def f_bitxor(args, ctx):
    if args[0] is None or args[1] is None:
        return None
    return cast.to_int(args[0], cast.STRICT) ^ cast.to_int(args[1], cast.STRICT)


@register("bitnot", SCALAR, vexec=np.invert)
def f_bitnot(args, ctx):
    v = args[0]
    return None if v is None else ~cast.to_int(v, cast.STRICT)


@register("conv", SCALAR)
def f_conv(args, ctx):
    """conv(str, from_base, to_base)"""
    if any(a is None for a in args):
        return None
    s, fb, tb = cast.to_string(args[0]), cast.to_int(args[1]), cast.to_int(args[2])
    n = int(s, fb)
    if tb == 10:
        return str(n)
    digits = "0123456789abcdefghijklmnopqrstuvwxyz"
    neg = n < 0
    n = abs(n)
    out = ""
    while True:
        out = digits[n % tb] + out
        n //= tb
        if n == 0:
            break
    return ("-" if neg else "") + out
