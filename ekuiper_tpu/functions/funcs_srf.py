"""Set-returning functions — analogue of internal/binder/function/funcs_srf.go.
`unnest` expands an array field into multiple rows (ProjectSetOp)."""
from __future__ import annotations

from .registry import SRF, register


@register("unnest", SRF)
def f_unnest(args, ctx):
    """Returns the list of rows to expand into. Array of objects merges each
    object's fields into the row; scalars become the column value."""
    v = args[0]
    if v is None:
        return []
    if not isinstance(v, (list, tuple)):
        raise ValueError("unnest expects an array")
    return list(v)
