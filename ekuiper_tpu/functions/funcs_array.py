"""Array functions — analogue of internal/binder/function/funcs_array.go (24 funcs)."""
from __future__ import annotations

import random
from typing import Any, List

from ..data import cast
from .registry import SCALAR, register


def _arr(v: Any) -> List[Any]:
    if not isinstance(v, (list, tuple)):
        raise ValueError(f"expected array but got {type(v).__name__}")
    return list(v)


@register("array_create", SCALAR)
def f_array_create(args, ctx):
    return list(args)


@register("array_position", SCALAR)
def f_array_position(args, ctx):
    if args[0] is None:
        return -1
    arr = _arr(args[0])
    for i, v in enumerate(arr):
        if v == args[1]:
            return i
    return -1


@register("element_at", SCALAR)
def f_element_at(args, ctx):
    v = args[0]
    if v is None:
        return None
    if isinstance(v, dict):
        return v.get(cast.to_string(args[1]))
    arr = _arr(v)
    idx = cast.to_int(args[1])
    if idx < -len(arr) or idx >= len(arr):
        raise ValueError(f"element_at index {idx} out of range")
    return arr[idx]


@register("array_contains", SCALAR)
def f_array_contains(args, ctx):
    return args[0] is not None and args[1] in _arr(args[0])


@register("array_remove", SCALAR)
def f_array_remove(args, ctx):
    if args[0] is None:
        return None
    return [v for v in _arr(args[0]) if v != args[1]]


@register("array_last_position", SCALAR)
def f_array_last_position(args, ctx):
    if args[0] is None:
        return -1
    arr = _arr(args[0])
    for i in range(len(arr) - 1, -1, -1):
        if arr[i] == args[1]:
            return i
    return -1


@register("array_contains_any", SCALAR)
def f_array_contains_any(args, ctx):
    if args[0] is None or args[1] is None:
        return False
    a = _arr(args[0])
    return any(v in a for v in _arr(args[1]))


@register("array_intersect", SCALAR)
def f_array_intersect(args, ctx):
    if args[0] is None or args[1] is None:
        return None
    b = _arr(args[1])
    out, seen = [], []
    for v in _arr(args[0]):
        if v in b and v not in seen:
            seen.append(v)
            out.append(v)
    return out


@register("array_union", SCALAR)
def f_array_union(args, ctx):
    if args[0] is None or args[1] is None:
        return None
    out: List[Any] = []
    for v in _arr(args[0]) + _arr(args[1]):
        if v not in out:
            out.append(v)
    return out


@register("array_max", SCALAR)
def f_array_max(args, ctx):
    if args[0] is None:
        return None
    best = None
    for v in _arr(args[0]):
        if v is None:
            continue
        if best is None or cast.compare(v, best) == 1:
            best = v
    return best


@register("array_min", SCALAR)
def f_array_min(args, ctx):
    if args[0] is None:
        return None
    best = None
    for v in _arr(args[0]):
        if v is None:
            continue
        if best is None or cast.compare(v, best) == -1:
            best = v
    return best


@register("array_except", SCALAR)
def f_array_except(args, ctx):
    if args[0] is None or args[1] is None:
        return None
    b = _arr(args[1])
    out: List[Any] = []
    for v in _arr(args[0]):
        if v not in b and v not in out:
            out.append(v)
    return out


@register("repeat", SCALAR)
def f_repeat(args, ctx):
    return [args[0]] * cast.to_int(args[1])


@register("sequence", SCALAR)
def f_sequence(args, ctx):
    start, stop = cast.to_int(args[0]), cast.to_int(args[1])
    step = cast.to_int(args[2]) if len(args) > 2 else (1 if stop >= start else -1)
    if step == 0:
        raise ValueError("sequence step cannot be 0")
    return list(range(start, stop + (1 if step > 0 else -1), step))


@register("array_cardinality", SCALAR)
def f_array_cardinality(args, ctx):
    return 0 if args[0] is None else len(_arr(args[0]))


@register("array_flatten", SCALAR)
def f_array_flatten(args, ctx):
    if args[0] is None:
        return None
    out: List[Any] = []
    for v in _arr(args[0]):
        if isinstance(v, (list, tuple)):
            out.extend(v)
        else:
            out.append(v)
    return out


@register("array_distinct", SCALAR)
def f_array_distinct(args, ctx):
    if args[0] is None:
        return None
    out: List[Any] = []
    for v in _arr(args[0]):
        if v not in out:
            out.append(v)
    return out


@register("array_map", SCALAR)
def f_array_map(args, ctx):
    """array_map(func_name, arr) — applies a scalar builtin to each element."""
    from . import registry as _r

    if args[1] is None:
        return None
    fd = _r.lookup(cast.to_string(args[0]))
    if fd is None or fd.ftype != SCALAR:
        raise ValueError(f"array_map: unknown scalar function {args[0]}")
    return [fd.exec([v], ctx) for v in _arr(args[1])]


@register("array_join", SCALAR)
def f_array_join(args, ctx):
    if args[0] is None:
        return None
    sep = cast.to_string(args[1]) if len(args) > 1 else ","
    null_repl = cast.to_string(args[2]) if len(args) > 2 else None
    parts = []
    for v in _arr(args[0]):
        if v is None:
            if null_repl is not None:
                parts.append(null_repl)
        else:
            parts.append(cast.to_string(v))
    return sep.join(parts)


@register("array_shuffle", SCALAR)
def f_array_shuffle(args, ctx):
    if args[0] is None:
        return None
    out = _arr(args[0])
    random.shuffle(out)
    return out


@register("array_sort", SCALAR)
def f_array_sort(args, ctx):
    if args[0] is None:
        return None
    import functools

    return sorted(_arr(args[0]), key=functools.cmp_to_key(
        lambda a, b: cast.compare(a, b) or 0
    ))


@register("array_concat", SCALAR)
def f_array_concat(args, ctx):
    out: List[Any] = []
    for a in args:
        if a is None:
            return None
        out.extend(_arr(a))
    return out


@register("kvpair_array_to_obj", SCALAR)
def f_kvpair_array_to_obj(args, ctx):
    if args[0] is None:
        return None
    out = {}
    for pair in _arr(args[0]):
        if isinstance(pair, dict) and "key" in pair:
            out[cast.to_string(pair["key"])] = pair.get("value")
    return out
