"""Rule-hit tracking functions — analogue of funcs_global_state.go:
last_hit_count/last_hit_time/last_agg_hit_count/last_agg_hit_time.
Backed by the rule's state (and keyed state for cross-rule visibility,
reference: internal/keyedstate/kv.go:28-36).
"""
from __future__ import annotations

from ..utils import timex
from .registry import AGGREGATE, SCALAR, register


@register("last_hit_count", SCALAR, stateful=True)
def f_last_hit_count(args, ctx):
    n = ctx.get_state("hit_count", 0)
    ctx.put_state("hit_count", n + 1)
    return n


@register("last_hit_time", SCALAR, stateful=True)
def f_last_hit_time(args, ctx):
    t = ctx.get_state("hit_time", 0)
    ctx.put_state("hit_time", timex.now_ms())
    return t


@register("last_agg_hit_count", AGGREGATE, stateful=True)
def f_last_agg_hit_count(args, ctx):
    n = ctx.get_state("agg_hit_count", 0)
    ctx.put_state("agg_hit_count", n + 1)
    return n


@register("last_agg_hit_time", AGGREGATE, stateful=True)
def f_last_agg_hit_time(args, ctx):
    t = ctx.get_state("agg_hit_time", 0)
    ctx.put_state("agg_hit_time", timex.now_ms())
    return t
