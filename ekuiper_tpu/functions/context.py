"""Function execution context — analogue of api.FunctionContext
(reference: contract/api/ctx.go:41-66 + internal/xsql functionRuntime).

Carries per-call-instance state (for stateful analytic/accumulator functions),
rule identity, and the current window range for window_start()/window_end().
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..data.rows import Row, WindowRange


@dataclass
class FunctionContext:
    rule_id: str = ""
    func_id: int = 0
    state: Dict[str, Any] = field(default_factory=dict)
    window_range: Optional[WindowRange] = None
    row: Optional[Row] = None  # current row (meta access etc.)
    keyed_state: Optional[Any] = None  # global cross-rule KV
    trigger_time: int = 0

    def get_state(self, key: str, default: Any = None) -> Any:
        return self.state.get(key, default)

    def put_state(self, key: str, value: Any) -> None:
        self.state[key] = value


EMPTY = FunctionContext()
