"""Extension function plugins as builtins — the reference ships these as
portable/native plugins (extensions/functions/{geohash,image,onnx}); here
they register directly since their dependencies are bundled (pure-python
geohash, pillow for image ops, torch-cpu for model inference).

- geohash*: full surface of extensions/functions/geohash/geohash.go
  (encode/decode/boundingBox/neighbor/neighbors, string + uint64 forms,
  mmcloughlin/geohash-compatible base32 and neighbor ordering).
- resize/thumbnail: extensions/functions/image/{resize,thumbnail}.go
  semantics over pillow (bilinear resize, raw RGB mode, format-preserving
  re-encode; base64 strings accepted where Go takes []byte — JSON rows
  carry binary as base64).
- model_infer: the role of extensions/functions/onnx/onnx.go — in-process
  model inference as a SQL function. Divergence: TorchScript via the
  bundled torch-cpu instead of onnxruntime (not in image); models load
  from <data_dir>/models/<name>.pt, cached per process.
"""
from __future__ import annotations

import base64
import io
from typing import Any, Dict, List, Tuple

from .registry import SCALAR, register

_B32 = "0123456789bcdefghjkmnpqrstuvwxyz"
_B32_IDX = {c: i for i, c in enumerate(_B32)}

# mmcloughlin/geohash neighbor ordering (geohash.go g_direction)
_DIRS = {
    "North": (1, 0), "NorthEast": (1, 1), "East": (0, 1),
    "SouthEast": (-1, 1), "South": (-1, 0), "SouthWest": (-1, -1),
    "West": (0, -1), "NorthWest": (1, -1),
}
_NEIGHBOR_ORDER = ["North", "NorthEast", "East", "SouthEast",
                   "South", "SouthWest", "West", "NorthWest"]


def _interleave(lat: float, lon: float, bits: int) -> int:
    """bits total, even bits longitude first (standard geohash)."""
    lat_rng = [-90.0, 90.0]
    lon_rng = [-180.0, 180.0]
    out = 0
    for i in range(bits):
        rng, v = (lon_rng, lon) if i % 2 == 0 else (lat_rng, lat)
        mid = (rng[0] + rng[1]) / 2
        bit = 1 if v >= mid else 0
        out = (out << 1) | bit
        if bit:
            rng[0] = mid
        else:
            rng[1] = mid
    return out


def _deinterleave(code: int, bits: int) -> Tuple[Tuple[float, float], Tuple[float, float]]:
    lat_rng = [-90.0, 90.0]
    lon_rng = [-180.0, 180.0]
    for i in range(bits):
        bit = (code >> (bits - 1 - i)) & 1
        rng = lon_rng if i % 2 == 0 else lat_rng
        mid = (rng[0] + rng[1]) / 2
        if bit:
            rng[0] = mid
        else:
            rng[1] = mid
    return (lat_rng[0], lat_rng[1]), (lon_rng[0], lon_rng[1])


def _gh_encode(lat: float, lon: float, chars: int = 12) -> str:
    code = _interleave(float(lat), float(lon), chars * 5)
    return "".join(_B32[(code >> (5 * (chars - 1 - i))) & 31]
                   for i in range(chars))


def _gh_code(hash_: str) -> int:
    code = 0
    for c in hash_:
        if c not in _B32_IDX:
            raise ValueError(f"invalid geohash character {c!r}")
        code = (code << 5) | _B32_IDX[c]
    return code


def _gh_box(hash_: str) -> Dict[str, float]:
    (la0, la1), (lo0, lo1) = _deinterleave(_gh_code(hash_), len(hash_) * 5)
    return {"MinLat": la0, "MaxLat": la1, "MinLng": lo0, "MaxLng": lo1}


def _gh_decode(hash_: str) -> Tuple[float, float]:
    b = _gh_box(hash_)
    return ((b["MinLat"] + b["MaxLat"]) / 2, (b["MinLng"] + b["MaxLng"]) / 2)


def _split_axes(code: int, bits: int) -> Tuple[int, int, int, int]:
    """Interleaved code -> (lat_int, lon_int, lat_bits, lon_bits).
    Even bit positions (MSB-first) are longitude."""
    lon_bits = (bits + 1) // 2
    lat_bits = bits // 2
    lat = lon = 0
    for i in range(bits):
        bit = (code >> (bits - 1 - i)) & 1
        if i % 2 == 0:
            lon = (lon << 1) | bit
        else:
            lat = (lat << 1) | bit
    return lat, lon, lat_bits, lon_bits


def _join_axes(lat: int, lon: int, bits: int) -> int:
    lon_bits = (bits + 1) // 2
    lat_bits = bits // 2
    out = 0
    li, oi = lat_bits, lon_bits
    for i in range(bits):
        if i % 2 == 0:
            oi -= 1
            out = (out << 1) | ((lon >> oi) & 1)
        else:
            li -= 1
            out = (out << 1) | ((lat >> li) & 1)
    return out


def _neighbor_code(code: int, bits: int, direction: str) -> int:
    """Neighbor via per-axis integer increment with wraparound — the
    mmcloughlin/geohash approach, so pole-row cells wrap instead of
    returning themselves (a clamped midpoint re-encode would)."""
    if direction not in _DIRS:
        raise ValueError(f"invalid direction {direction!r}")
    dlat, dlon = _DIRS[direction]
    lat, lon, lat_bits, lon_bits = _split_axes(code, bits)
    lat = (lat + dlat) % (1 << lat_bits)
    lon = (lon + dlon) % (1 << lon_bits)
    return _join_axes(lat, lon, bits)


def _gh_neighbor(hash_: str, direction: str) -> str:
    code = _neighbor_code(_gh_code(hash_), len(hash_) * 5, direction)
    chars = len(hash_)
    return "".join(_B32[(code >> (5 * (chars - 1 - i))) & 31]
                   for i in range(chars))


_INT_BITS = 64


def _gh_encode_int(lat: float, lon: float) -> int:
    return _interleave(float(lat), float(lon), _INT_BITS)


def _gh_box_int(code: int) -> Dict[str, float]:
    (la0, la1), (lo0, lo1) = _deinterleave(int(code), _INT_BITS)
    return {"MinLat": la0, "MaxLat": la1, "MinLng": lo0, "MaxLng": lo1}


def _gh_neighbor_int(code: int, direction: str) -> int:
    return _neighbor_code(int(code), _INT_BITS, direction)


@register("geohashencode", SCALAR)
def f_geohash_encode(args, ctx):
    chars = int(args[2]) if len(args) > 2 else 12
    return _gh_encode(float(args[0]), float(args[1]), chars)


@register("geohashencodeint", SCALAR)
def f_geohash_encode_int(args, ctx):
    return _gh_encode_int(float(args[0]), float(args[1]))


@register("geohashdecode", SCALAR)
def f_geohash_decode(args, ctx):
    lat, lon = _gh_decode(str(args[0]))
    return {"Latitude": lat, "Longitude": lon}


@register("geohashdecodeint", SCALAR)
def f_geohash_decode_int(args, ctx):
    b = _gh_box_int(int(args[0]))
    return {"Latitude": (b["MinLat"] + b["MaxLat"]) / 2,
            "Longitude": (b["MinLng"] + b["MaxLng"]) / 2}


@register("geohashboundingbox", SCALAR)
def f_geohash_bbox(args, ctx):
    return _gh_box(str(args[0]))


@register("geohashboundingboxint", SCALAR)
def f_geohash_bbox_int(args, ctx):
    return _gh_box_int(int(args[0]))


@register("geohashneighbor", SCALAR)
def f_geohash_neighbor(args, ctx):
    return _gh_neighbor(str(args[0]), str(args[1]))


@register("geohashneighborint", SCALAR)
def f_geohash_neighbor_int(args, ctx):
    return _gh_neighbor_int(int(args[0]), str(args[1]))


@register("geohashneighbors", SCALAR)
def f_geohash_neighbors(args, ctx):
    h = str(args[0])
    return [_gh_neighbor(h, d) for d in _NEIGHBOR_ORDER]


@register("geohashneighborsint", SCALAR)
def f_geohash_neighbors_int(args, ctx):
    c = int(args[0])
    return [_gh_neighbor_int(c, d) for d in _NEIGHBOR_ORDER]


# ------------------------------------------------------------------- image
def _img_bytes(arg: Any) -> bytes:
    if isinstance(arg, (bytes, bytearray)):
        return bytes(arg)
    if isinstance(arg, str):
        return base64.b64decode(arg)
    raise ValueError(f"expected image bytes / base64, got {type(arg).__name__}")


def _resize(args: List[Any], exact: bool) -> Any:
    from PIL import Image

    raw = _img_bytes(args[0])
    width, height = int(args[1]), int(args[2])
    if width < 0 or height < 0:
        raise ValueError("width/height must be non-negative")
    is_raw = bool(args[3]) if len(args) > 3 else False
    img = Image.open(io.BytesIO(raw))
    fmt = img.format or "PNG"
    if exact:
        img = img.resize((width, height), Image.BILINEAR)
    else:
        img.thumbnail((width, height), Image.BILINEAR)
    if is_raw:
        # raw RGB byte planes, the reference's model-input mode
        # (resize.go:70-84)
        return img.convert("RGB").tobytes()
    out = io.BytesIO()
    img.save(out, format=fmt)
    return out.getvalue()


@register("resize", SCALAR)
def f_resize(args, ctx):
    """resize(img, width, height[, raw]) — image/resize.go:42."""
    return _resize(args, exact=True)


@register("thumbnail", SCALAR)
def f_thumbnail(args, ctx):
    """thumbnail(img, maxWidth, maxHeight) — image/thumbnail.go."""
    return _resize(args, exact=False)


# --------------------------------------------------------------- inference
_MODELS: Dict[str, Any] = {}


import re as _re

_MODEL_NAME = _re.compile(r"^[A-Za-z0-9_.-]+$")


def _load_model(name: str):
    m = _MODELS.get(name)
    if m is None:
        import os

        import torch

        from ..utils.config import get_config

        # the name comes from SQL text — it must stay a bare file name
        # under <data_dir>/models, never a path (traversal would make the
        # function an arbitrary-file loader)
        if not _MODEL_NAME.match(name) or ".." in name:
            raise ValueError(f"invalid model name {name!r}")
        base = name[:-3] if name.endswith(".pt") else name
        path = os.path.join(get_config().data_dir, "models", f"{base}.pt")
        m = torch.jit.load(path, map_location="cpu")
        m.eval()
        _MODELS[name] = m
    return m


@register("model_infer", SCALAR)
def f_model_infer(args, ctx):
    """model_infer(model_name, input...) — in-process inference, the role
    of extensions/functions/onnx/onnx.go (TorchScript divergence: models
    are .pt files under <data_dir>/models/). Each extra arg is one input
    tensor (scalars and flat lists become float32 tensors); the output
    tensor returns as a (nested) list."""
    import torch

    model = _load_model(str(args[0]))
    tensors = []
    for a in args[1:]:
        if isinstance(a, (list, tuple)):
            tensors.append(torch.as_tensor(a, dtype=torch.float32))
        else:
            tensors.append(torch.as_tensor([float(a)], dtype=torch.float32))
    with torch.no_grad():
        out = model(*tensors)
    if isinstance(out, (list, tuple)):
        return [o.tolist() for o in out]
    return out.tolist()
