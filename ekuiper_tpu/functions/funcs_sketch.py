"""Sketch UDFs — the north-star additions (BASELINE.json): approximate
distinct count (HyperLogLog), approximate percentile (log-histogram /
DDSketch-class), and heavy hitters (count-min backed).

On the fused device path these map to wide kernel components
(ops/sketches.py); the host-path implementations here are used by the
buffered window operators and compute small-group results (exactly, which is
a strict accuracy upgrade at host scales).
"""
from __future__ import annotations

from collections import Counter
from typing import Any, List

from ..data import cast
from .registry import AGGREGATE, register


def _hll_exec(args, ctx):
    # host groups are small: exact distinct count
    seen = set()
    for v in args[0]:
        if v is not None:
            seen.add(v if isinstance(v, (int, float, str, bool)) else repr(v))
    return len(seen)


register("hll", AGGREGATE)(_hll_exec)
register("distinct_count_approx", AGGREGATE)(_hll_exec)


# host path: same semantics as percentile_cont (exact at host scales)
from .funcs_agg import f_percentile_cont  # noqa: E402

register("percentile_approx", AGGREGATE)(f_percentile_cont)


def _val_heavy_hitters(args: List[Any]) -> str:
    if len(args) != 2:
        return "expects 2 arguments (col, k)"
    from ..sql import ast

    if isinstance(args[1], ast.IntegerLiteral) and args[1].val <= 0:
        return "k must be a positive integer"
    return ""


@register("heavy_hitters", AGGREGATE, val=_val_heavy_hitters)
def f_heavy_hitters(args, ctx):
    """heavy_hitters(col, k) — top-k values by frequency as
    [{value, count}, ...]. Exact at host-window scales; the device
    CountMinSketch primitive (ops/sketches.py) serves memory-bounded
    window-level sketching beyond what a buffered window holds."""
    if len(args) < 2:
        raise ValueError("heavy_hitters expects 2 arguments (col, k)")
    k_arg = args[1]
    k = cast.to_int(k_arg[0] if isinstance(k_arg, list) else k_arg)
    counts = Counter(
        v if isinstance(v, (int, float, str, bool)) else repr(v)
        for v in args[0]
        if v is not None
    )
    return [
        {"value": v, "count": c} for v, c in counts.most_common(k)
    ]
