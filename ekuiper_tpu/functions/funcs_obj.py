"""Object functions — analogue of internal/binder/function/funcs_obj.go (11 funcs)."""
from __future__ import annotations

from typing import Any, Dict

from ..data import cast
from .registry import SCALAR, register


def _obj(v: Any) -> Dict[str, Any]:
    if not isinstance(v, dict):
        raise ValueError(f"expected object but got {type(v).__name__}")
    return v


@register("keys", SCALAR)
def f_keys(args, ctx):
    return None if args[0] is None else list(_obj(args[0]).keys())


@register("values", SCALAR)
def f_values(args, ctx):
    return None if args[0] is None else list(_obj(args[0]).values())


@register("object", SCALAR)
def f_object(args, ctx):
    """object(keys_array, values_array)"""
    if args[0] is None or args[1] is None:
        return None
    ks, vs = args[0], args[1]
    if len(ks) != len(vs):
        raise ValueError("object(): keys and values must have equal length")
    return {cast.to_string(k): v for k, v in zip(ks, vs)}


@register("zip", SCALAR)
def f_zip(args, ctx):
    """zip(array_of_pairs) — [[k,v],...] → object"""
    if args[0] is None:
        return None
    out = {}
    for pair in args[0]:
        if not isinstance(pair, (list, tuple)) or len(pair) != 2:
            raise ValueError("zip(): each element must be a [key, value] pair")
        out[cast.to_string(pair[0])] = pair[1]
    return out


@register("items", SCALAR)
def f_items(args, ctx):
    return None if args[0] is None else [[k, v] for k, v in _obj(args[0]).items()]


@register("object_concat", SCALAR)
def f_object_concat(args, ctx):
    out: Dict[str, Any] = {}
    for a in args:
        if a is None:
            continue
        out.update(_obj(a))
    return out


@register("object_construct", SCALAR)
def f_object_construct(args, ctx):
    """object_construct(k1, v1, k2, v2, ...) — skips null values."""
    if len(args) % 2 != 0:
        raise ValueError("object_construct requires an even number of args")
    out = {}
    for i in range(0, len(args), 2):
        if args[i + 1] is not None:
            out[cast.to_string(args[i])] = args[i + 1]
    return out


@register("erase", SCALAR)
def f_erase(args, ctx):
    if args[0] is None:
        return None
    obj = dict(_obj(args[0]))
    names = args[1] if isinstance(args[1], (list, tuple)) else [args[1]]
    for name in names:
        obj.pop(cast.to_string(name), None)
    return obj


@register("object_size", SCALAR)
def f_object_size(args, ctx):
    return 0 if args[0] is None else len(_obj(args[0]))


@register("object_pick", SCALAR)
def f_object_pick(args, ctx):
    if args[0] is None:
        return None
    obj = _obj(args[0])
    names = args[1] if isinstance(args[1], (list, tuple)) else list(args[1:])
    return {cast.to_string(n): obj[cast.to_string(n)] for n in names if cast.to_string(n) in obj}


@register("obj_to_kvpair_array", SCALAR)
def f_obj_to_kvpair_array(args, ctx):
    if args[0] is None:
        return None
    return [{"key": k, "value": v} for k, v in _obj(args[0]).items()]
