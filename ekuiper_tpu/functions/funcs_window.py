"""SQL window functions — analogue of internal/binder/function/funcs_window.go.
Applied post-aggregation by the WindowFuncOp."""
from __future__ import annotations

from .registry import WINDOW_FUNC, register


@register("row_number", WINDOW_FUNC, stateful=True)
def f_row_number(args, ctx):
    n = ctx.get_state("row_number", 0) + 1
    ctx.put_state("row_number", n)
    return n
