"""SQL window functions — analogue of internal/binder/function/funcs_window.go.
Applied post-aggregation by the WindowFuncOp."""
from __future__ import annotations

from .registry import WINDOW_FUNC, register


@register("row_number", WINDOW_FUNC, stateful=True)
def f_row_number(args, ctx):
    n = ctx.get_state("row_number", 0) + 1
    ctx.put_state("row_number", n)
    return n


def _collection_only(name: str):
    # rank/dense_rank/lead are whole-collection functions: a per-row exec
    # cannot see the value order, so the window-func operator precomputes
    # them as __analytic_* cal-cols and the evaluator reads the cache.
    # Reaching this exec means the call bypassed the operator.
    def f(args, ctx):
        raise RuntimeError(
            f"{name}() is computed by the window-func operator, "
            "not per-row")

    return f


register("rank", WINDOW_FUNC, stateful=True)(_collection_only("rank"))
register("dense_rank", WINDOW_FUNC, stateful=True)(
    _collection_only("dense_rank"))
register("lead", WINDOW_FUNC, stateful=True)(_collection_only("lead"))
