"""Join node — analogue of eKuiper's JoinOp nested-loop join over window
collections (internal/topo/operator/join_operator.go) plus the stream-lookup
join of LookupNode (internal/topo/node/lookup_node.go) with TTL cache
(internal/topo/lookup/cache/cache.go:31-103).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple as PyTuple

from ..data.batch import ColumnBatch
from ..data.rows import JoinTuple, Row, Tuple, WindowTuples
from ..sql import ast
from ..sql.eval import Evaluator
from ..utils import timex
from .node import Node


class JoinNode(Node):
    """Nested-loop join over a window's mixed-emitter rows."""

    def __init__(self, name: str, joins: List[ast.Join], left_name: str, **kw) -> None:
        super().__init__(name, op_type="op", **kw)
        self.joins = joins
        self.left_name = left_name
        self.ev = Evaluator()

    def process(self, item: Any) -> None:
        if not isinstance(item, WindowTuples):
            self.emit(item)
            return
        by_emitter: Dict[str, List[Any]] = {}
        for r in item.rows():
            if isinstance(r, Tuple):
                by_emitter.setdefault(r.emitter, []).append(r)
            elif isinstance(r, JoinTuple) and r.tuples:
                # a lookup join upstream already widened this row; group it
                # under its stream tuple's emitter
                by_emitter.setdefault(r.tuples[0].emitter, []).append(r)
        current: List[JoinTuple] = [
            JoinTuple(tuples=list(t.tuples)) if isinstance(t, JoinTuple)
            else JoinTuple(tuples=[t])
            for t in by_emitter.get(self.left_name, [])
        ]
        for join in self.joins:
            right_rows = by_emitter.get(join.table.ref_name, [])
            current = self._join_step(current, right_rows, join)
        if current:
            self.emit(WindowTuples(content=list(current), window_range=item.window_range))

    def _join_step(
        self, left: List[JoinTuple], right: List[Tuple], join: ast.Join
    ) -> List[JoinTuple]:
        out: List[JoinTuple] = []
        jt = join.join_type
        matched_right: set = set()
        def widen(rt) -> List[Tuple]:
            return list(rt.tuples) if isinstance(rt, JoinTuple) else [rt]

        for lt in left:
            matched = False
            for ri, rt in enumerate(right):
                if jt == ast.JoinType.CROSS:
                    ok = True
                else:
                    probe = JoinTuple(tuples=list(lt.tuples) + widen(rt))
                    ok = self.ev.eval_condition(join.on, probe)
                if ok:
                    matched = True
                    matched_right.add(ri)
                    out.append(JoinTuple(tuples=list(lt.tuples) + widen(rt)))
            if not matched and jt in (ast.JoinType.LEFT, ast.JoinType.FULL):
                out.append(JoinTuple(tuples=list(lt.tuples)))
        if jt in (ast.JoinType.RIGHT, ast.JoinType.FULL):
            for ri, rt in enumerate(right):
                if ri not in matched_right:
                    out.append(JoinTuple(tuples=widen(rt)))
        return out


class LookupJoinNode(Node):
    """Stream-to-lookup-table join with per-key TTL cache."""

    def __init__(
        self, name: str, lookup_source, join: ast.Join,
        key_fields: List[PyTuple[str, str]],  # (stream_field, table_field)
        cache_ttl_ms: int = 60_000, **kw,
    ) -> None:
        super().__init__(name, op_type="op", **kw)
        self.lookup = lookup_source
        self.join_def = join
        self.key_fields = key_fields
        self.cache_ttl = cache_ttl_ms
        self._cache: Dict[Any, PyTuple[int, List[Dict[str, Any]]]] = {}
        self.ev = Evaluator()

    def on_open(self) -> None:
        self.lookup.open()

    def on_close(self) -> None:
        self.lookup.close()

    def process(self, item: Any) -> None:
        rows: List[Row]
        if isinstance(item, ColumnBatch):
            rows = item.to_tuples()
        elif isinstance(item, WindowTuples):
            rows = item.rows()
        elif isinstance(item, Row):
            rows = [item]
        else:
            self.emit(item)
            return
        out: List[JoinTuple] = []
        table = self.join_def.table.ref_name
        for r in rows:
            values = []
            for sf, _tf in self.key_fields:
                v, _ = r.value(sf)
                values.append(v)
            key = tuple(values)
            hit = self._cache.get(key)
            now = timex.now_ms()
            if hit is not None and now - hit[0] < self.cache_ttl:
                matches = hit[1]
            else:
                matches = self.lookup.lookup(
                    [], [tf for _sf, tf in self.key_fields], values
                )
                self._cache[key] = (now, matches)
            if matches:
                for m in matches:
                    out.append(JoinTuple(tuples=[
                        r if isinstance(r, Tuple) else Tuple(message=r.all_values()),
                        Tuple(emitter=table, message=m),
                    ]))
            elif self.join_def.join_type == ast.JoinType.LEFT:
                out.append(JoinTuple(tuples=[
                    r if isinstance(r, Tuple) else Tuple(message=r.all_values())
                ]))
        for jt in out:
            self.emit(jt)
