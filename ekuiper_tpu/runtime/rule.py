"""Rule lifecycle FSM — analogue of eKuiper's rule.State
(internal/topo/rule/state.go:76-575): Starting/Running/Stopping/Stopped
with a serialized action queue, restart strategy with exponential backoff +
jitter, and per-rule status/metrics aggregation.
"""
from __future__ import annotations

import queue
import random
import threading
from enum import Enum
from typing import Any, Dict, Optional

from ..planner.planner import RuleDef, plan_rule
from ..utils import timex
from ..utils.infra import logger
from .topo import Topo


class RunState(str, Enum):
    STOPPED = "stopped"
    STARTING = "starting"
    RUNNING = "running"
    STOPPING = "stopping"
    STOPPED_BY_ERR = "stopped_by_error"


class RuleState:
    def __init__(self, rule: RuleDef, store) -> None:
        self.rule = rule
        self.store = store
        self.state = RunState.STOPPED
        self.topo: Optional[Topo] = None
        self.last_error: str = ""
        self.started_at = 0
        self._lock = threading.RLock()
        self._actions: "queue.Queue[str]" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._supervisor: Optional[threading.Thread] = None
        self._stop_supervision = threading.Event()

    # --------------------------------------------------------------- actions
    def start(self) -> None:
        self._enqueue("start")

    def stop(self) -> None:
        self._enqueue("stop")

    def restart(self) -> None:
        self._enqueue("stop")
        self._enqueue("start")

    def _enqueue(self, action: str) -> None:
        self._actions.put(action)
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._drain_actions, daemon=True,
                    name=f"rule-{self.rule.id}",
                )
                self._worker.start()

    def _drain_actions(self) -> None:
        while True:
            try:
                action = self._actions.get(timeout=0.5)
            except queue.Empty:
                return
            try:
                if action == "start":
                    self._do_start()
                elif action == "stop":
                    self._do_stop()
            except Exception as exc:
                logger.error("rule %s action %s failed: %s", self.rule.id, action, exc)
                with self._lock:
                    self.state = RunState.STOPPED_BY_ERR
                    self.last_error = str(exc)

    # ------------------------------------------------------------- transitions
    def _do_start(self) -> None:
        with self._lock:
            if self.state in (RunState.RUNNING, RunState.STARTING):
                return
            self.state = RunState.STARTING
        topo = plan_rule(self.rule, self.store)
        topo.open()
        with self._lock:
            self.topo = topo
            self.state = RunState.RUNNING
            self.started_at = timex.now_ms()
            self.last_error = ""
        self._stop_supervision.clear()
        self._supervisor = threading.Thread(
            target=self._supervise, daemon=True,
            name=f"rule-supervisor-{self.rule.id}",
        )
        self._supervisor.start()

    def _do_stop(self) -> None:
        with self._lock:
            if self.state in (RunState.STOPPED, RunState.STOPPING):
                if self.state == RunState.STOPPED:
                    return
            self.state = RunState.STOPPING
        self._stop_supervision.set()
        if self.topo is not None:
            try:
                self.topo.save_state_now()
            except Exception as exc:
                logger.debug("save state on stop failed: %s", exc)
            self.topo.close()
        with self._lock:
            self.topo = None
            self.state = RunState.STOPPED

    # ------------------------------------------------------------- supervision
    def _supervise(self) -> None:
        """Watch the topo error channel, apply the restart strategy
        (reference: state.go:498-575 runTopo)."""
        opts = self.rule.options.get("restartStrategy", {})
        attempts = int(opts.get("attempts", 0))
        delay = int(opts.get("delay", 1000))
        max_delay = int(opts.get("maxDelay", 30_000))
        multiplier = float(opts.get("multiplier", 2.0))
        jitter = float(opts.get("jitterFactor", 0.1))
        tried = 0
        cur_delay = delay
        while not self._stop_supervision.is_set():
            topo = self.topo
            if topo is None:
                return
            err = topo.wait_error(timeout=0.5)
            if err is None:
                continue
            logger.error("rule %s runtime error: %s", self.rule.id, err)
            with self._lock:
                self.last_error = str(err)
            if tried >= attempts:
                with self._lock:
                    self.state = RunState.STOPPED_BY_ERR
                topo.close()
                with self._lock:
                    self.topo = None
                return
            tried += 1
            topo.close()
            sleep_ms = int(cur_delay * (1 + random.uniform(-jitter, jitter)))
            timex.sleep(max(sleep_ms, 0))
            cur_delay = min(int(cur_delay * multiplier), max_delay)
            try:
                new_topo = plan_rule(self.rule, self.store)
                new_topo.open()
                with self._lock:
                    self.topo = new_topo
                    self.state = RunState.RUNNING
            except Exception as exc:
                with self._lock:
                    self.state = RunState.STOPPED_BY_ERR
                    self.last_error = str(exc)
                return

    # ----------------------------------------------------------------- status
    def status(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "status": self.state.value,
            }
            if self.last_error:
                out["message"] = self.last_error
            if self.topo is not None and self.state == RunState.RUNNING:
                out.update(self.topo.status())
            return out
