"""Rule lifecycle FSM — analogue of eKuiper's rule.State
(internal/topo/rule/state.go:76-575): Starting/Running/Stopping/Stopped
with a serialized action queue, restart strategy with exponential backoff +
jitter, and per-rule status/metrics aggregation.
"""
from __future__ import annotations

import queue
import random
import threading
from enum import Enum
from typing import Any, Dict, Optional

from ..planner.planner import RuleDef, plan_rule
from ..utils import timex
from ..utils.infra import logger
from .topo import Topo


class RunState(str, Enum):
    STOPPED = "stopped"
    STARTING = "starting"
    RUNNING = "running"
    STOPPING = "stopping"
    STOPPED_BY_ERR = "stopped_by_error"
    # cron/duration rules between activations (reference schedule states,
    # internal/pkg/schedule + def/rule.go:40-42)
    SCHEDULED = "stopped: waiting for next schedule"


class RuleState:
    def __init__(self, rule: RuleDef, store) -> None:
        self.rule = rule
        self.store = store
        self.state = RunState.STOPPED
        self.topo: Optional[Topo] = None
        self.last_error: str = ""
        self.started_at = 0
        self._lock = threading.RLock()
        # worker-spawn guard, SEPARATE from self._lock: _enqueue runs
        # inside timex timer callbacks, which the mock clock fires while
        # holding the clock lock — and self._lock is held elsewhere
        # while reading the clock (_set_state -> flight recorder), so
        # taking self._lock here would close the clock/rule ABBA square
        # utils/lockcheck.py caught on day one (clock orders first)
        self._worker_mu = threading.Lock()
        self._actions: "queue.Queue[str]" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._supervisor: Optional[threading.Thread] = None
        self._stop_supervision = threading.Event()
        # schedule options (reference def/rule.go Cron/Duration/...Range)
        from ..utils import cron as cronlib

        self._cron = None
        self._duration_ms = 0
        self._ranges = rule.options.get("cronDatetimeRange") or []
        if rule.options.get("cron"):
            self._cron = cronlib.Cron(str(rule.options["cron"]))
        if rule.options.get("duration"):
            self._duration_ms = cronlib.parse_duration_ms(
                rule.options["duration"])
        if self._cron is not None and self._duration_ms <= 0:
            raise ValueError("cron rules require a duration")
        self._sched_timer = None
        self._sched_gen = 0  # invalidates stale timers after a user stop

    def _set_state(self, st: RunState, reason: str = "") -> None:
        """Every FSM transition goes through here so the flight recorder
        (runtime/events.py) keeps a replayable state history per rule —
        callers hold self._lock or run on the serialized action worker."""
        prev = self.state
        self.state = st
        if prev is not st:
            from .events import recorder

            recorder().record(
                "rule_state", rule=self.rule.id,
                severity=("error" if st is RunState.STOPPED_BY_ERR
                          else "info"),
                state=st.value, previous=prev.value,
                **({"reason": reason} if reason else {}))

    # --------------------------------------------------------------- actions
    def start(self) -> None:
        self._enqueue("start")

    def stop(self) -> None:
        self._enqueue("stop")

    def restart(self) -> None:
        self._enqueue("stop")
        self._enqueue("start")

    def _enqueue(self, action: str) -> None:
        self._actions.put(action)
        with self._worker_mu:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._drain_actions, daemon=True,
                    name=f"rule-{self.rule.id}",
                )
                self._worker.start()

    def _drain_actions(self) -> None:
        from ..utils.rulelog import set_rule_context

        set_rule_context(self.rule.id)
        while True:
            try:
                action = self._actions.get(timeout=0.5)
            except queue.Empty:
                return
            try:
                if action == "start":
                    self._do_start()
                elif action == "stop":
                    self._do_stop()
                elif action.startswith("cron_fire:"):
                    self._do_cron_fire(int(action.split(":", 1)[1]))
                elif action.startswith("cron_expire:"):
                    self._do_cron_expire(int(action.split(":", 1)[1]))
            except Exception as exc:
                logger.error("rule %s action %s failed: %s", self.rule.id, action, exc)
                with self._lock:
                    self._set_state(RunState.STOPPED_BY_ERR, reason=str(exc))
                    self.last_error = str(exc)

    # ------------------------------------------------------------- transitions
    def _do_start(self) -> None:
        with self._lock:
            if self.state in (RunState.RUNNING, RunState.STARTING):
                return
            self._set_state(RunState.STARTING)
        if self._cron is not None:
            self._schedule_next_fire()
            return
        self._open_topo()
        if self._duration_ms > 0:
            # duration-only: run once for the duration, then stop
            gen = self._sched_gen
            self._sched_timer = timex.after(
                self._duration_ms,
                lambda ts: self._enqueue(f"cron_expire:{gen}"))

    def _schedule_next_fire(self) -> None:
        now = timex.now_ms()
        fire_at = self._cron.next_fire_ms(now)
        gen = self._sched_gen
        with self._lock:
            self._set_state(RunState.SCHEDULED)
        self._sched_timer = timex.after(
            fire_at - now, lambda ts: self._enqueue(f"cron_fire:{gen}"))

    def _do_cron_fire(self, gen: int) -> None:
        from ..utils import cron as cronlib

        if gen != self._sched_gen:
            return  # stale timer from before a user stop
        if self.state != RunState.SCHEDULED:
            return
        if not cronlib.in_ranges(timex.now_ms(), self._ranges):
            self._schedule_next_fire()
            return
        self._open_topo()
        self._sched_timer = timex.after(
            self._duration_ms, lambda ts: self._enqueue(f"cron_expire:{gen}"))

    def _do_cron_expire(self, gen: int) -> None:
        if gen != self._sched_gen:
            return
        self._close_topo()
        if self._cron is not None:
            self._schedule_next_fire()
        else:
            with self._lock:
                self._set_state(RunState.STOPPED)

    def _open_topo(self) -> None:
        with self._lock:
            if self.state == RunState.RUNNING:
                return
            self._set_state(RunState.STARTING)
        topo = plan_rule(self.rule, self.store)
        topo.open()
        now = timex.now_ms()  # before the lock — clock orders first
        with self._lock:
            self.topo = topo
            self._set_state(RunState.RUNNING)
            self.started_at = now
            self.last_error = ""
        self._stop_supervision.clear()
        self._supervisor = threading.Thread(
            target=self._supervise, daemon=True,
            name=f"rule-supervisor-{self.rule.id}",
        )
        self._supervisor.start()

    def _close_topo(self) -> None:
        self._stop_supervision.set()
        if self.topo is not None:
            try:
                self.topo.save_state_now()
            except Exception as exc:
                logger.debug("save state on stop failed: %s", exc)
            self.topo.close()
        with self._lock:
            self.topo = None

    def _do_stop(self) -> None:
        with self._lock:
            if self.state == RunState.STOPPED:
                return
            self._set_state(RunState.STOPPING)
        self._sched_gen += 1  # invalidate in-flight schedule timers
        if self._sched_timer is not None:
            self._sched_timer.stop()
            self._sched_timer = None
        self._close_topo()
        with self._lock:
            self._set_state(RunState.STOPPED)

    # ------------------------------------------------------------- supervision
    def _supervise(self) -> None:
        """Watch the topo error channel, apply the restart strategy
        (reference: state.go:498-575 runTopo)."""
        from ..utils.rulelog import set_rule_context

        set_rule_context(self.rule.id)
        opts = self.rule.options.get("restartStrategy", {})
        attempts = int(opts.get("attempts", 0))
        delay = int(opts.get("delay", 1000))
        max_delay = int(opts.get("maxDelay", 30_000))
        multiplier = float(opts.get("multiplier", 2.0))
        jitter = float(opts.get("jitterFactor", 0.1))
        tried = 0
        cur_delay = delay
        while not self._stop_supervision.is_set():
            topo = self.topo
            if topo is None:
                return
            err = topo.wait_error(timeout=0.5)
            if err is None:
                continue
            logger.error("rule %s runtime error: %s", self.rule.id, err)
            with self._lock:
                self.last_error = str(err)
            if tried >= attempts:
                with self._lock:
                    self._set_state(RunState.STOPPED_BY_ERR,
                                    reason=str(err))
                topo.close()
                with self._lock:
                    self.topo = None
                return
            tried += 1
            topo.close()
            sleep_ms = int(cur_delay * (1 + random.uniform(-jitter, jitter)))
            timex.sleep(max(sleep_ms, 0))
            cur_delay = min(int(cur_delay * multiplier), max_delay)
            try:
                new_topo = plan_rule(self.rule, self.store)
                new_topo.open()
                with self._lock:
                    self.topo = new_topo
                    self._set_state(RunState.RUNNING,
                                    reason="restart strategy")
            except Exception as exc:
                with self._lock:
                    self._set_state(RunState.STOPPED_BY_ERR,
                                    reason=str(exc))
                    self.last_error = str(exc)
                return

    # ----------------------------------------------------------------- status
    def status(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "status": self.state.value,
            }
            if self.last_error:
                out["message"] = self.last_error
            if self.topo is not None and self.state == RunState.RUNNING:
                out.update(self.topo.status())
            return out
