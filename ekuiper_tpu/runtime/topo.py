"""Per-rule topology — analogue of eKuiper's Topo (internal/topo/topo.go:46-318):
owns the node DAG, opens sinks→ops→sources, drains errors, coordinates
checkpoints, and persists/restores state through the rule's KV store.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List, Optional

from ..observability.histogram import LatencyHistogram
from ..store import kv
from ..utils import timex
from ..utils.infra import logger
from ..utils.metrics import flatten_status
from .events import Barrier
from .node import Node


class Topo:
    def __init__(self, rule_id: str, qos: int = 0, checkpoint_interval_ms: int = 300_000) -> None:
        self.rule_id = rule_id
        self.qos = qos
        self.checkpoint_interval_ms = checkpoint_interval_ms
        self.sources: List[Node] = []
        self.ops: List[Node] = []
        self.sinks: List[Node] = []
        # (SubTopoRef, entry node) pairs — shared sources this rule rides;
        # the live SrcSubTopo instances are resolved at open() time
        self.shared: List = []
        self._live_shared: List = []
        self.errq: "queue.Queue[BaseException]" = queue.Queue(maxsize=8)
        self._open = False
        self._ckpt_timer = None
        self._ckpt_id = 0
        self._ckpt_lock = threading.Lock()
        self._ckpt_pending: Dict[int, Dict[str, Optional[dict]]] = {}
        self._store = None
        # rule-level ingest→emit latency distribution (ms): sinks record a
        # sample per delivered emission (nodes_sink.py _observe_e2e); the
        # Prometheus layer exports it as the kuiper_rule_e2e_latency_ms
        # histogram, the status JSON as a p50/p90/p99/max summary
        self.e2e_hist = LatencyHistogram()

    # ------------------------------------------------------------------ wiring
    def add_source(self, node: Node) -> Node:
        node._topo = self
        node.stats.rule_id = self.rule_id
        self.sources.append(node)
        return node

    def add_op(self, node: Node) -> Node:
        node._topo = self
        node.stats.rule_id = self.rule_id
        self.ops.append(node)
        return node

    def add_sink(self, node: Node) -> Node:
        node._topo = self
        node.stats.rule_id = self.rule_id
        self.sinks.append(node)
        return node

    def add_shared_source(self, ref, entry: Node) -> Node:
        """Ride a pooled shared source (runtime/subtopo.py SubTopoRef);
        `entry` is this rule's pass-through attach point (must also be
        add_op'd). The live instance is resolved when the topo opens."""
        self.shared.append((ref, entry))
        return entry

    def all_nodes(self) -> List[Node]:
        return self.sources + self.ops + self.sinks

    def live_shared(self) -> List:
        """(SrcSubTopo, entry node) pairs this rule currently rides — the
        public accessor for observability layers (scrapes must not reach
        into the private open()/close()-managed list)."""
        return list(self._live_shared)

    def entry_nodes(self) -> List[Node]:
        """This rule's first OWN nodes on the data path: the attach
        points of shared sources plus every direct consumer of a private
        source. The QoS control plane installs per-rule shed gates here —
        upstream of them sits shared (multi-rule) or connector-owned
        work, downstream is all this rule's private pipeline, so a gate
        at the entry sheds exactly one rule's input."""
        out: List[Node] = []
        seen: set = set()
        for _ref, entry in self.shared:
            if id(entry) not in seen:
                seen.add(id(entry))
                out.append(entry)
        for src in self.sources:
            for n in src.outputs:
                if id(n) not in seen:
                    seen.add(id(n))
                    out.append(n)
        return out

    def set_shed(self, fraction: float) -> None:
        """Install (or clear, fraction=0) the rule-scoped shed gate on
        every entry node (runtime/control.py SLO-driven shedding)."""
        for node in self.entry_nodes():
            node.set_shed_fraction(fraction)

    def shed_fraction(self) -> float:
        """The currently installed shed fraction (max across entries)."""
        return max((n._shed_frac for n in self.entry_nodes()),
                   default=0.0)

    def shed_rows(self) -> int:
        """Rows discarded by the shed gate so far (reason="shed_qos"
        across entry nodes) — the control plane's per-rule counter."""
        return sum(n.stats.dropped.get("shed_qos", 0)
                   for n in self.entry_nodes())

    def observe_e2e(self, lat_ms: int) -> None:
        """One ingest→emit latency sample (ms), recorded by sink nodes."""
        self.e2e_hist.record(lat_ms)

    # --------------------------------------------------------------- lifecycle
    def open(self) -> None:
        """Start sinks → ops → sources (reference order, topo.go:275-318),
        restore checkpointed state, then activate checkpointing if QoS>0."""
        if self.qos > 0:
            self._store = kv.get_store().kv(f"checkpoint:{self.rule_id}")
            self._restore()
        if self.qos >= 2:
            # exactly-once: data items carry their sender so fan-in nodes
            # can hold back barriered edges (node.py _handle_barrier)
            for node in self.all_nodes():
                node._tag_data = True
        for node in self.sinks + self.ops + self.sources:
            node.open()
        self._live_shared = [
            (ref.resolve_and_attach(self.rule_id, entry, self), entry)
            for ref, entry in self.shared
        ]
        self._open = True
        if self.qos > 0:
            self._schedule_checkpoint()

    def close(self) -> None:
        self._open = False
        if self._ckpt_timer is not None:
            self._ckpt_timer.stop()
        for subtopo, _ in self._live_shared:
            subtopo.detach(self.rule_id)
        self._live_shared = []
        for node in self.sources + self.ops + self.sinks:
            node.close()
        for node in self.all_nodes():
            node.join(timeout=2.0)

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until every node's input queue is drained AND no node is
        mid-dispatch (queue.unfinished_tasks == 0). Emissions happen while the
        emitting node's task is still unfinished, so a snapshot where all
        counts are zero means no data is in flight anywhere in the DAG.
        Deterministic replacement for sleep()-based settling in tests."""
        import time as _time

        deadline = _time.perf_counter() + timeout
        # shared-subtopo nodes (the physical source + its decode ring) count
        # too: data sitting there is still in flight toward this rule
        nodes = self.all_nodes() + [
            n for st, _ in self._live_shared for n in st.nodes]
        while _time.perf_counter() < deadline:
            if all(n.inq.unfinished_tasks == 0 and n.extra_pending() == 0
                   for n in nodes):
                return True
            # kuiperlint: ignore[clock-discipline]: real-thread poll — worker queues drain in wall time even when the engine clock is mocked
            _time.sleep(0.002)
        return False

    def drain_error(self, err: BaseException, origin: str = "") -> None:
        logger.error("rule %s node %s failed: %s", self.rule_id, origin, err)
        try:
            self.errq.put_nowait(err)
        except queue.Full:
            pass

    def wait_error(self, timeout: Optional[float] = None) -> Optional[BaseException]:
        try:
            return self.errq.get(timeout=timeout)
        except queue.Empty:
            return None

    # ------------------------------------------------------------- status JSON
    def status(self) -> Dict[str, Any]:
        stats = {n.name: n.stats for n in self.all_nodes()}
        for subtopo, _ in self._live_shared:
            # shared ingest pipelines serve this rule too; surface their
            # metrics under the rule status like the reference does for
            # shared source instances
            for name, sm in subtopo.status().items():
                stats.setdefault(name, sm)
        out = flatten_status(stats)
        # rule-level SLO summary: the ingest→emit distribution percentiles
        out["e2e_latency_ms"] = self.e2e_hist.snapshot()
        # engine-health views (observability/devwatch.py): per-op XLA
        # trace-vs-cache-hit counts — a steady-state rule should show
        # compiles flat while cache_hits climb; anything else is paying
        # compile latency per batch
        from ..observability import devwatch

        xla = devwatch.registry().rule_status(self.rule_id)
        if xla:
            out["xla_compile"] = xla
        # device-time split (observability/kernwatch.py): the rule's
        # sampled host-dispatch vs device-compute time and per-kernel
        # roofline utilization — the device-side twin of the host stage
        # timings above
        from ..observability import kernwatch

        kern = kernwatch.rule_status(self.rule_id)
        if kern:
            out["device_time"] = kern
        # health-plane verdict (observability/health.py), when the
        # evaluator has one — last verdict only, a status call must not
        # pay evaluation cost
        from ..observability import health

        verdict = health.rule_verdict(self.rule_id)
        if verdict is not None:
            out["health"] = verdict
        return out

    def topo_json(self) -> Dict[str, Any]:
        edges: Dict[str, List[str]] = {}
        for n in self.all_nodes():
            edges[n.name] = [o.name for o in n.outputs]
        return {
            "sources": [n.name for n in self.sources],
            "edges": edges,
        }

    # -------------------------------------------------------------- checkpoint
    def _schedule_checkpoint(self) -> None:
        def fire(ts: int) -> None:
            if not self._open:
                return
            self.trigger_checkpoint()
            self._schedule_checkpoint()

        self._ckpt_timer = timex.after(self.checkpoint_interval_ms, fire)

    def trigger_checkpoint(self) -> int:
        """Inject barriers at sources (coordinator.go:236-324)."""
        with self._ckpt_lock:
            self._ckpt_id += 1
            cid = self._ckpt_id
            self._ckpt_pending[cid] = {}
        barrier = Barrier(checkpoint_id=cid, qos=self.qos)
        for src in self.sources:
            src.put(barrier)
        return cid

    def checkpoint_ack(self, node_name: str, barrier: Barrier, state: Optional[dict]) -> None:
        """Task snapshot ack; completes the checkpoint when all stateful
        nodes have answered (coordinator.go:93-171)."""
        with self._ckpt_lock:
            pend = self._ckpt_pending.get(barrier.checkpoint_id)
            if pend is None:
                return
            pend[node_name] = state
            expected = {n.name for n in self.all_nodes()}
            if set(pend.keys()) >= expected:
                states = {k: v for k, v in pend.items() if v is not None}
                del self._ckpt_pending[barrier.checkpoint_id]
                if self._store is not None:
                    self._store.set("latest", {
                        "checkpoint_id": barrier.checkpoint_id,
                        "states": states,
                    })
                logger.debug(
                    "rule %s checkpoint %d complete (%d stateful nodes)",
                    self.rule_id, barrier.checkpoint_id, len(states),
                )

    def _restore(self) -> None:
        snap, ok = self._store.get_ok("latest")
        if not ok or not snap:
            return
        states = snap.get("states", {})
        by_name = {n.name: n for n in self.all_nodes()}
        for name, state in states.items():
            node = by_name.get(name)
            if node is not None:
                node.restore_state(state)
        self._ckpt_id = snap.get("checkpoint_id", 0)

    def save_state_now(self) -> None:
        """Force-save without barriers (EnableSaveStateBeforeStop,
        topo.go:113-120) — used on graceful stop."""
        if self._store is None:
            return
        states = {}
        for node in self.all_nodes():
            try:
                s = node.snapshot_state()
            except Exception as exc:
                # one wedged node (e.g. bounded async-emit drain timeout)
                # must not discard every OTHER node's state — notably a
                # memory-only CacheNode whose pending at-least-once sink
                # payloads persist only through this snapshot
                logger.error("%s: stop-time snapshot failed (%s) — saving "
                             "the other nodes' state", node.name, exc)
                node.stats.inc_exception(f"stop snapshot failed: {exc}")
                continue
            if s is not None:
                states[node.name] = s
        with self._ckpt_lock:
            self._ckpt_id += 1
            self._store.set("latest", {
                "checkpoint_id": self._ckpt_id, "states": states,
            })
