"""Sharded ingest decode pool — the host-side half of the bytes-in hot path.

Full-pipe ingest was GIL-bound on ONE thread doing decode -> batch build ->
emit while the fused node's worker did upload -> fold: under concurrent CPU
load the decode convoyed and throughput halved (VERDICT r5 weak #3). The
pool moves decode off the connector thread:

- the source's raw flush submits (payloads, timestamps) jobs here instead
  of decoding inline; the connector callback returns immediately;
- N workers decode concurrently — the native parse additionally fans each
  job across GIL-free C shards (native/jsoncol.cpp), so one big drain
  parallelizes even when only one job is in flight;
- results emit IN SUBMIT ORDER through a bounded ring (depth
  `ingest_ring_depth`, default 2): decode of batch k+1 overlaps the
  host->device upload+fold of batch k, and a full ring blocks `submit`,
  which is the backpressure toward the broker drain.

Ordering contract: emission order == submission order, always — the pool
is invisible to everything downstream except for the added pipelining.
`drain()` blocks until every submitted job has emitted; the source calls it
on final flushes (EOF/close) so batches never trail stream-end events.

Round 7 adds the `upload` stage: the ring drainer runs prepare_fn on each
result IN SUBMIT ORDER just before emitting it — the source wires this to
IngestPrepCtx.precompute, which key-slot-encodes the batch (native C table,
ops/keytable.py) and pre-pads + device_puts the kernel inputs under the
SAME share keys the fused node's _shared_device_inputs uses. A batch thus
arrives at the fused worker already slot-encoded and already resident on
device: H2D of batch k+1 overlaps the fold dispatch of batch k, and the
fused worker's own `upload` stage collapses to cache lookups. Running the
encode on the ordered drain (not on whichever worker finishes first) keeps
slot numbering, emitted group order, and checkpoint key order exactly what
the inline path produces — the pool stays invisible downstream.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Any, Callable, Dict, Optional, Tuple

from ..utils.infra import logger


class DecodePool:
    """Fixed worker pool with strictly ordered emission.

    decode_fn(job) -> result | None   runs on a worker thread (must be
                                      thread-safe; None = nothing to emit)
    emit_fn(result)                   called in submit order; at most one
                                      thread emits at any time
    prepare_fn(result)                optional post-decode stage run by the
                                      drainer IN SUBMIT ORDER just before
                                      each emit (the pipelined upload
                                      stage; ordered so key-slot
                                      assignment stays deterministic)
    """

    def __init__(self, size: int, ring_depth: int, decode_fn: Callable,
                 emit_fn: Callable, name: str = "ingest",
                 prepare_fn: Optional[Callable] = None,
                 stats=None) -> None:
        self.size = max(1, int(size))
        self.ring_depth = max(1, int(ring_depth))
        self._name = name
        self._decode = decode_fn
        self._emit = emit_fn
        self._prepare = prepare_fn
        # optional StatManager: the drainer accrues each job's
        # decoded→emitted dwell to a "ring" stage — time a READY result
        # waited for its emission turn (stamping at submit would fold the
        # decode work, already accrued to "decode", in again and misstate
        # the pipeline balance)
        self._stats = stats
        self._ready_ts: Dict[int, float] = {}  # seq -> result-deposit time
        # memory accounting: decoded batches parked in the ring awaiting
        # their emission turn hold host columns alive — a visible
        # component row, not a mystery RSS bump (probe runs at scrape
        # time only; ring depth is small so the walk is a few dicts)
        from ..observability import memwatch

        memwatch.register("decode_ring", self, DecodePool._ring_bytes)
        self._lock = threading.Lock()
        self._job_ready = threading.Condition(self._lock)
        self._slot_free = threading.Condition(self._lock)
        self._drained = threading.Condition(self._lock)
        self._jobs: list = []  # [(seq, job)] pending pickup
        self._results: dict = {}  # seq -> result, decoded awaiting its turn
        self._next_seq = 0  # next submit() sequence number
        self._emit_seq = 0  # next sequence to emit
        self._in_flight = 0  # submitted - emitted
        self._emitting = False  # one drainer at a time keeps order total
        self._closed = False
        self._threads = [
            threading.Thread(target=self._worker, args=(i,), daemon=True,
                             name=f"{name}-decode-{i}")
            for i in range(self.size)
        ]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------------ api
    @property
    def in_flight(self) -> int:
        """Jobs submitted but not yet emitted (ring occupancy)."""
        with self._lock:
            return self._in_flight

    @property
    def queue_depth(self) -> int:
        """Jobs submitted but not yet picked up by a worker — sustained
        nonzero means decode is the bottleneck, not the ring."""
        with self._lock:
            return len(self._jobs)

    def submit(self, job: Any) -> None:
        """Queue a decode job; blocks while the ring is full (backpressure).
        Raises RuntimeError after close()."""
        with self._lock:
            if self._closed:
                raise RuntimeError("decode pool is closed")
            while self._in_flight >= self.ring_depth and not self._closed:
                self._slot_free.wait(timeout=1.0)
            if self._closed:
                raise RuntimeError("decode pool is closed")
            self._jobs.append((self._next_seq, job))
            self._next_seq += 1
            self._in_flight += 1
            self._job_ready.notify()

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        """Block until every submitted job has emitted. Returns False on
        timeout (a wedged decode must not hang EOF/close forever)."""
        deadline = None if timeout is None else _time.perf_counter() + timeout
        with self._lock:
            while self._in_flight > 0:
                remaining = (None if deadline is None
                             else deadline - _time.perf_counter())
                if remaining is not None and remaining <= 0:
                    return False
                self._drained.wait(timeout=remaining)
        return True

    def close(self, timeout: float = 5.0) -> None:
        self.drain(timeout=timeout)
        with self._lock:
            self._closed = True
            self._job_ready.notify_all()
            self._slot_free.notify_all()
        for t in self._threads:
            t.join(timeout=1.0)

    # ------------------------------------------------------------ autosize
    def resize(self, new_size: int) -> int:
        """Adjust the worker count (QoS auto-sizing, runtime/control.py).
        Growth spawns threads immediately; shrink retires the highest-
        indexed workers at their next wake (in-flight decodes finish —
        the ordering contract is untouched, only parallelism changes).
        Returns the applied size; a closed pool keeps its size."""
        new_size = max(1, int(new_size))
        with self._lock:
            if self._closed:
                return self.size
            old = self.size
            self.size = new_size
            if new_size < old:
                self._job_ready.notify_all()  # wake retirees
        for i in range(old, new_size):
            t = threading.Thread(target=self._worker, args=(i,),
                                 daemon=True,
                                 name=f"{self._name}-decode-{i}")
            self._threads.append(t)
            t.start()
        return new_size

    def set_ring_depth(self, depth: int) -> int:
        """Adjust the ordered-ring depth (QoS auto-sizing). A deeper ring
        lets decode run further ahead of upload+fold; a grown depth frees
        submitters currently blocked on the old bound."""
        with self._lock:
            self.ring_depth = max(1, int(depth))
            self._slot_free.notify_all()
            return self.ring_depth

    # -------------------------------------------------------------- worker
    def _worker(self, idx: int = 0) -> None:
        while True:
            with self._lock:
                while not self._jobs and not self._closed \
                        and idx < self.size:
                    self._job_ready.wait(timeout=1.0)
                if idx >= self.size and not self._jobs:
                    return  # retired by resize(); peers drain the queue
                if not self._jobs:
                    if self._closed:
                        return
                    continue
                seq, job = self._jobs.pop(0)
            try:
                result = self._decode(job)
            except Exception as exc:
                logger.warning("decode pool job failed: %s", exc)
                if self._stats is not None:
                    # the job's rows are gone: count the loss in the drop
                    # taxonomy, sized by the job's payload count (a job is
                    # a whole flush unit — (kind, items, tss); counting 1
                    # would understate the loss by the batch size). The
                    # per-payload decode errors inside a SURVIVING job are
                    # already counted by the decode_fn.
                    n_lost = 1
                    if (isinstance(job, tuple) and len(job) > 1
                            and hasattr(job[1], "__len__")):
                        n_lost = max(len(job[1]), 1)
                    self._stats.inc_dropped("decode_error", n=n_lost,
                                            detail="decode pool job failed")
                result = None
            self._finish(seq, result)

    def _ring_bytes(self) -> int:
        """Host bytes held by decoded-but-unemitted ring results."""
        with self._lock:
            results = list(self._results.values())
        total = 0
        for r in results:
            cols = getattr(r, "columns", None)
            if not cols:
                continue
            for arr in cols.values():
                nb = getattr(arr, "nbytes", 0)
                total += int(nb or 0)
        return total

    def _finish(self, seq: int, result: Any) -> None:
        """Deposit a finished decode; if the emit cursor's result is ready
        and nobody is draining, become the drainer. Emission runs OUTSIDE
        the lock (emit lands in the fused node's queue, which can block on
        backpressure) but the `_emitting` flag keeps it single-threaded, so
        order stays total."""
        with self._lock:
            self._results[seq] = result
            if self._stats is not None:
                self._ready_ts[seq] = _time.perf_counter()
            if self._emitting or self._emit_seq not in self._results:
                return
            self._emitting = True
        while True:
            with self._lock:
                if self._emit_seq not in self._results:
                    self._emitting = False
                    return
                head = self._results.pop(self._emit_seq)
                t_ready = self._ready_ts.pop(self._emit_seq, None)
                self._emit_seq += 1
            if t_ready is not None and self._stats is not None:
                self._stats.observe_stage(
                    "ring", (_time.perf_counter() - t_ready) * 1e6,
                    getattr(head, "n", 0) if head is not None else 0)
            try:
                if head is not None:
                    if self._prepare is not None:
                        # upload stage — INSIDE the ordered drain, so the
                        # key-slot encode assigns slots in submission order
                        # (worker-completion order would make slot
                        # numbering, emitted group order, and checkpoint
                        # key order nondeterministic run-to-run). Still
                        # off the fused worker: prepare of batch k+1 runs
                        # while the fused node folds batch k. A failure
                        # only loses the pre-compute — the fused node
                        # rebuilds inline, exactly as before.
                        try:
                            self._prepare(head)
                        except Exception as exc:
                            logger.warning(
                                "ingest prepare (upload) failed: %s", exc)
                    self._emit(head)
            except Exception as exc:
                logger.warning("decode pool emit failed: %s", exc)
            finally:
                with self._lock:
                    self._in_flight -= 1
                    self._slot_free.notify_all()
                    if self._in_flight == 0:
                        self._drained.notify_all()


def pad_col_for_device(host, vm, mb: int, dtype: str = "float32",
                       sharding=None):
    """Canonical pad + device upload for one kernel column — the ONE
    builder behind the share keys ("dcol", name, mb) and
    ("dexpr", expr_tag, name, mb). Both the prep ctx (pool-side
    pre-upload) and nodes_fused._shared_device_inputs (inline fallback)
    call this, so a cache hit can never serve a differently built array
    than the inline path would have made. `dtype` follows the plan's
    per-column map (ops/groupby.py col_np_dtype): float32 for plain
    numeric columns, int32 for the expression IR's derived columns.
    `sharding` (a jax NamedSharding — the sharded kernel's "rows" axis)
    places the padded array ACROSS the mesh so each shard's slice does
    its own H2D copy; such uploads live under mesh-tag-suffixed share
    keys and can never alias the replicated single-chip form."""
    import jax.numpy as jnp
    import numpy as np

    arr = np.asarray(host, dtype=np.dtype(dtype))
    if len(arr) < mb:
        arr = np.pad(arr, (0, mb - len(arr)))
    dm = None
    if vm is not None:
        m = vm if len(vm) == mb else np.pad(vm, (0, mb - len(vm)))
        dm = _put(m, sharding)
    return _put(arr, sharding), dm


def _put(arr, sharding):
    import jax
    import jax.numpy as jnp

    if sharding is None:
        return jnp.asarray(arr)
    return jax.device_put(arr, sharding)


def share_key(kind: str, *parts, mesh_tag: str = ""):
    """THE share-key builder for pre-padded device uploads — used by the
    prep ctx (pool side) AND both consumer twins
    (nodes_fused._shared_device_inputs, nodes_sharedfold._device_inputs)
    so producer and consumer can never drift to different keys: a miss
    means a silently duplicated upload, a half-match could serve a
    replicated array to a sharded consumer. Mesh-tagged keys get the
    tag suffix; un-tagged keys keep the historical tuple shape."""
    return (kind,) + parts + ((mesh_tag,) if mesh_tag else ())


def slot_wire_u16(capacity_u16: bool, mesh_tag: str) -> bool:
    """Slot wire dtype decision for shared uploads: uint16 only when the
    capacity allows AND the consumer is single-chip — sharded kernels
    always take int32 (the certified shard_map form)."""
    return bool(capacity_u16) and not mesh_tag


def pad_slots_for_device(slots, mb: int, u16: bool, sharding=None):
    """Canonical pad + dtype + upload for the slot vector — the ONE
    builder behind the share key ("dslots", key_name, mb, u16[, mesh]).
    Sharded consumers always pass u16=False (int32 is the certified
    shard_map wire dtype) plus their row sharding."""
    import numpy as np

    s = slots
    if len(s) < mb:
        s = np.pad(s, (0, mb - len(s)))
    return _put(s.astype(np.uint16 if u16 else np.int32), sharding)


class IngestPrepCtx:
    """Shared ingest prep + the pipelined upload stage.

    One of these rides every ColumnBatch (as `shared_ctx`) emitted by a
    prep-enabled source or shared subtopo. Two jobs:

    - `encode(batch, key_name)`: ONE group-key encode per batch for every
      fan-out consumer (the neutral KeyTable assigns dense
      insertion-ordered slots; a consumer feeding its own table the same
      key sequence via keys_slice gets identical ids). The table's hashed
      path rides the native C key-slot table (ops/keytable.py
      _native_encode) when the extension is present.

    - `precompute(batch)`: the upload stage, run by decode-pool workers.
      Consumers declare their kernel-input shape with `register_upload`;
      precompute then key-slot-encodes the batch and builds the padded
      float32 device columns + slot vector under the SAME share keys
      nodes_fused._shared_device_inputs memoizes on — so the fused worker
      finds everything cached and its per-batch `upload` stage collapses
      to dict lookups while H2D of batch k+1 overlapped fold of batch k.

    Capacity-grow signalling round-trips through the share-key scheme: the
    slot vector's key carries a u16 bit derived from the neutral table's
    capacity at encode time. When a grow crosses 65,535 the bit flips, so
    any in-flight batch pre-uploaded with the old dtype simply MISSES the
    fused node's cache lookup and is re-padded/re-uploaded there with the
    grown dtype (the grow itself re-specializes the fold executables).
    Slot VALUES are insertion-ordered and dense, so pre-encoded slots stay
    valid across grows — only the dtype choice is capacity-sensitive.
    """

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.key_tables: Dict[str, Any] = {}
        # (key_name|None, micro_batch, mesh_tag) -> {"columns": set,
        # "sharding": NamedSharding|None}; key_name None = columns-only
        # spec (multi-dim consumers); mesh_tag "" = single-chip uploads,
        # "RxK" = mesh-placed uploads under tag-suffixed share keys
        self._specs: Dict[Tuple[Optional[str], int, str], Dict[str, Any]] = {}
        # (expr_tag, micro_batch, mesh_tag) -> (DerivedCol tuple,
        # sharding|None) — expression-IR prep columns pre-encoded +
        # pre-uploaded by the pool, placed per the consumer's mesh
        self._derived: Dict[Tuple[str, int, str], tuple] = {}
        # tiered key state (ops/tierstore.py): prefetch hooks that spot
        # returning demoted keys in a decoding batch and start their
        # packed rows' H2D copy a batch early
        self._tier_hooks: List[Any] = []
        # telemetry: batches/columns pre-uploaded by the pool (bench + tests)
        self.n_precomputed = 0
        self.n_precomputed_cols = 0

    # ----------------------------------------------------------- encoding
    def encode(self, batch, key_name: str):
        """(slots int32, n_keys, kt) for `key_name` over `batch`, computed
        once per batch across all consumers."""
        def factory():
            import numpy as np

            from ..ops.keytable import KeyTable

            with self.lock:
                kt = self.key_tables.get(key_name)
                if kt is None:
                    kt = self.key_tables[key_name] = KeyTable()
                col = batch.columns.get(key_name)
                if col is None:
                    col = np.full(batch.n, None, dtype=np.object_)
                slots, _ = kt.encode_column(col)
                return slots, kt.n_keys, kt

        return batch.share(("slots", key_name), factory)

    # ------------------------------------------------------- upload stage
    def register_upload(self, key_name: Optional[str], columns,
                        micro_batch: int, derived=None, sharding=None,
                        mesh_tag: str = "") -> None:
        """A fused consumer declares what precompute() should build. Merged
        by (key_name, micro_batch, mesh_tag): heterogeneous consumers of
        one stream union their column needs — one upload serves all of
        them; mesh-sharded consumers register separately under their mesh
        tag with the row `sharding` their kernel folds from (per-shard
        H2D, nodes_fused.py prep_spec). `derived` is an optional
        (expr_tag, DerivedCol tuple): the consumer's expression-IR prep
        columns (sql/expr_ir.py), encoded + pre-uploaded under share keys
        that include the IR hash so two plans with different expressions
        can never alias an upload."""
        with self.lock:
            spec = self._specs.setdefault(
                (key_name, int(micro_batch), str(mesh_tag or "")),
                {"columns": set(), "sharding": sharding})
            spec["columns"].update(columns)
            if sharding is not None:
                spec["sharding"] = sharding
            if derived:
                tag, dcols = derived
                # derived uploads are mesh-scoped too: a sharded
                # consumer's ("dexpr", ..., mesh_tag) lookup must hit a
                # mesh-placed array, and the replicated form must not be
                # built for nobody
                self._derived[(tag, int(micro_batch),
                               str(mesh_tag or ""))] = (
                    tuple(dcols),
                    sharding if mesh_tag else None)

    def register_tier_prefetch(self, fn) -> None:
        """A tiered fused consumer's prefetch hook (TierManager.prefetch)
        — run per batch by precompute(), best-effort."""
        with self.lock:
            if fn not in self._tier_hooks:
                self._tier_hooks.append(fn)

    def precompute(self, batch) -> int:
        """Build padded device inputs for `batch` under the fused node's
        share keys. Returns the number of device arrays created. Failures
        are non-fatal: the fused node rebuilds anything missing inline."""
        import numpy as np

        with self.lock:
            specs = [(k, {"columns": set(v["columns"]),
                          "sharding": v.get("sharding")})
                     for k, v in self._specs.items()]
            derived = list(self._derived.items())
            tier_hooks = list(self._tier_hooks)
        if getattr(batch, "n", 0) == 0:
            return 0
        for hook in tier_hooks:
            # tiered prefetch: start returning demoted keys' packed-row
            # H2D early; a failure only loses the overlap — admit()
            # uploads inline exactly as without prefetch
            try:
                hook(batch)
            except Exception as exc:
                logger.warning("tier prefetch failed: %s", exc)
        if not specs and not derived:
            return 0
        try:
            import jax.numpy as jnp  # noqa: F401 — availability probe
        except Exception:
            return 0
        n_up = 0
        for (key_name, mb, mesh_tag), spec in specs:
            columns = spec["columns"]
            shd = spec.get("sharding") if mesh_tag else None
            if batch.n > mb:
                # multi-chunk batches can't ship as one pre-padded upload
                # (fold's device-input contract); source flushes are
                # micro-batch aligned so this is the rare tail only
                continue
            if key_name is not None:
                slots, n_keys, kt = self.encode(batch, key_name)
                from ..ops.groupby import slot_dtype

                with self.lock:
                    u16 = slot_wire_u16(
                        slot_dtype(kt.capacity) is np.uint16, mesh_tag)
                batch.share(share_key("dslots", key_name, mb, u16,
                                      mesh_tag=mesh_tag),
                            lambda s=slots, u=u16, m=mb, d=shd:
                            pad_slots_for_device(s, m, u, sharding=d))
                n_up += 1
            for name in sorted(columns):
                col = batch.columns.get(name)
                if col is None or col.dtype == np.object_:
                    continue  # fused node NaN-fills / coerces these itself
                vm = batch.valid.get(name)
                batch.share(share_key("dcol", name, mb,
                                      mesh_tag=mesh_tag),
                            lambda h=col, v=vm, m=mb, d=shd:
                            pad_col_for_device(h, v, m, sharding=d))
                n_up += 1
        for (tag, mb, mesh_tag), (dcols, dshd) in derived:
            if batch.n > mb:
                continue
            for d in dcols:
                # encode once per batch (shared across consumers with the
                # same IR — the host encode is placement-independent),
                # then pad+upload under the tagged share key with the
                # consumer's placement — the fused node's inline twin
                # uses the SAME builders and keys
                host = batch.share(
                    ("dexpr_host", tag, d.name),
                    lambda _d=d, _b=batch: _d.encode(
                        _b.columns.get(_d.raw), _b.n))
                batch.share(share_key("dexpr", tag, d.name, mb,
                                      mesh_tag=mesh_tag),
                            lambda h=host, m=mb, _dt=d.dtype, _s=dshd:
                            pad_col_for_device(h, None, m, dtype=_dt,
                                               sharding=_s))
                n_up += 1
        if n_up:
            with self.lock:
                self.n_precomputed += 1
                self.n_precomputed_cols += n_up
        return n_up
