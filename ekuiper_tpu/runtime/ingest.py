"""Sharded ingest decode pool — the host-side half of the bytes-in hot path.

Full-pipe ingest was GIL-bound on ONE thread doing decode -> batch build ->
emit while the fused node's worker did upload -> fold: under concurrent CPU
load the decode convoyed and throughput halved (VERDICT r5 weak #3). The
pool moves decode off the connector thread:

- the source's raw flush submits (payloads, timestamps) jobs here instead
  of decoding inline; the connector callback returns immediately;
- N workers decode concurrently — the native parse additionally fans each
  job across GIL-free C shards (native/jsoncol.cpp), so one big drain
  parallelizes even when only one job is in flight;
- results emit IN SUBMIT ORDER through a bounded ring (depth
  `ingest_ring_depth`, default 2): decode of batch k+1 overlaps the
  host->device upload+fold of batch k, and a full ring blocks `submit`,
  which is the backpressure toward the broker drain.

Ordering contract: emission order == submission order, always — the pool
is invisible to everything downstream except for the added pipelining.
`drain()` blocks until every submitted job has emitted; the source calls it
on final flushes (EOF/close) so batches never trail stream-end events.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Any, Callable, Optional

from ..utils.infra import logger


class DecodePool:
    """Fixed worker pool with strictly ordered emission.

    decode_fn(job) -> result | None   runs on a worker thread (must be
                                      thread-safe; None = nothing to emit)
    emit_fn(result)                   called in submit order; at most one
                                      thread emits at any time
    """

    def __init__(self, size: int, ring_depth: int, decode_fn: Callable,
                 emit_fn: Callable, name: str = "ingest") -> None:
        self.size = max(1, int(size))
        self.ring_depth = max(1, int(ring_depth))
        self._decode = decode_fn
        self._emit = emit_fn
        self._lock = threading.Lock()
        self._job_ready = threading.Condition(self._lock)
        self._slot_free = threading.Condition(self._lock)
        self._drained = threading.Condition(self._lock)
        self._jobs: list = []  # [(seq, job)] pending pickup
        self._results: dict = {}  # seq -> result, decoded awaiting its turn
        self._next_seq = 0  # next submit() sequence number
        self._emit_seq = 0  # next sequence to emit
        self._in_flight = 0  # submitted - emitted
        self._emitting = False  # one drainer at a time keeps order total
        self._closed = False
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"{name}-decode-{i}")
            for i in range(self.size)
        ]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------------ api
    @property
    def in_flight(self) -> int:
        """Jobs submitted but not yet emitted (ring occupancy)."""
        with self._lock:
            return self._in_flight

    def submit(self, job: Any) -> None:
        """Queue a decode job; blocks while the ring is full (backpressure).
        Raises RuntimeError after close()."""
        with self._lock:
            if self._closed:
                raise RuntimeError("decode pool is closed")
            while self._in_flight >= self.ring_depth and not self._closed:
                self._slot_free.wait(timeout=1.0)
            if self._closed:
                raise RuntimeError("decode pool is closed")
            self._jobs.append((self._next_seq, job))
            self._next_seq += 1
            self._in_flight += 1
            self._job_ready.notify()

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        """Block until every submitted job has emitted. Returns False on
        timeout (a wedged decode must not hang EOF/close forever)."""
        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._lock:
            while self._in_flight > 0:
                remaining = (None if deadline is None
                             else deadline - _time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._drained.wait(timeout=remaining)
        return True

    def close(self, timeout: float = 5.0) -> None:
        self.drain(timeout=timeout)
        with self._lock:
            self._closed = True
            self._job_ready.notify_all()
            self._slot_free.notify_all()
        for t in self._threads:
            t.join(timeout=1.0)

    # -------------------------------------------------------------- worker
    def _worker(self) -> None:
        while True:
            with self._lock:
                while not self._jobs and not self._closed:
                    self._job_ready.wait(timeout=1.0)
                if not self._jobs:
                    if self._closed:
                        return
                    continue
                seq, job = self._jobs.pop(0)
            try:
                result = self._decode(job)
            except Exception as exc:
                logger.warning("decode pool job failed: %s", exc)
                result = None
            self._finish(seq, result)

    def _finish(self, seq: int, result: Any) -> None:
        """Deposit a finished decode; if the emit cursor's result is ready
        and nobody is draining, become the drainer. Emission runs OUTSIDE
        the lock (emit lands in the fused node's queue, which can block on
        backpressure) but the `_emitting` flag keeps it single-threaded, so
        order stays total."""
        with self._lock:
            self._results[seq] = result
            if self._emitting or self._emit_seq not in self._results:
                return
            self._emitting = True
        while True:
            with self._lock:
                if self._emit_seq not in self._results:
                    self._emitting = False
                    return
                head = self._results.pop(self._emit_seq)
                self._emit_seq += 1
            try:
                if head is not None:
                    self._emit(head)
            except Exception as exc:
                logger.warning("decode pool emit failed: %s", exc)
            finally:
                with self._lock:
                    self._in_flight -= 1
                    self._slot_free.notify_all()
                    if self._in_flight == 0:
                        self._drained.notify_all()
