"""Events flowing between runtime nodes — analogue of the reference's
BufferOrEvent stream (data + barriers piggybacked on the same channels,
internal/topo/node/node.go:121-127).

Data travels as ColumnBatch (micro-batched columnar, the TPU-native form) or
as row collections (WindowTuples/GroupedTuplesSet) after windowing; control
events (barrier, watermark, EOF, window trigger) interleave in-band so
alignment semantics match the reference's checkpoint design.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class Barrier:
    """Checkpoint barrier (Chandy-Lamport aligned snapshot marker,
    reference: internal/topo/checkpoint/barrier_handler.go)."""

    checkpoint_id: int
    source_id: str = ""
    qos: int = 1  # 1 at-least-once (tracker), 2 exactly-once (aligner)


@dataclass
class Watermark:
    """Event-time watermark: no further events with ts < `ts` expected
    (reference: internal/topo/node/watermark_op.go)."""

    ts: int


@dataclass
class EOF:
    """Stream end (trial runs / bounded sources)."""

    source_id: str = ""


@dataclass
class Trigger:
    """Window trigger tick (processing-time), enqueued by clock timers into
    the owning window node's input so handling serializes with data."""

    ts: int
    tag: Any = None


@dataclass
class PreTrigger:
    """Advance notice of an upcoming window boundary, enqueued ~1 device RTT
    early so the fused agg node can pre-issue its finalize + async transfer
    (ops/prefinalize.py). ts = the boundary the notice is for."""

    ts: int


@dataclass
class ErrorEvent:
    error: BaseException
    origin: str = ""
