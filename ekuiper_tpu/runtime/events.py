"""Events flowing between runtime nodes — analogue of the reference's
BufferOrEvent stream (data + barriers piggybacked on the same channels,
internal/topo/node/node.go:121-127).

Data travels as ColumnBatch (micro-batched columnar, the TPU-native form) or
as row collections (WindowTuples/GroupedTuplesSet) after windowing; control
events (barrier, watermark, EOF, window trigger) interleave in-band so
alignment semantics match the reference's checkpoint design.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class Barrier:
    """Checkpoint barrier (Chandy-Lamport aligned snapshot marker,
    reference: internal/topo/checkpoint/barrier_handler.go)."""

    checkpoint_id: int
    source_id: str = ""
    qos: int = 1  # 1 at-least-once (tracker), 2 exactly-once (aligner)


@dataclass
class Watermark:
    """Event-time watermark: no further events with ts < `ts` expected
    (reference: internal/topo/node/watermark_op.go)."""

    ts: int


@dataclass
class EOF:
    """Stream end (trial runs / bounded sources)."""

    source_id: str = ""


@dataclass
class Trigger:
    """Window trigger tick (processing-time), enqueued by clock timers into
    the owning window node's input so handling serializes with data."""

    ts: int
    tag: Any = None


@dataclass
class PreTrigger:
    """Advance notice of an upcoming window boundary, enqueued ~1 device RTT
    early so the fused agg node can pre-issue its finalize + async transfer
    (ops/prefinalize.py). ts = the boundary the notice is for."""

    ts: int


@dataclass
class ErrorEvent:
    error: BaseException
    origin: str = ""


# ---------------------------------------------------------------------------
# Engine flight recorder — a bounded in-memory ring of STRUCTURED engine
# events (rule state changes, recompile storms, drop bursts, shared-fold
# attach/detach, qos private fallbacks, memory-budget evictions). The
# node-to-node events above are data-plane; these are control-plane
# breadcrumbs: when a rule degrades at 3am, `GET /diagnostics/events` (or
# a tools/kuiperdiag.py bundle) replays the last N state transitions
# without anyone having had DEBUG logging on. Recording is a deque append
# under a short lock — cheap enough for every producer site; producers
# are expected to pre-throttle high-frequency conditions (drop BURSTS at
# decade thresholds, ONE storm event per jit site), so the ring holds
# hours of history, not milliseconds.


class FlightRecorder:
    """Bounded ring of engine events, oldest evicted first. Capacity
    defaults from `KUIPER_EVENTS_RING` (read at construction — the
    singleton below picks it up at import, tests construct their own);
    the durable trail beyond the ring is the telemetry timeline
    (observability/timeline.py), which `record()` mirrors into."""

    DEFAULT_CAPACITY = 1024

    def __init__(self, capacity: Optional[int] = None) -> None:
        from collections import deque

        if capacity is None:
            import os

            try:
                capacity = int(os.environ.get("KUIPER_EVENTS_RING", ""))
            except (TypeError, ValueError):
                capacity = self.DEFAULT_CAPACITY
        self.capacity = max(int(capacity), 1)
        self._ring: "deque" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0  # total ever recorded (monotonic event id)

    #: event severities, mildest first — producers grade their events so
    #: pollers can alert on warn/error without parsing kinds
    SEVERITIES = ("info", "warn", "error")

    def record(self, kind: str, rule: str = "", severity: str = "info",
               ts_ms: Optional[int] = None, **detail: Any) -> None:
        """Append one event. `detail` values must be JSON-serializable
        (the ring is served verbatim over REST). `severity` grades the
        event info/warn/error; unknown grades clamp to info. Callers
        that hold a lock which also gets taken inside engine-clock timer
        callbacks MUST pass `ts_ms` (their pre-lock clock read): reading
        the clock here would put their lock before the clock lock, the
        ABBA class utils/lockcheck.py polices (clock orders first)."""
        from ..utils import timex

        if severity not in self.SEVERITIES:
            severity = "info"
        ev = {"kind": kind, "rule": rule, "severity": severity,
              "ts_ms": timex.now_ms() if ts_ms is None else int(ts_ms),
              **detail}
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
        # mirror into the durable timeline AFTER the ring lock releases
        # (the timeline takes its own lock + does file I/O — neither
        # belongs under this ring's short lock, and callers may already
        # hold evaluator/controller locks above us)
        from ..observability import timeline as _timeline

        _timeline.note_event(ev)

    def events(self, kind: Optional[str] = None,
               rule: Optional[str] = None,
               limit: Optional[int] = None,
               since: Optional[int] = None) -> list:
        """Events oldest→newest, optionally filtered. `since` returns
        only events with seq > since — pollers tail the ring
        incrementally by passing the last seq they saw (kuiperdiag
        bundles record it). `limit` keeps the NEWEST n after filtering —
        except when combined with `since`, where it keeps the OLDEST n:
        a tailing client pages FORWARD from its cursor, so truncation
        must drop the events it will fetch next page, not the ones
        between its cursor and the window (which `last_seq` would then
        silently skip forever)."""
        with self._lock:
            out = list(self._ring)
        if since is not None:
            out = [e for e in out if e["seq"] > since]
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        if rule is not None:
            out = [e for e in out if e["rule"] == rule]
        if since is not None:
            if limit is not None and limit >= 0:
                out = out[:limit]
            return out
        if limit is not None and limit >= 0:
            out = out[len(out) - min(limit, len(out)):]
        return out

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._seq

    def clear(self) -> None:
        """Test hook — empties the ring, keeps the monotonic seq."""
        with self._lock:
            self._ring.clear()

    def diagnostics(self, kind: Optional[str] = None,
                    rule: Optional[str] = None,
                    limit: Optional[int] = None,
                    since: Optional[int] = None) -> Dict[str, Any]:
        """The GET /diagnostics/events payload. `last_seq` is the newest
        seq in the response (or the caller's `since` when nothing newer
        exists) — feed it back as `?since=` to tail without re-reading."""
        evs = self.events(kind=kind, rule=rule, limit=limit, since=since)
        return {"events": evs, "capacity": self.capacity,
                "total_recorded": self.total_recorded,
                "returned": len(evs),
                "last_seq": evs[-1]["seq"] if evs else (since or 0)}


_recorder = FlightRecorder()


def recorder() -> FlightRecorder:
    """The engine-wide flight recorder singleton."""
    return _recorder
