"""Switch node — graph-API conditional fan-out
(reference: internal/topo/node/switch_node.go).

Each case expression owns an output port (a list of downstream nodes). A row
is routed to every case it matches; with `stop_at_first_match` routing stops
at the first matching case. Control events (barrier/watermark/EOF) broadcast
to ALL downstreams via the Node defaults so checkpointing still aligns.
"""
from __future__ import annotations

from typing import Any, List

from ..data.batch import ColumnBatch
from ..data.rows import Row, WindowTuples
from ..sql import ast
from ..sql.eval import Evaluator
from .node import Node


class SwitchNode(Node):
    def __init__(self, name: str, cases: List[ast.Expr],
                 stop_at_first_match: bool = False, **kw) -> None:
        super().__init__(name, op_type="op", **kw)
        self.cases = cases
        self.stop_at_first_match = stop_at_first_match
        self.case_outputs: List[List[Node]] = [[] for _ in cases]
        self.ev = Evaluator()

    def connect_case(self, case_idx: int, downstream: Node) -> Node:
        """Wire one case port; also registers the downstream for control-event
        broadcast (checkpoint barriers must reach every branch)."""
        self.case_outputs[case_idx].append(downstream)
        if downstream not in self.outputs:
            self.outputs.append(downstream)
        downstream._input_names.add(self.name)  # fan-in count for barriers
        return downstream

    def process(self, item: Any) -> None:
        if isinstance(item, ColumnBatch):
            rows: List[Any] = item.to_tuples()
        elif isinstance(item, WindowTuples):
            rows = [item]  # collections route as a unit (condition on rows())
        elif isinstance(item, (Row, dict)):
            rows = [item]
        else:
            self.emit(item)
            return
        for r in rows:
            cond_row = r
            for i, case in enumerate(self.cases):
                try:
                    matched = self.ev.eval_condition(case, cond_row)
                except Exception:
                    matched = False
                if matched:
                    self.stats.inc_out(1)
                    for out in self.case_outputs[i]:
                        self.send_to(out, r)
                    if self.stop_at_first_match:
                        break
