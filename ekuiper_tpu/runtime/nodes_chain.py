"""Sink/source chain operators — analogues of the reference's per-edge nodes
(SURVEY §2.3):

  BatchNode        size+linger batching pre-sink (batch_op.go:29-38)
  EncodeNode       rows -> bytes via a converter (encode_op.go)
  CompressNode /   wrap utils.codecs compressors (compress_op.go)
  DecompressNode
  EncryptNode /    aes gcm/cfb (encrypt_op.go)
  DecryptNode
  CacheNode        at-least-once sink buffering: memory page + KV-store disk
                   spill, resend loop with backoff
                   (cache_op.go, cache/sync_cache.go:107-378)
  RateLimitNode    per-interval latest-message throttle (rate_limit.go:36-67)
  DedupTriggerNode interval dedup w/ expiring state (dedup_trigger_op.go:32-302)

All are ordinary Nodes on the threaded DAG; they pass through Barrier /
Watermark / EOF control events via the Node defaults.
"""
from __future__ import annotations

import base64
import json
import pickle
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..utils import timex
from ..utils.codecs import get_compressor, get_encryptor
from ..utils.infra import logger
from .events import EOF
from .node import Node


def _dumps(item: Any) -> str:
    """KV-safe serialization for spilled payloads (KV backends store JSON)."""
    return base64.b64encode(pickle.dumps(item)).decode("ascii")


def _loads(raw: Any) -> Any:
    return pickle.loads(base64.b64decode(raw))


class BatchNode(Node):
    """Accumulate messages; emit a list when size or linger expires
    (batch_op.go:29-38 — sendInterval/batchSize)."""

    def __init__(self, name: str, size: int = 0, linger_ms: int = 0, **kw) -> None:
        super().__init__(name, **kw)
        if size <= 0 and linger_ms <= 0:
            raise ValueError("batch needs batchSize or lingerInterval")
        self.size = size
        self.linger_ms = linger_ms
        self._buf: List[Any] = []
        self._mu = threading.Lock()
        self._timer = None

    def on_open(self) -> None:
        if self.linger_ms > 0:
            self._arm()

    def _arm(self) -> None:
        self._timer = timex.get_clock().after(self.linger_ms, lambda _now: self._fire())

    def _fire(self) -> None:
        self._flush()
        if not self._stop.is_set():
            self._arm()

    def _flush(self) -> None:
        with self._mu:
            buf, self._buf = self._buf, []
        if buf:
            self.emit(buf, count=len(buf))

    def process(self, item: Any) -> None:
        items = item if isinstance(item, list) else [item]
        full = False
        with self._mu:
            self._buf.extend(items)
            full = self.size > 0 and len(self._buf) >= self.size
        if full:
            self._flush()

    def on_eof(self, eof: EOF) -> None:
        self._flush()
        self.broadcast(eof)

    def on_close(self) -> None:
        if self._timer is not None:
            self._timer.stop()
        self._flush()


class TransformNode(Node):
    """Sink-side transform as a standalone stage (transform_op.go): applied
    BEFORE encode/compress/encrypt so those stages see the projected payload.
    When present, the terminal SinkNode's own transform is disabled."""

    def __init__(self, name: str, send_single: bool = False,
                 fields: Optional[List[str]] = None,
                 exclude_fields: Optional[List[str]] = None,
                 data_template: str = "", omit_if_empty: bool = False,
                 **kw) -> None:
        super().__init__(name, **kw)
        self.send_single = send_single
        self.fields = fields
        self.exclude_fields = exclude_fields
        self.data_template = data_template
        self.omit_if_empty = omit_if_empty

    def process(self, item: Any) -> None:
        from .nodes_sink import apply_transform, to_messages

        msgs = to_messages(item)
        if not msgs and self.omit_if_empty:
            return
        msgs = [apply_transform(m, self.fields, self.exclude_fields,
                                self.data_template) for m in msgs]
        if self.send_single:
            for m in msgs:
                self.emit(m)
        else:
            self.emit(msgs if len(msgs) != 1 else msgs[0])


class EncodeNode(Node):
    """Rows -> bytes via the sink's FORMAT converter (encode_op.go)."""

    def __init__(self, name: str, converter, **kw) -> None:
        super().__init__(name, **kw)
        self.converter = converter

    def process(self, item: Any) -> None:
        from .nodes_sink import to_messages

        if isinstance(item, (bytes, bytearray)):
            self.emit(bytes(item))  # already encoded upstream
            return
        if isinstance(item, str):
            # rendered dataTemplate output is the final wire payload
            self.emit(item.encode())
            return
        msgs = to_messages(item)
        payload = msgs[0] if len(msgs) == 1 else msgs
        self.emit(self.converter.encode(payload))


class CompressNode(Node):
    def __init__(self, name: str, algorithm: str, **kw) -> None:
        super().__init__(name, **kw)
        self._compress, _ = get_compressor(algorithm)

    def process(self, item: Any) -> None:
        if not isinstance(item, (bytes, bytearray)):
            item = json.dumps(item, default=str).encode()
        self.emit(self._compress(bytes(item)))


class DecompressNode(Node):
    def __init__(self, name: str, algorithm: str, **kw) -> None:
        super().__init__(name, **kw)
        _, self._decompress = get_compressor(algorithm)

    def process(self, item: Any) -> None:
        self.emit(self._decompress(bytes(item)))


class EncryptNode(Node):
    def __init__(self, name: str, algorithm: str, props: Dict[str, Any], **kw) -> None:
        super().__init__(name, **kw)
        self._enc = get_encryptor(algorithm, props)

    def process(self, item: Any) -> None:
        if not isinstance(item, (bytes, bytearray)):
            item = json.dumps(item, default=str).encode()
        self.emit(self._enc.encrypt(bytes(item)))


class DecryptNode(Node):
    def __init__(self, name: str, algorithm: str, props: Dict[str, Any], **kw) -> None:
        super().__init__(name, **kw)
        self._enc = get_encryptor(algorithm, props)

    def process(self, item: Any) -> None:
        self.emit(self._enc.decrypt(bytes(item)))


class CacheNode(Node):
    """At-least-once sink buffer (sync_cache.go:107-378).

    Pass-through while the downstream sink is healthy. The SinkNode reports
    failures back via `nack(payload)`; nacked payloads go to the memory page,
    spilling to the rule's KV store beyond `memory_threshold`. A resend timer
    retries oldest-first, preserving order, with `resend_interval_ms` pacing.
    """

    def __init__(
        self,
        name: str,
        store_kv=None,  # KV namespace for disk spill (None = memory only)
        memory_threshold: int = 1024,
        max_disk_cache: int = 1024 * 1024,
        resend_interval_ms: int = 100,
        **kw,
    ) -> None:
        super().__init__(name, **kw)
        self.kv = store_kv
        self.memory_threshold = memory_threshold
        self.max_disk_cache = max_disk_cache
        self.resend_interval_ms = resend_interval_ms
        self._mem: List[Any] = []
        self._disk_head = 0  # next key to resend
        self._disk_tail = 0  # next key to write
        self._mu = threading.Lock()
        self._timer = None
        self._armed = False  # resend timer reserved (see _reserve_arm_locked)
        self._closed = False
        self._inflight = None  # ("mem"|"disk", item) awaiting sink ack/nack
        # (disk_key, item) for a mem in-flight delivery whose payload a
        # barrier spilled to disk while the sink ack was still outstanding;
        # the late ack must delete that record or the resend timer would
        # redeliver an already-delivered item (duplicate sink output)
        self._spilled_inflight = None
        if self.kv is not None:  # restore spill bounds from a previous run
            keys = []
            for k in self.kv.keys():
                try:
                    keys.append(int(k))  # close-spill prepends: can be < 0
                except (TypeError, ValueError):
                    continue
            if keys:
                keys.sort()
                self._disk_head, self._disk_tail = keys[0], keys[-1] + 1

    def on_open(self) -> None:
        # a restart with spilled backlog must resend WITHOUT waiting for new
        # traffic (a fully-consumed rewindable source may never push again)
        with self._mu:
            arm = ((self._mem or self._disk_head != self._disk_tail)
                   and self._reserve_arm_locked())
        if arm:
            self._register_arm()

    # pass-through; SinkNode acks successes / nacks failures back to us
    def process(self, item: Any) -> None:
        with self._mu:
            pending = (bool(self._mem) or self._disk_head != self._disk_tail
                       or self._inflight is not None)
        if pending:
            self._enqueue(item)  # keep order: new items go behind the backlog
        else:
            self.emit(item)

    def ack(self, item: Any) -> None:
        """Downstream delivery confirmed — only now drop the spilled copy
        (sync_cache deletes a disk record only after a successful send)."""
        arm = False
        with self._mu:
            fl = self._inflight
            if fl is None or fl[1] is not item and fl[1] != item:
                sp = self._spilled_inflight
                if sp is not None and (sp[1] is item or sp[1] == item):
                    # late ack for a delivery whose payload a barrier moved
                    # to disk — drop the spilled record so it isn't resent
                    self._spilled_inflight = None
                    self.kv.delete(str(sp[0]))
                    if sp[0] == self._disk_head:
                        self._disk_head += 1
                    if bool(self._mem) or self._disk_head != self._disk_tail:
                        arm = self._reserve_arm_locked()
                # else: ack for a pass-through item — nothing tracked
            else:
                kind = fl[0]
                self._inflight = None
                if kind == "disk":
                    self.kv.delete(str(self._disk_head))
                    self._disk_head += 1
                if bool(self._mem) or self._disk_head != self._disk_tail:
                    arm = self._reserve_arm_locked()
        if arm:
            self._register_arm()

    def nack(self, item: Any) -> None:
        """Called by the downstream SinkNode when collect ultimately fails."""
        arm = False
        tracked = False
        with self._mu:
            fl = self._inflight
            sp = self._spilled_inflight
            if fl is not None and (fl[1] is item or fl[1] == item):
                self._inflight = None
                if fl[0] == "mem":
                    self._mem.insert(0, item)
                # a disk record was never deleted — it will be re-read
                tracked = True
                arm = self._reserve_arm_locked()
            elif sp is not None and (sp[1] is item or sp[1] == item):
                # failed delivery whose payload a barrier spilled: the disk
                # record IS the retry copy — re-enqueueing would duplicate
                self._spilled_inflight = None
                tracked = True
                arm = self._reserve_arm_locked()
        if arm:
            self._register_arm()
        if not tracked:
            self._enqueue(item, front=True)

    def _enqueue(self, item: Any, front: bool = False) -> None:
        dropped = 0
        with self._mu:
            if front:
                self._mem.insert(0, item)
            elif self.kv is not None and (
                len(self._mem) >= self.memory_threshold
                or self._disk_head != self._disk_tail  # FIFO: go behind spill
            ):
                if self._disk_tail - self._disk_head < self.max_disk_cache:
                    self.kv.set(str(self._disk_tail), _dumps(item))
                    self._disk_tail += 1
                else:
                    dropped = 1  # stat recorded below, outside _mu
            else:
                self._mem.append(item)
            arm = self._reserve_arm_locked()
        if dropped:
            # outside _mu: inc_exception reads the engine clock, and the
            # mock clock fires _resend -> _mu while holding the clock
            # lock (clock orders before _mu — utils/lockcheck.py)
            self.stats.inc_exception("disk cache full, dropped")
        if arm:
            self._register_arm()

    def _arm(self) -> None:
        with self._mu:
            arm = self._reserve_arm_locked()
        if arm:
            self._register_arm()

    def _reserve_arm_locked(self) -> bool:
        """Reserve the resend timer. Caller holds self._mu and, when this
        returns True, MUST call _register_arm() AFTER releasing it: timer
        registration takes the engine clock lock, and the mock clock
        fires callbacks (-> _resend -> self._mu) while holding it —
        arming under self._mu was the clock/cache ABBA
        utils/lockcheck.py caught on day one (clock orders before _mu)."""
        if self._armed or self._closed:
            return False
        self._armed = True
        return True

    def _register_arm(self) -> None:
        # outside self._mu by contract (see _reserve_arm_locked)
        self._timer = timex.get_clock().after(
            self.resend_interval_ms, lambda _now: self._resend())

    def _resend(self) -> None:
        arm = False
        item = None
        with self._mu:
            self._timer = None
            self._armed = False
            if self._closed:
                return
            if self._inflight is not None or self._spilled_inflight is not None:
                # previous delivery still unconfirmed — wait for ack/nack
                # (a spilled in-flight is still a live downstream delivery;
                # resending its disk record now would duplicate it)
                arm = self._reserve_arm_locked()
            elif self._mem:
                item = self._mem.pop(0)
                self._inflight = ("mem", item)
            elif self.kv is not None and self._disk_head != self._disk_tail:
                raw = self.kv.get(str(self._disk_head))
                if raw is None:  # lost record — skip the slot
                    self._disk_head += 1
                    arm = self._reserve_arm_locked()
                else:
                    item = _loads(raw)
                    self._inflight = ("disk", item)  # deleted only on ack
        if arm:
            self._register_arm()
        if item is not None:
            self.emit(item)

    def pending(self) -> int:
        with self._mu:
            n = len(self._mem) + (self._disk_tail - self._disk_head)
            if self._inflight is not None and self._inflight[0] == "mem":
                n += 1
            return n

    def _spill_page_locked(self) -> Tuple[int, int]:
        """Move the memory page (queue FRONT — oldest pending) plus any
        unconfirmed in-flight delivery INTO the spill KV, prepending BEFORE
        the disk head (keys may go negative) so replay order stays
        oldest-first. Enforces max_disk_cache like _enqueue: the OLDEST
        items keep their slots, the newest overflow drops. Caller holds
        self._mu and returns (moved, dropped); the caller records the
        drop stat AFTER releasing _mu (inc_exception reads the engine
        clock — clock orders before _mu, utils/lockcheck.py)."""
        items = list(self._mem)
        inflight_item = None
        if self._inflight is not None and self._inflight[0] == "mem":
            inflight_item = self._inflight[1]
            items.insert(0, inflight_item)
            self._inflight = None
        n_drop = 0
        room = self.max_disk_cache - (self._disk_tail - self._disk_head)
        if len(items) > max(room, 0):
            n_drop = len(items) - max(room, 0)
            items = items[:max(room, 0)]
        for item in reversed(items):
            self._disk_head -= 1
            self.kv.set(str(self._disk_head), _dumps(item))
        if inflight_item is not None and items:
            # items[0] (the in-flight delivery) landed at the new disk head;
            # remember the key so its still-outstanding ack can delete it
            self._spilled_inflight = (self._disk_head, inflight_item)
        self._mem.clear()
        return len(items), n_drop

    def snapshot_state(self) -> Optional[dict]:
        # The spill KV is the ONE durable store for pending payloads: at a
        # barrier the memory page moves into it (immediately durable even
        # if the checkpoint never completes), and the JSON checkpoint
        # carries only bookkeeping — no payload double-persist between the
        # checkpoint and the close-time spill. Memory-only caches (no KV)
        # still encode the page into the checkpoint itself.
        out = None
        dropped = 0
        with self._mu:
            if self.kv is not None:
                n, dropped = self._spill_page_locked()
                out = {"spilled": n}
            else:
                items = list(self._mem)
                if self._inflight is not None and self._inflight[0] == "mem":
                    items.insert(0, self._inflight[1])
        if dropped:
            self.stats.inc_exception("disk cache full, dropped", n=dropped)
        if out is not None:
            return out
        return {"mem_enc": [_dumps(i) for i in items]}

    def restore_state(self, state: dict) -> None:
        with self._mu:
            if "mem_enc" in state:
                self._mem = [_loads(r) for r in state["mem_enc"]]
            elif "mem" in state:  # legacy raw-list snapshots
                self._mem = list(state.get("mem", []))
            # KV-backed pages were spilled at snapshot time; __init__
            # already recovered the disk bounds

    def on_close(self) -> None:
        with self._mu:
            # closed gate: an arm reserved but not yet registered by a
            # racing thread may still create a timer, but its _resend
            # no-ops once closed is set — nothing re-emits after close
            self._closed = True
            timer, self._timer = self._timer, None
        if timer is not None:
            timer.stop()
        # spill whatever is still in memory (items nacked after the last
        # barrier) so nothing is lost across restarts; a disk-sourced
        # in-flight record was never deleted, so it replays by itself
        if self.kv is not None:
            with self._mu:
                _, dropped = self._spill_page_locked()
            if dropped:
                self.stats.inc_exception("disk cache full, dropped",
                                         n=dropped)


class RateLimitNode(Node):
    """Keep only the most recent message per interval (rate_limit.go:36-67,
    default 'latest' strategy; mergeField frame-merge is host-path only)."""

    def __init__(self, name: str, interval_ms: int, **kw) -> None:
        super().__init__(name, **kw)
        if interval_ms < 1:
            raise ValueError("interval should be larger than 1ms")
        self.interval_ms = interval_ms
        self._latest: Any = None
        self._has = False
        self._mu = threading.Lock()
        self._timer = None

    def on_open(self) -> None:
        self._arm()

    def _arm(self) -> None:
        self._timer = timex.get_clock().after(self.interval_ms, lambda _now: self._fire())

    def _fire(self) -> None:
        with self._mu:
            item, self._has = (self._latest, False) if self._has else (None, False)
            self._latest = None
        if item is not None:
            self.emit(item)
        if not self._stop.is_set():
            self._arm()

    def process(self, item: Any) -> None:
        with self._mu:
            self._latest = item
            self._has = True

    def on_close(self) -> None:
        if self._timer is not None:
            self._timer.stop()


class DedupTriggerNode(Node):
    """Interval-overlap dedup for trigger events (dedup_trigger_op.go:32-302).

    Rows carry start/end(/now) fields; already-seen [start,end) sub-ranges are
    suppressed, novel sub-ranges emit as {alias: [[start,end],...]} merged into
    the row. Seen state expires after `expire_ms`.
    """

    def __init__(
        self,
        name: str,
        alias: str = "dedup_trigger",
        start_field: str = "start",
        end_field: str = "end",
        now_field: str = "",
        expire_ms: int = 3_600_000,
        **kw,
    ) -> None:
        super().__init__(name, **kw)
        self.alias = alias
        self.start_field = start_field
        self.end_field = end_field
        self.now_field = now_field
        self.expire_ms = expire_ms
        self._seen: List[List[int]] = []  # sorted non-overlapping [start,end)

    def process(self, item: Any) -> None:
        from ..data.rows import Row

        msg = item.all_values() if isinstance(item, Row) else dict(item)
        start = int(msg.get(self.start_field, 0))
        end = int(msg.get(self.end_field, 0))
        now = int(msg.get(self.now_field, end)) if self.now_field else end
        if end <= start:
            raise ValueError(f"dedup trigger: end {end} <= start {start}")
        # expire old state
        horizon = now - self.expire_ms
        self._seen = [iv for iv in self._seen if iv[1] > horizon]
        novel = self._subtract(start, end)
        if not novel:
            return  # fully duplicate
        self._insert(start, end)
        msg = dict(msg)
        msg[self.alias] = novel
        self.emit(msg)

    def _subtract(self, start: int, end: int) -> List[List[int]]:
        """[start,end) minus seen ranges -> novel sub-ranges."""
        out: List[List[int]] = []
        cur = start
        for s, e in sorted(self._seen):
            if e <= cur:
                continue
            if s >= end:
                break
            if s > cur:
                out.append([cur, min(s, end)])
            cur = max(cur, e)
            if cur >= end:
                break
        if cur < end:
            out.append([cur, end])
        return out

    def _insert(self, start: int, end: int) -> None:
        merged: List[List[int]] = []
        for s, e in sorted(self._seen + [[start, end]]):
            if merged and s <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], e)
            else:
                merged.append([s, e])
        self._seen = merged

    def snapshot_state(self) -> Optional[dict]:
        return {"seen": [list(iv) for iv in self._seen]}

    def restore_state(self, state: dict) -> None:
        self._seen = [list(iv) for iv in state.get("seen", [])]
