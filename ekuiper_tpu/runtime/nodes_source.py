"""Source pipeline node — the fused analogue of the reference's source split
(connector → rate-limit → decode → preprocessor, planner_source.go:35-197).

A SourceNode owns a connector (io registry), decodes payloads via the
converter, coerces to the stream schema (preprocessor semantics incl.
event-time extraction from the TIMESTAMP option), accumulates rows into
columnar micro-batches (size/linger bounded), and emits ColumnBatch — the
TPU-native ingest form. Micro-batching here is what turns the reference's
per-tuple goroutine hops into whole-batch device work.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..data import cast
from ..data.batch import ColumnBatch, from_tuples
from ..data.rows import Tuple
from ..data.types import Schema
from ..utils import timex
from ..utils.infra import logger
from .events import EOF
from .node import Node


class SourceNode(Node):
    def __init__(
        self,
        name: str,
        connector,  # io.Source instance
        schema: Optional[Schema] = None,
        timestamp_field: str = "",
        strict_validation: bool = False,
        micro_batch_rows: int = 4096,
        linger_ms: int = 10,
        buffer_length: int = 1024,
        emit_batches: bool = True,
        converter=None,  # io.converters.Converter for bytes payloads
        project_columns=None,  # column-pruning set (planner/optimizer.py)
        decode_pool_size: int = 0,  # 0 = decode inline (no pool threads)
        decode_shards: int = 0,  # native parse shards; 0 = auto
        ring_depth: int = 2,  # decoded-batch ring depth (pool backpressure)
        prep_upload: bool = True,  # pool workers pre-encode keys + device_put
    ) -> None:
        super().__init__(name, op_type="source", buffer_length=buffer_length)
        self.connector = connector
        self.converter = converter
        self.schema = schema
        self.timestamp_field = timestamp_field
        self.strict = cast.STRICT if strict_validation else cast.CONVERT_ALL
        self.micro_batch_rows = micro_batch_rows
        self.linger_ms = linger_ms
        self.project_columns = (set(project_columns)
                                if project_columns is not None else None)
        if self.project_columns is not None and self.schema is not None \
                and not self.schema.schemaless:
            # restrict the declared schema too: from_tuples materializes a
            # column per schema field, so pruning must reach it or typed
            # streams would re-grow zero-filled columns at batch build
            from ..data.types import Schema

            self.schema = Schema(fields=[
                f for f in self.schema.fields
                if f.name in self.project_columns])
        self.emit_batches = emit_batches
        # batch mode buffers RAW decoded messages; schema coercion +
        # event-time extraction run COLUMNAR at flush (data/batch.py
        # from_messages) instead of per-row — the row path (emit_batches=
        # False) keeps the per-tuple preprocessor
        self._pending_msgs: List[Dict[str, Any]] = []
        self._pending_ts: List[int] = []
        # native fast path: JSON bytes payloads for a fully-scalar typed
        # schema buffer RAW and decode straight to columns in C at flush
        # (io/fastjson.py over native/jsoncol.cpp)
        self._fast_spec = None
        self._pending_raw: List[bytes] = []
        self._pending_raw_ts: List[int] = []
        if converter is not None and schema is not None:
            from ..io.converters import JsonConverter
            from ..io.fastjson import ensure_native, schema_field_spec

            if type(converter) is JsonConverter and \
                    self.strict != cast.STRICT:
                # STRICT streams keep the python cast path — the C decoder
                # hard-codes CONVERT_ALL coercion
                spec = schema_field_spec(self.schema)
                if spec is not None and timestamp_field:
                    # event-time via the fast path needs an exact int64
                    # column; other shapes keep the python extractor
                    ftypes = {f.name: f.type for f in self.schema.fields}
                    from ..data.types import DataType

                    if ftypes.get(timestamp_field) != DataType.BIGINT:
                        spec = None
                self._fast_spec = spec
                if spec is not None:
                    ensure_native()
        self._pending_lock = threading.Lock()
        self._linger_timer = None
        # sharded ingest pipeline (runtime/ingest.py): flush-time decode
        # runs on pool workers, shard-parallel inside the native parse,
        # handed to the fused node through a bounded ordered ring. Pool-
        # less sources (decode_pool_size=0) decode inline exactly as
        # before. The pool itself starts LAZILY at first use: planned-but-
        # never-opened topos (rule validation plans then closes without
        # open()) must not leak worker threads.
        self.decode_pool_size = (int(decode_pool_size) if emit_batches
                                 else 0)
        self.ring_depth = int(ring_depth)
        self._decode_shards = (int(decode_shards) if decode_shards
                               else max(self.decode_pool_size, 1))
        self._pool = None
        # pipelined upload stage (runtime/ingest.py IngestPrepCtx): pool
        # workers key-slot-encode each decoded batch and pre-pad +
        # device_put its kernel inputs, so the fused worker receives
        # device-resident refs instead of raw host columns. Only with the
        # pool on — the decode_pool_size=0 default path stays bit-for-bit
        # the pre-pool inline pipeline (mock-clock determinism).
        self.prep_ctx = None
        if self.decode_pool_size > 0 and prep_upload:
            from .ingest import IngestPrepCtx

            self.prep_ctx = IngestPrepCtx()

    # ------------------------------------------------------------------ ingest
    def on_open(self) -> None:
        self.connector.open(self.ingest)

    def on_close(self) -> None:
        try:
            self.connector.close()
        except Exception as exc:
            logger.debug("source %s close error: %s", self.name, exc)
        self._flush()
        if self._pool is not None:
            self._pool.close()

    def ingest(self, payload: Any, metadata: Optional[Dict[str, Any]] = None) -> None:
        """Connector callback: raw bytes (decoded here via the stream's
        FORMAT converter), a LIST of raw bytes payloads (a broker drain —
        batch-decoded), dict, list of dicts, or Tuple."""
        now = timex.now_ms()
        if self._fast_spec is not None and self.emit_batches:
            raws = None
            if isinstance(payload, (bytes, bytearray)):
                raws = [bytes(payload)]
            elif (isinstance(payload, list) and payload
                  and all(isinstance(p, (bytes, bytearray))
                          for p in payload)):
                raws = [bytes(p) for p in payload]
            if raws is not None:
                self.stats.inc_in(len(raws))
                self._buffer("raw", raws, [now] * len(raws))
                return
        if isinstance(payload, (bytes, bytearray)):
            if self.converter is None:
                self.stats.inc_exception("bytes payload but no converter")
                return
            try:
                payload = self.converter.decode(bytes(payload))
            except Exception as exc:
                self.stats.inc_exception(f"decode error: {exc}")
                self.stats.inc_dropped("decode_error")
                return
        msgs: List[Dict[str, Any]] = []
        if isinstance(payload, Tuple):
            self.stats.inc_in(1)
            if not self.emit_batches:
                t = self._preprocess(payload)
                if t is not None:
                    t.ingest_ms = now
                    self.emit(t)
                return
            # preserve the tuple's own (replay/historical) timestamp
            self._buffer("msgs", [payload.message], [payload.timestamp or now])
            return
        elif isinstance(payload, dict):
            msgs = [payload]
        elif isinstance(payload, list):
            if payload and isinstance(payload[0], (bytes, bytearray)):
                msgs = self._decode_many(payload)
                if msgs is None:
                    return
            else:
                msgs = [m for m in payload if isinstance(m, dict)]
        elif payload is None:
            return
        else:
            self.stats.inc_exception(f"unsupported payload {type(payload)}")
            return
        if not msgs:
            return
        self.stats.inc_in(len(msgs))
        if not self.emit_batches:
            for m in msgs:
                t = self._preprocess(Tuple(
                    emitter=self.name, message=m, timestamp=now,
                    metadata=metadata or {}))
                if t is not None:
                    t.ingest_ms = now
                    self.emit(t)
            return
        self._buffer("msgs", msgs, [now] * len(msgs))

    def _buffer(self, kind: str, new_items: list, new_ts: list) -> None:
        """Append to a pending buffer under the lock, then flush at the
        micro-batch threshold or arm the linger timer — the single place
        holding the batching policy for all three ingest shapes. The
        target list is resolved INSIDE the lock: a caller-bound reference
        could be swapped out by a concurrent flush between the attribute
        read and the lock, silently losing the whole append."""
        with self._pending_lock:
            if kind == "raw":
                self._pending_raw.extend(new_items)
                self._pending_raw_ts.extend(new_ts)
            else:
                self._pending_msgs.extend(new_items)
                self._pending_ts.extend(new_ts)
            full = (len(self._pending_msgs) + len(self._pending_raw)
                    >= self.micro_batch_rows)
        if full:
            self._flush(final=False)
            with self._pending_lock:
                leftover = bool(self._pending_msgs or self._pending_raw)
            if not leftover:
                return
            # a micro-batch-aligned flush kept a remainder: make sure a
            # linger timer is live so it cannot stall if ingest pauses
        self._arm_linger()

    def _arm_linger(self) -> None:
        if self._linger_timer is None or self._linger_timer.fired \
                or self._linger_timer.stopped:
            self._linger_timer = timex.after(
                self.linger_ms, lambda ts: self._linger_flush())

    def _linger_flush(self) -> None:
        """Timer-driven flush: stays micro-batch-aligned under sustained
        ingest (a large pending still emits exact micro_batch slices; only
        a sub-micro-batch tail flushes whole) and re-arms while a
        remainder is pending so it drains within another linger period."""
        self._flush(final=False)
        with self._pending_lock:
            leftover = bool(self._pending_msgs or self._pending_raw)
        if leftover:
            self._arm_linger()

    def _decode_many(self, payloads: List[bytes]) -> Optional[List[Dict[str, Any]]]:
        """Batch-decode a run of raw payloads. For JSON this splices the
        payloads into ONE array and parses once — one C-level json.loads
        instead of thousands (≈4x per-object) — falling back to per-payload
        decode when any payload is itself an array or malformed."""
        from ..io.converters import JsonConverter

        if self.converter is None:
            self.stats.inc_exception("bytes payload but no converter")
            return None
        if isinstance(self.converter, JsonConverter) and all(
                isinstance(p, (bytes, bytearray)) for p in payloads):
            try:
                spliced = b"[" + b",".join(bytes(p) for p in payloads) + b"]"
                out = self.converter.decode(spliced)
                if all(isinstance(m, dict) for m in out):
                    return out
            except Exception:
                pass  # fall through: per-payload decode isolates bad ones
        msgs: List[Dict[str, Any]] = []
        for p in payloads:
            if isinstance(p, dict):  # mixed drains: dicts pass through
                msgs.append(p)
                continue
            try:
                m = self.converter.decode(bytes(p))
            except Exception as exc:
                self.stats.inc_exception(f"decode error: {exc}")
                self.stats.inc_dropped("decode_error")
                continue
            if isinstance(m, dict):
                msgs.append(m)
            elif isinstance(m, list):
                msgs.extend(x for x in m if isinstance(x, dict))
        return msgs

    def _preprocess(self, t: Tuple) -> Optional[Tuple]:
        """Schema validation/coercion + event-time extraction
        (reference: internal/topo/operator/preprocessor.go)."""
        if self.schema is not None and not self.schema.schemaless:
            msg = {}
            for f in self.schema.fields:
                if f.name in t.message:
                    try:
                        msg[f.name] = cast.to_typed(t.message[f.name], f, self.strict)
                    except cast.CastError as exc:
                        self.stats.inc_exception(str(exc))
                        return None
            t.message = msg
        if self.timestamp_field:
            v = t.message.get(self.timestamp_field)
            if v is None:
                self.stats.inc_exception(
                    f"missing timestamp field {self.timestamp_field}"
                )
                return None
            try:
                t.timestamp = cast.to_datetime_ms(v)
            except cast.CastError as exc:
                self.stats.inc_exception(str(exc))
                return None
        if self.project_columns is not None:
            # column pruning (planner/optimizer.py): drop unreferenced
            # fields before batching — smaller batches, tuples, uploads
            t.message = {k: v for k, v in t.message.items()
                         if k in self.project_columns}
        return t

    # ------------------------------------------------------------------ state
    def snapshot_state(self):
        """Rewindable sources (io/contract.py) checkpoint their offset so a
        restored rule resumes the stream where the snapshot cut it."""
        get_off = getattr(self.connector, "get_offset", None)
        if get_off is None:
            return None
        try:
            return {"offset": get_off()}
        except Exception:
            return None

    def restore_state(self, state: dict) -> None:
        rew = getattr(self.connector, "rewind", None)
        if rew is not None and state and "offset" in state:
            try:
                rew(state["offset"])
            except Exception as exc:
                self.stats.inc_exception(f"rewind failed: {exc}")

    def _flush(self, final: bool = True) -> bool:
        """Flush pending buffers; a final flush also drains the decode
        ring so callers can safely broadcast EOF/barriers after it.
        Returns False when that drain timed out (rows may still be
        decoding) — the barrier path fails its checkpoint on that. The
        drain runs OUTSIDE the pending lock: appending new rows needs
        nothing from the ring, and a held lock would stall every
        connector callback for the drain's duration."""
        msgs = raws = None
        with self._pending_lock:
            if self._pending_msgs or self._pending_raw:
                msgs, self._pending_msgs = self._pending_msgs, []
                tss, self._pending_ts = self._pending_ts, []
                raws, self._pending_raw = self._pending_raw, []
                rtss, self._pending_raw_ts = self._pending_raw_ts, []
                if not final and len(raws) > self.micro_batch_rows:
                    # emit micro_batch-aligned slices and keep the
                    # remainder pending: the fused kernel pads every chunk
                    # to a static micro_batch shape, so a 1024-row tail
                    # would upload a full chunk's worth of padding — on a
                    # bandwidth-limited link that nearly halves ingest for
                    # misaligned flushes
                    cut = (len(raws) // self.micro_batch_rows
                           ) * self.micro_batch_rows
                    self._pending_raw = raws[cut:]
                    self._pending_raw_ts = rtss[cut:]
                    raws, rtss = raws[:cut], rtss[:cut]
        if msgs:
            self._dispatch_job(("msgs", msgs, tss))
        if raws:
            self._dispatch_job(("raw", raws, rtss))
        if final and self._pool is not None:
            if not self._pool.drain():
                logger.error(
                    "source %s: decode ring drain timed out on a final "
                    "flush; decoded batches may trail stream-end events",
                    self.name)
                return False
        return True

    def _ensure_pool(self):
        from .ingest import DecodePool

        with self._pending_lock:
            if self._pool is None:
                self._pool = DecodePool(
                    self.decode_pool_size, self.ring_depth,
                    decode_fn=self._decode_job,
                    emit_fn=self._emit_decoded,
                    name=self.name,
                    prepare_fn=(self._prep_upload
                                if self.prep_ctx is not None else None),
                    stats=self.stats)
            return self._pool

    def _prep_upload(self, batch: ColumnBatch) -> None:
        """Upload stage (pool worker thread): precompute key slots + padded
        device inputs for the batch so the fused node's upload collapses to
        share-cache hits. Accrues to THIS node's `upload` stage — together
        with the fused node's (now residual) `upload` timing the pipeline
        balance stays observable per node."""
        import time as _time

        t0 = _time.perf_counter()
        n_up = self.prep_ctx.precompute(batch)
        if n_up:
            self.stats.observe_stage(
                "upload", (_time.perf_counter() - t0) * 1e6, batch.n)

    def pool_depths(self):
        """(ring occupancy, decode queue depth) for the Prometheus gauges;
        None when no pool has started."""
        pool = self._pool
        if pool is None:
            return None
        return pool.in_flight, pool.queue_depth

    def resize_ingest(self, pool_size=None, ring_depth=None):
        """QoS auto-sizing hook (runtime/control.py): adjust the decode
        pool and/or ring of an already-pooled source. Returns the applied
        {pool_size, ring_depth}, or None for an inline source — the
        control plane never converts a decode_pool_size=0 source to
        pooled (that path is bit-for-bit deterministic by contract)."""
        if self.decode_pool_size <= 0:
            return None
        if pool_size is not None:
            self.decode_pool_size = max(1, int(pool_size))
            if self._pool is not None:
                self.decode_pool_size = self._pool.resize(
                    self.decode_pool_size)
        if ring_depth is not None:
            self.ring_depth = max(1, int(ring_depth))
            if self._pool is not None:
                self.ring_depth = self._pool.set_ring_depth(self.ring_depth)
        return {"pool_size": self.decode_pool_size,
                "ring_depth": self.ring_depth}

    def register_prep_spec(self, spec) -> None:
        """Plan-time upload-spec registration: (key_name, columns,
        micro_batch) from the planner, so the pool's upload stage serves
        from the FIRST batch instead of after the fused node's first fold
        (which also registers, covering un-plumbed paths)."""
        if self.prep_ctx is not None:
            self.prep_ctx.register_upload(*spec)

    def register_tier_prefetch(self, fn) -> None:
        """Tiered key state (ops/tierstore.py): wire the fused consumer's
        cold-tier prefetch into the pool's ordered upload stage."""
        if self.prep_ctx is not None:
            self.prep_ctx.register_tier_prefetch(fn)

    def _dispatch_job(self, job) -> None:
        """Decode+emit one flush unit: on the decode pool when configured
        (shard-parallel native parse off the connector thread, ordered
        ring emission — runtime/ingest.py), else inline as before. BOTH
        job kinds go through the ring when the pool is on, so a msg batch
        can never overtake an earlier raw batch still decoding."""
        if self.decode_pool_size > 0:
            try:
                self._ensure_pool().submit(job)
                return
            except RuntimeError:
                pass  # pool closed (shutdown race): decode inline
        self._emit_decoded(self._decode_job(job))

    def _emit_decoded(self, batch: Optional[ColumnBatch]) -> None:
        if batch is not None and batch.n:
            self.emit(batch, count=batch.n)

    def _decode_job(self, job) -> Optional[ColumnBatch]:
        """One decode unit: ("raw", payloads, tss) | ("msgs", msgs, tss)
        -> ColumnBatch | None. Runs on pool workers — touches only
        immutable config, the converter, and the (locked) StatManager."""
        import time as _time

        from ..data.batch import from_messages

        kind, items, tss = job
        t0 = _time.perf_counter()
        if kind == "raw":
            batch = self._decode_raw_to_batch(items, tss)
        else:
            batch, n_drop = from_messages(
                items, tss, schema=self.schema, emitter=self.name,
                strict=self.strict, timestamp_field=self.timestamp_field,
                on_error=self.stats.inc_exception,
                project=self.project_columns)
            if n_drop:
                logger.debug("source %s dropped %d rows at columnarize",
                             self.name, n_drop)
        self.stats.observe_stage(
            "decode", (_time.perf_counter() - t0) * 1e6, len(items))
        if batch is not None and batch.ingest_ms is None and tss:
            # e2e provenance: the batch speaks for its OLDEST row (arrival
            # order == tss order), so micro-batch linger and every later
            # pipeline stage count toward the recorded ingest→emit latency
            batch.ingest_ms = int(tss[0])
        if batch is not None and self.prep_ctx is not None \
                and batch.shared_ctx is None:
            # ride the prep ctx on the batch so downstream fused nodes
            # consume the shared encode/upload instead of redoing them
            batch.ensure_share_state()
            batch.shared_ctx = self.prep_ctx
        return batch

    def _decode_raw_to_batch(self, raws: List[bytes],
                             rtss: List[int]) -> Optional[ColumnBatch]:
        """Native columnar decode of buffered raw JSON payloads
        (io/fastjson.py); python fallback preserves row↔timestamp pairing."""
        import numpy as np

        from ..io.fastjson import decode_columns

        out = decode_columns(raws, self._fast_spec,
                             shards=self._decode_shards)
        if out is None:
            from ..data.batch import from_messages

            msgs: List[Dict[str, Any]] = []
            tss: List[int] = []
            for p, t in zip(raws, rtss):
                try:
                    m = self.converter.decode(p)
                except Exception as exc:
                    self.stats.inc_exception(f"decode error: {exc}")
                    self.stats.inc_dropped("decode_error")
                    continue
                if isinstance(m, dict):
                    msgs.append(m)
                    tss.append(t)
                elif isinstance(m, list):
                    for x in m:
                        if isinstance(x, dict):
                            msgs.append(x)
                            tss.append(t)
            if not msgs:
                return None
            batch, _ = from_messages(
                msgs, tss, schema=self.schema, emitter=self.name,
                strict=self.strict, timestamp_field=self.timestamp_field,
                on_error=self.stats.inc_exception,
                project=self.project_columns)
            return batch
        cols, valid, bad = out
        keep = ~np.asarray(bad, dtype=np.bool_)
        n_bad = len(raws) - int(keep.sum())
        if n_bad:
            self.stats.inc_exception(
                "undecodable or uncastable payload", n=n_bad)
            self.stats.inc_dropped("decode_error", n=n_bad)
        ts = np.asarray(rtss, dtype=np.int64)
        if self.timestamp_field:
            vm = valid[self.timestamp_field]
            missing = keep & ~vm
            n_missing = int(missing.sum())
            if n_missing:
                self.stats.inc_exception(
                    f"missing timestamp field {self.timestamp_field}",
                    n=n_missing)
                keep &= vm
            ts = cols[self.timestamp_field]
        if not keep.any():
            return None
        all_keep = keep.all()
        columns = {k: (v if all_keep else v[keep]) for k, v in cols.items()}
        vout = {}
        for k, vm in valid.items():
            vs = vm if all_keep else vm[keep]
            if not vs.all():
                vout[k] = vs
        return ColumnBatch(
            n=int(keep.sum()), columns=columns, valid=vout,
            timestamps=(ts if all_keep else ts[keep]), emitter=self.name)

    def on_eof(self, eof: EOF) -> None:
        self._flush()
        self.broadcast(eof)

    def extra_pending(self) -> int:
        return self._pool.in_flight if self._pool is not None else 0

    def on_barrier(self, barrier) -> None:
        """Checkpoint barrier: flush pending rows and drain the decode
        ring BEFORE snapshotting the connector offset and forwarding. The
        offset already covers every ingested row, so any row still
        buffered here when the barrier passes would be downstream of the
        checkpoint cut yet behind the offset — lost on restore. A drain
        timeout therefore FAILS this checkpoint (no ack — a later barrier
        retries) while still forwarding the barrier so downstream
        aligners never stall, mirroring Node.on_barrier's snapshot-error
        path."""
        if not self._flush(final=True):
            self.stats.inc_exception(
                "decode ring drain timed out; checkpoint skipped")
            self.broadcast(barrier)
            return
        super().on_barrier(barrier)

    # source node's queue is only used for barriers/EOF injection
    def process(self, item: Any) -> None:
        self.ingest(item)
