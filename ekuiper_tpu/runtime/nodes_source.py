"""Source pipeline node — the fused analogue of the reference's source split
(connector → rate-limit → decode → preprocessor, planner_source.go:35-197).

A SourceNode owns a connector (io registry), decodes payloads via the
converter, coerces to the stream schema (preprocessor semantics incl.
event-time extraction from the TIMESTAMP option), accumulates rows into
columnar micro-batches (size/linger bounded), and emits ColumnBatch — the
TPU-native ingest form. Micro-batching here is what turns the reference's
per-tuple goroutine hops into whole-batch device work.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..data import cast
from ..data.batch import ColumnBatch, from_tuples
from ..data.rows import Tuple
from ..data.types import Schema
from ..utils import timex
from ..utils.infra import logger
from .events import EOF
from .node import Node


class SourceNode(Node):
    def __init__(
        self,
        name: str,
        connector,  # io.Source instance
        schema: Optional[Schema] = None,
        timestamp_field: str = "",
        strict_validation: bool = False,
        micro_batch_rows: int = 4096,
        linger_ms: int = 10,
        buffer_length: int = 1024,
        emit_batches: bool = True,
        converter=None,  # io.converters.Converter for bytes payloads
        project_columns=None,  # column-pruning set (planner/optimizer.py)
    ) -> None:
        super().__init__(name, op_type="source", buffer_length=buffer_length)
        self.connector = connector
        self.converter = converter
        self.schema = schema
        self.timestamp_field = timestamp_field
        self.strict = cast.STRICT if strict_validation else cast.CONVERT_ALL
        self.micro_batch_rows = micro_batch_rows
        self.linger_ms = linger_ms
        self.project_columns = (set(project_columns)
                                if project_columns is not None else None)
        if self.project_columns is not None and self.schema is not None \
                and not self.schema.schemaless:
            # restrict the declared schema too: from_tuples materializes a
            # column per schema field, so pruning must reach it or typed
            # streams would re-grow zero-filled columns at batch build
            from ..data.types import Schema

            self.schema = Schema(fields=[
                f for f in self.schema.fields
                if f.name in self.project_columns])
        self.emit_batches = emit_batches
        self._pending: List[Tuple] = []
        self._pending_lock = threading.Lock()
        self._linger_timer = None

    # ------------------------------------------------------------------ ingest
    def on_open(self) -> None:
        self.connector.open(self.ingest)

    def on_close(self) -> None:
        try:
            self.connector.close()
        except Exception as exc:
            logger.debug("source %s close error: %s", self.name, exc)
        self._flush()

    def ingest(self, payload: Any, metadata: Optional[Dict[str, Any]] = None) -> None:
        """Connector callback: raw bytes (decoded here via the stream's
        FORMAT converter), dict, list of dicts, or Tuple."""
        now = timex.now_ms()
        if isinstance(payload, (bytes, bytearray)):
            if self.converter is None:
                self.stats.inc_exception("bytes payload but no converter")
                return
            try:
                payload = self.converter.decode(bytes(payload))
            except Exception as exc:
                self.stats.inc_exception(f"decode error: {exc}")
                return
        rows: List[Tuple] = []
        if isinstance(payload, Tuple):
            rows = [payload]
        elif isinstance(payload, dict):
            rows = [Tuple(emitter=self.name, message=payload, timestamp=now,
                          metadata=metadata or {})]
        elif isinstance(payload, list):
            rows = [
                Tuple(emitter=self.name, message=m, timestamp=now,
                      metadata=metadata or {})
                for m in payload if isinstance(m, dict)
            ]
        elif payload is None:
            return
        else:
            self.stats.inc_exception(f"unsupported payload {type(payload)}")
            return
        self.stats.inc_in(len(rows))
        rows = [self._preprocess(t) for t in rows]
        rows = [t for t in rows if t is not None]
        if not rows:
            return
        if not self.emit_batches:
            for t in rows:
                self.emit(t)
            return
        with self._pending_lock:
            self._pending.extend(rows)
            full = len(self._pending) >= self.micro_batch_rows
        if full:
            self._flush()
        elif self._linger_timer is None or self._linger_timer.fired or self._linger_timer.stopped:
            self._linger_timer = timex.after(self.linger_ms, lambda ts: self._flush())

    def _preprocess(self, t: Tuple) -> Optional[Tuple]:
        """Schema validation/coercion + event-time extraction
        (reference: internal/topo/operator/preprocessor.go)."""
        if self.schema is not None and not self.schema.schemaless:
            msg = {}
            for f in self.schema.fields:
                if f.name in t.message:
                    try:
                        msg[f.name] = cast.to_typed(t.message[f.name], f, self.strict)
                    except cast.CastError as exc:
                        self.stats.inc_exception(str(exc))
                        return None
            t.message = msg
        if self.timestamp_field:
            v = t.message.get(self.timestamp_field)
            if v is None:
                self.stats.inc_exception(
                    f"missing timestamp field {self.timestamp_field}"
                )
                return None
            try:
                t.timestamp = cast.to_datetime_ms(v)
            except cast.CastError as exc:
                self.stats.inc_exception(str(exc))
                return None
        if self.project_columns is not None:
            # column pruning (planner/optimizer.py): drop unreferenced
            # fields before batching — smaller batches, tuples, uploads
            t.message = {k: v for k, v in t.message.items()
                         if k in self.project_columns}
        return t

    # ------------------------------------------------------------------ state
    def snapshot_state(self):
        """Rewindable sources (io/contract.py) checkpoint their offset so a
        restored rule resumes the stream where the snapshot cut it."""
        get_off = getattr(self.connector, "get_offset", None)
        if get_off is None:
            return None
        try:
            return {"offset": get_off()}
        except Exception:
            return None

    def restore_state(self, state: dict) -> None:
        rew = getattr(self.connector, "rewind", None)
        if rew is not None and state and "offset" in state:
            try:
                rew(state["offset"])
            except Exception as exc:
                self.stats.inc_exception(f"rewind failed: {exc}")

    def _flush(self) -> None:
        with self._pending_lock:
            if not self._pending:
                return
            rows, self._pending = self._pending, []
        batch = from_tuples(rows, schema=self.schema, emitter=self.name)
        self.emit(batch, count=batch.n)

    def on_eof(self, eof: EOF) -> None:
        self._flush()
        self.broadcast(eof)

    # source node's queue is only used for barriers/EOF injection
    def process(self, item: Any) -> None:
        self.ingest(item)
