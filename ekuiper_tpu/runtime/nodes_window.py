"""Host-path window operators — analogue of eKuiper's WindowOperator v1/v2
(internal/topo/node/window_op.go:235 execProcessingWindow,
event_window_trigger.go:112 execEventWindow) and WatermarkOp
(watermark_op.go:33-170).

These buffer rows and emit WindowTuples at triggers. They serve the window
types / options the fused device kernel doesn't take (sliding, session,
state, event-time, trigger conditions); the aggregation over their output
is still batch-vectorized downstream where possible.
"""
from __future__ import annotations

import numpy as np

from typing import Any, List, Optional

from ..data.batch import ColumnBatch
from ..data.rows import Row, Tuple, WindowRange, WindowTuples
from ..sql import ast
from ..sql.eval import Evaluator
from ..utils import timex
from .events import EOF, Trigger, Watermark
from .node import Node


class WatermarkNode(Node):
    """Generates watermarks from event timestamps, drops late events
    (reference: watermark_op.go — lateTolerance drop + ordered release)."""

    def __init__(self, name: str, late_tolerance_ms: int = 0, **kw) -> None:
        super().__init__(name, op_type="op", **kw)
        self.late_tolerance = late_tolerance_ms
        self.max_ts = 0
        self.dropped = 0

    def process(self, item: Any) -> None:
        if isinstance(item, ColumnBatch):
            # columnar path: late-drop by mask, order by timestamp, forward
            # the batch WITHOUT exploding to rows (the columnar spine
            # continues into the window operator)
            ts = item.timestamps
            if ts is None:
                ts = np.zeros(item.n, dtype=np.int64)
            wm = self.max_ts - self.late_tolerance
            keep = ts >= wm
            n_late = int(item.n - keep.sum())
            if n_late:
                self.dropped += n_late
                self.stats.inc_dropped("stale_watermark", n=n_late)
                idx = np.nonzero(keep)[0]
                item = item.take(idx)
                ts = ts[idx]
            if item.n:
                self.max_ts = max(self.max_ts, int(ts.max()))
                order = np.argsort(ts, kind="stable")
                if not np.array_equal(order, np.arange(item.n)):
                    item = item.take(order)
                self.emit(item, count=item.n)
        elif isinstance(item, Row):
            if item.timestamp < self.max_ts - self.late_tolerance:
                self.dropped += 1
                self.stats.inc_dropped("stale_watermark")
            else:
                self.max_ts = max(self.max_ts, item.timestamp)
                self.emit(item)
        else:
            self.emit(item)
            return
        new_wm = self.max_ts - self.late_tolerance
        if new_wm > 0:
            self.broadcast(Watermark(ts=new_wm))

    def watermark_ts(self) -> Optional[int]:
        """Current watermark (None until one is established) — the health
        plane's watermark-lag probe (observability/health.py) reads this
        per tick; lag = engine clock − watermark. Mirrors the broadcast
        guard in `_on`: a tolerance-adjusted value ≤ 0 was never emitted
        downstream and must not read as a (wildly lagging) watermark."""
        wm = self.max_ts - self.late_tolerance
        if wm <= 0:
            return None
        return wm

    def snapshot_state(self) -> Optional[dict]:
        return {"max_ts": self.max_ts}

    def restore_state(self, state: dict) -> None:
        self.max_ts = state.get("max_ts", 0)


class WindowNode(Node):
    """Buffering window operator, all types, processing- or event-time."""

    def __init__(
        self,
        name: str,
        window: ast.Window,
        is_event_time: bool = False,
        rule_id: str = "",
        **kw,
    ) -> None:
        super().__init__(name, op_type="op", **kw)
        self.window = window
        self.is_event_time = is_event_time
        self.ev = Evaluator(rule_id=rule_id)
        self.buffer: List[Row] = []
        self.length_ms = window.length_ms()
        self.interval_ms = window.interval_ms()
        self.delay_ms = window.delay_ms()
        self.wt = window.window_type
        # count window
        self.count_len = window.length or 0
        self.count_interval = window.interval or self.count_len
        self._rows_since_emit = 0
        # session
        self._session_start: Optional[int] = None
        self._session_timer = None
        self._session_cap_timer = None
        # state window
        self._state_open = False
        # event-time bookkeeping
        self._next_emit_end: Optional[int] = None
        self._timer = None
        # event-time sliding: rows that already triggered their window
        # (id-keyed — mutating data objects leaked state, VERDICT weak#7)
        self._slid_ids: set = set()
        # columnar spine: tumbling/hopping buffer ColumnBatches whole and
        # explode to rows only at emit, only for selected rows. A window
        # FILTER rides along when it compiles to a vectorized host closure;
        # otherwise the row path below handles everything.
        self._vfilter = None
        self._use_bbuf = self.wt in (
            ast.WindowType.TUMBLING_WINDOW, ast.WindowType.HOPPING_WINDOW)
        if window.filter is not None and self._use_bbuf:
            from ..sql.compiler import try_compile

            self._vfilter = try_compile(window.filter, mode="host")
            if self._vfilter is None:
                self._use_bbuf = False
        self.bbuf: List[ColumnBatch] = []

    # ----------------------------------------------------------------- open
    def on_open(self) -> None:
        if self.is_event_time:
            return
        if self.wt in (ast.WindowType.TUMBLING_WINDOW, ast.WindowType.HOPPING_WINDOW):
            self._schedule_next_tick()

    def on_close(self) -> None:
        for t in (self._timer, self._session_timer, self._session_cap_timer):
            if t is not None:
                t.stop()

    def _tick_interval(self) -> int:
        if self.wt == ast.WindowType.TUMBLING_WINDOW:
            return self.length_ms
        return self.interval_ms or self.length_ms

    def _schedule_next_tick(self) -> None:
        now = timex.now_ms()
        interval = self._tick_interval()
        # epoch-aligned boundaries like the reference's getAlignedWindowEndTime
        next_end = timex.align_to_window(now + 1, interval)
        self._timer = timex.after(
            next_end - now, lambda ts: self.put_control(Trigger(ts=ts))
        )

    # --------------------------------------------------------------- ingest
    def process(self, item: Any) -> None:
        if isinstance(item, ColumnBatch):
            if self._use_bbuf:
                self._ingest_batch(item)
                return
            rows: List[Row] = item.to_tuples()
        elif isinstance(item, Row):
            # single rows (incl. JoinTuples from lookup joins) keep the row
            # buffer; trigger paths merge it with the columnar buffer
            rows = [item]
        else:
            self.emit(item)
            return
        if self.window.filter is not None:
            rows = [r for r in rows if self.ev.eval_condition(self.window.filter, r)]
        for r in rows:
            self._ingest_row(r)

    # ------------------------------------------------------- columnar buffer
    def _ingest_batch(self, batch: ColumnBatch) -> None:
        """Tumbling/hopping: batches buffer WHOLE; no per-row work at
        ingest. Selection/eviction happen on the timestamp arrays at
        trigger time, and rows materialize only when a window emits."""
        if self._vfilter is not None and batch.n:
            try:
                mask = np.broadcast_to(np.asarray(
                    self._vfilter(batch.columns), dtype=np.bool_),
                    (batch.n,)).copy()
                for c in self._vfilter.columns:
                    # null filter columns exclude the row, matching the
                    # row evaluator and FilterNode (nodes_ops.py)
                    mask &= batch.is_valid(c)
            except Exception:
                mask = np.array([
                    self.ev.eval_condition(self.window.filter, r)
                    for r in batch.to_tuples()], dtype=np.bool_)
            if not mask.all():
                batch = batch.take(np.nonzero(mask)[0])
        if batch.n:
            self.bbuf.append(batch)

    def _bts(self, batch: ColumnBatch):
        if batch.timestamps is None:
            return np.zeros(batch.n, dtype=np.int64)
        return batch.timestamps

    def _bbuf_select(self, start: int, end: int) -> List[Row]:
        """Materialize rows with start <= ts < end (ts-ordered batches)."""
        out: List[Row] = []
        for batch in self.bbuf:
            ts = self._bts(batch)
            mask = (ts >= start) & (ts < end)
            if mask.all():
                out.extend(batch.to_tuples())
            elif mask.any():
                out.extend(batch.take(np.nonzero(mask)[0]).to_tuples())
        return out

    def _bbuf_evict_before(self, cutoff: int) -> None:
        kept: List[ColumnBatch] = []
        for batch in self.bbuf:
            ts = self._bts(batch)
            mask = ts >= cutoff
            if mask.all():
                kept.append(batch)
            elif mask.any():
                kept.append(batch.take(np.nonzero(mask)[0]))
        self.bbuf = kept

    def _bbuf_all_rows(self) -> List[Row]:
        out: List[Row] = []
        for batch in self.bbuf:
            out.extend(batch.to_tuples())
        return out

    def _ingest_row(self, r: Row) -> None:
        wt = self.wt
        if wt == ast.WindowType.COUNT_WINDOW:
            self.buffer.append(r)
            if len(self.buffer) > self.count_len:
                del self.buffer[: len(self.buffer) - self.count_len]
            self._rows_since_emit += 1
            if self._rows_since_emit >= self.count_interval:
                self._rows_since_emit = 0
                self._emit_window(list(self.buffer), WindowRange(0, timex.now_ms()))
            return
        if wt == ast.WindowType.STATE_WINDOW:
            if not self._state_open:
                if self.ev.eval_condition(self.window.begin_condition, r):
                    self._state_open = True
                    self.buffer = [r]
                return
            self.buffer.append(r)
            if self.ev.eval_condition(self.window.emit_condition, r):
                self._emit_window(self.buffer, WindowRange(0, timex.now_ms()))
                self.buffer = []
                self._state_open = False
            return
        if wt == ast.WindowType.SESSION_WINDOW and not self.is_event_time:
            now = timex.now_ms()
            if not self.buffer:
                self._session_start = now
                if self.length_ms > 0:
                    self._session_cap_timer = timex.after(
                        self.length_ms, lambda ts: self.put_control(Trigger(ts=ts, tag="cap"))
                    )
            self.buffer.append(r)
            if self._session_timer is not None:
                self._session_timer.stop()
            timeout = self.interval_ms or self.length_ms
            self._session_timer = timex.after(
                timeout, lambda ts: self.put_control(Trigger(ts=ts, tag="gap"))
            )
            return
        if wt == ast.WindowType.SLIDING_WINDOW and not self.is_event_time:
            now = timex.now_ms()
            self.buffer.append(r)
            self._evict_before(now - self.length_ms - self.delay_ms)
            should = True
            if self.window.trigger_condition is not None:
                should = self.ev.eval_condition(self.window.trigger_condition, r)
            if should:
                if self.delay_ms > 0:
                    t0 = now
                    timex.after(
                        self.delay_ms,
                        lambda ts: self.put_control(Trigger(ts=ts, tag=("delayed", t0))),
                    )
                else:
                    self._emit_window(
                        [x for x in self.buffer if x.timestamp > now - self.length_ms],
                        WindowRange(now - self.length_ms, now),
                    )
            return
        # tumbling/hopping (processing or event time), event-time session/sliding
        self.buffer.append(r)
        if self.is_event_time:
            return

    # -------------------------------------------------------------- triggers
    def on_trigger(self, trig: Trigger) -> None:
        wt = self.wt
        if wt in (ast.WindowType.TUMBLING_WINDOW, ast.WindowType.HOPPING_WINDOW):
            end = trig.ts
            start = end - self.length_ms
            if wt == ast.WindowType.TUMBLING_WINDOW:
                rows = self._bbuf_all_rows() + self.buffer
                self.bbuf = []
                self.buffer = []
            else:
                # windows are [start, end); the upper bound matters — a row
                # landing in the same ms as the tick must count once (in the
                # next window), not in both
                rows = self._bbuf_select(start, end) + [
                    r for r in self.buffer if start <= r.timestamp < end]
                cutoff = end - self.length_ms + (self.interval_ms or 0)
                self._bbuf_evict_before(cutoff)
                self._evict_before(cutoff)
            self._emit_window(rows, WindowRange(start, end))
            self._schedule_next_tick()
            return
        if wt == ast.WindowType.SESSION_WINDOW:
            if trig.tag == "gap" or trig.tag == "cap":
                if self.buffer:
                    self._emit_window(
                        self.buffer,
                        WindowRange(self._session_start or 0, trig.ts),
                    )
                    self.buffer = []
                if self._session_cap_timer is not None:
                    self._session_cap_timer.stop()
            return
        if wt == ast.WindowType.SLIDING_WINDOW and isinstance(trig.tag, tuple):
            _, t0 = trig.tag
            start = t0 - self.length_ms
            end = t0 + self.delay_ms
            rows = [x for x in self.buffer if start < x.timestamp <= end]
            self._emit_window(rows, WindowRange(start, end))
            self._evict_before(timex.now_ms() - self.length_ms - self.delay_ms)
            return

    def on_watermark(self, wm: Watermark) -> None:
        """Event-time triggering (event_window_trigger.go:30-112)."""
        if not self.is_event_time:
            self.broadcast(wm)
            return
        wt = self.wt
        if wt in (ast.WindowType.TUMBLING_WINDOW, ast.WindowType.HOPPING_WINDOW):
            interval = self._tick_interval()
            if self._next_emit_end is None:
                # first window end at the next aligned boundary past the
                # earliest buffered event
                candidates = [int(self._bts(b).min())
                              for b in self.bbuf if b.n]
                candidates += [r.timestamp for r in self.buffer]
                if not candidates:
                    self.broadcast(wm)
                    return
                self._next_emit_end = timex.align_to_window(
                    min(candidates) + 1, interval)
            while self._next_emit_end is not None and wm.ts >= self._next_emit_end:
                end = self._next_emit_end
                start = end - self.length_ms
                # [start, end): row at exactly `end` opens the next window
                rows = self._bbuf_select(start, end) + [
                    r for r in self.buffer if start <= r.timestamp < end]
                cutoff = (end if wt == ast.WindowType.TUMBLING_WINDOW
                          else end - self.length_ms + interval)
                self._bbuf_evict_before(cutoff)
                self._evict_before(cutoff)
                self._emit_window(rows, WindowRange(start, end))
                self._next_emit_end = end + interval
        elif wt == ast.WindowType.SLIDING_WINDOW:
            # trigger one window per event whose (ts + delay) has passed;
            # already-triggered rows tracked by identity, not by mutating
            # the data objects
            ready = [r for r in self.buffer if r.timestamp + self.delay_ms <= wm.ts
                     and id(r) not in self._slid_ids]
            for r in ready:
                t0 = r.timestamp
                rows = [
                    x for x in self.buffer
                    if t0 - self.length_ms < x.timestamp <= t0 + self.delay_ms
                ]
                if self.window.trigger_condition is None or self.ev.eval_condition(
                    self.window.trigger_condition, r
                ):
                    self._emit_window(
                        rows, WindowRange(t0 - self.length_ms, t0 + self.delay_ms)
                    )
                self._slid_ids.add(id(r))
            self._evict_before(wm.ts - self.length_ms - self.delay_ms)
            self._slid_ids &= {id(r) for r in self.buffer}
        elif wt == ast.WindowType.SESSION_WINDOW:
            timeout = self.interval_ms or self.length_ms
            self.buffer.sort(key=lambda r: r.timestamp)
            while self.buffer:
                # find a complete session fully below the watermark
                session: List[Row] = [self.buffer[0]]
                for r in self.buffer[1:]:
                    if r.timestamp - session[-1].timestamp > timeout:
                        break
                    session.append(r)
                last = session[-1].timestamp
                if last + timeout <= wm.ts:
                    self._emit_window(
                        session,
                        WindowRange(session[0].timestamp, last + timeout),
                    )
                    self.buffer = self.buffer[len(session):]
                else:
                    break
        self.broadcast(wm)

    def on_eof(self, eof: EOF) -> None:
        # flush whatever is buffered (trial/bounded runs)
        rows = list(self.buffer) + self._bbuf_all_rows()
        if rows:
            now = timex.now_ms()
            self._emit_window(rows, WindowRange(now - self.length_ms, now))
            self.buffer = []
            self.bbuf = []
        self.broadcast(eof)

    def occupancy_rows(self) -> int:
        """Rows buffered awaiting a trigger (row + columnar buffers) —
        the host window path's analogue of pane-ring occupancy, sampled
        by the health evaluator."""
        return len(self.buffer) + sum(b.n for b in self.bbuf)

    # ----------------------------------------------------------------- emit
    def _emit_window(self, rows: List[Row], wr: WindowRange) -> None:
        self.emit(WindowTuples(content=list(rows), window_range=wr))

    def _evict_before(self, ts: int) -> None:
        """Drop rows strictly before ts (rows at ts can still belong to a
        [ts, ...) window)."""
        if ts <= 0:
            return
        self.buffer = [r for r in self.buffer if r.timestamp >= ts]

    # ----------------------------------------------------------------- state
    def snapshot_state(self) -> Optional[dict]:
        rows = [r for r in self.buffer if isinstance(r, Tuple)]
        rows += [r for r in self._bbuf_all_rows() if isinstance(r, Tuple)]
        return {
            "buffer": [
                {"message": r.message, "timestamp": r.timestamp,
                 "emitter": r.emitter,
                 # __analytic_* overlays are computed upstream of the
                 # window; losing them on restore would make the evaluator
                 # re-run the analytic (double-advancing its state)
                 "cal_cols": dict(r.cal_cols),
                 # sliding windows: already-triggered rows must not
                 # re-trigger (and duplicate their window) after a restore
                 "slid": id(r) in self._slid_ids}
                for r in rows
            ],
            "rows_since_emit": self._rows_since_emit,
            "state_open": self._state_open,
            "next_emit_end": self._next_emit_end,
        }

    def restore_state(self, state: dict) -> None:
        restored = []
        self._slid_ids = set()
        for d in state.get("buffer", []):
            r = Tuple(emitter=d.get("emitter", ""), message=d["message"],
                      timestamp=d["timestamp"],
                      cal_cols=dict(d.get("cal_cols", {})))
            restored.append(r)
            if d.get("slid"):
                self._slid_ids.add(id(r))
        # columnarizing drops cal-col overlays; rows carrying __analytic_*
        # state stay in the row buffer after a restore
        if (self._use_bbuf and restored
                and not any(r.cal_cols for r in restored)):
            from ..data.batch import from_tuples

            # one batch per emitter: joins match rows by emitter, and a
            # single batch can only stamp one
            by_emitter: dict = {}
            for r in restored:
                by_emitter.setdefault(r.emitter, []).append(r)
            self.bbuf = [from_tuples(rows, emitter=em)
                         for em, rows in by_emitter.items()]
            self.buffer = []
        else:
            self.buffer = restored
        self._rows_since_emit = state.get("rows_since_emit", 0)
        self._state_open = state.get("state_open", False)
        self._next_emit_end = state.get("next_emit_end")
