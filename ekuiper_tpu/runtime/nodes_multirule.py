"""Multi-rule fused window node — N homogeneous rules, one device program.

Extends FusedWindowAggNode with a BatchedGroupBy kernel (leading rule axis,
parallel/multirule.py) and per-rule output routing: each attached rule gets
its own downstream entry (its own sink chain, stats, backpressure), while
ingest, key encode, upload, fold, and finalize happen ONCE for the group.
This is the TPU-native answer to the reference's 300-rules-on-one-stream
fan-out deployment (reference: test/benchmark/multiple_rules, shared source
instances internal/topo/subtopo.go).
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..data.rows import WindowRange
from ..parallel.multirule import BatchedGroupBy, RuleBatchSpec
from ..sql import ast
from .node import Node
from .nodes_fused import FusedWindowAggNode


class MultiRuleFusedNode(FusedWindowAggNode):
    def __init__(
        self,
        name: str,
        window: ast.Window,
        spec: RuleBatchSpec,
        dims: List[ast.FieldRef],
        capacity: int = 16384,
        micro_batch: int = 4096,
        **kw,
    ) -> None:
        self.spec = spec  # before super().__init__: _make_gb reads it
        super().__init__(name, window, spec.plan, dims, capacity=capacity,
                         micro_batch=micro_batch, **kw)
        # boundary emits go through the async worker: one stacked (R,S+1,K)
        # transfer per family is MBs and must not stall the fold stream
        self._async_mr = (self.wt == ast.WindowType.TUMBLING_WINDOW
                          and not self.is_event_time)
        #: rule_id -> downstream entry node (per-rule sink chain); also
        #: connect()-ed so control events (EOF, errors) broadcast to all
        self.rule_outputs: Dict[str, Node] = {}

    def _make_gb(self, plan, capacity: int, micro_batch: int, mesh):
        return BatchedGroupBy(self.spec, capacity=capacity,
                              n_panes=int(self.n_panes),
                              micro_batch=micro_batch)

    def add_rule_output(self, rule_id: str, entry: Node) -> None:
        self.rule_outputs[rule_id] = entry
        self.connect(entry)  # control events (EOF) reach every rule chain

    # ------------------------------------------------------------------- emit
    def _emit(self, wr: WindowRange) -> None:
        """Synchronous family emit (EOF flush / non-boundary paths)."""
        n_keys = self.kt.n_keys
        if n_keys == 0:
            return
        outs, act = self.gb.finalize(self.state, n_keys)  # (R, S, K), (R, K)
        self._emit_rules(outs, act, n_keys, wr)

    def _emit_mr_async(self, wr: WindowRange) -> None:
        """Window-boundary family emit: dispatch the ONE-launch stacked
        finalize on the immutable state snapshot and hand the (R, S+1, K)
        transfer — MBs per family — to the emit worker. The boundary then
        resets the pane and folding continues; a sync fetch here would
        stall every rider of the shared source for the transfer duration."""
        n_keys = self.kt.n_keys
        if n_keys == 0:
            self.last_emit_info = None
            return
        self._emit_async("mr", self.gb.finalize_begin(self.state, n_keys), wr)

    def _deliver_mr(self, arr: np.ndarray, n_keys: int,
                    wr: WindowRange) -> None:
        """Emit-worker delivery: slice the landed stacked array per rule.
        n_keys was captured at dispatch; keys are append-only so the first
        n_keys table entries still match the snapshot's slot ids."""
        from ..ops.groupby import apply_int_semantics

        outs = [arr[:, i, :n_keys] for i in range(len(self.plan.specs))]
        act = arr[:, -1, :n_keys]
        outs = apply_int_semantics(self.plan.specs, outs)
        self._emit_rules(outs, act, n_keys, wr)

    def _emit_rules(self, outs, act, n_keys: int, wr: WindowRange) -> None:
        dim_names = [d.name for d in self.dims]
        keys = self.kt.keys_slice(0, n_keys)
        keys_arr = np.empty(len(keys), dtype=np.object_)
        keys_arr[:] = keys
        for r, rid in enumerate(self.gb.rule_ids):
            out_node = self.rule_outputs.get(rid)
            if out_node is None:
                continue
            active = np.nonzero(act[r] > 0)[0]
            if len(active) == 0:
                continue
            dim_cols: Dict[str, np.ndarray] = {}
            if dim_names:
                sel = keys_arr[active]
                if len(dim_names) == 1:
                    dim_cols[dim_names[0]] = sel
                else:
                    for i, dn in enumerate(dim_names):
                        col = np.empty(len(active), dtype=np.object_)
                        col[:] = [k[i] for k in sel.tolist()]
                        dim_cols[dn] = col
            agg_cols = [o[r][active] for o in outs]
            if self.emit_columnar:
                cb = self.direct_emit.run_columnar(
                    dim_cols, agg_cols, wr.window_start, wr.window_end)
                if cb is not None and cb.n:
                    self.stats.inc_out(cb.n)
                    self.send_to(out_node, cb)
            else:
                msgs = self.direct_emit.run(
                    dim_cols, agg_cols, wr.window_start, wr.window_end)
                if msgs:
                    self.stats.inc_out(len(msgs))
                    # Always a list (same emission-type contract as
                    # FusedWindowAggNode._emit_direct).
                    self.send_to(out_node, msgs)

    # ------------------------------------------------------------------ state
    def restore_state(self, state: dict) -> None:
        keys = state.get("keys", [])
        self.kt.restore([tuple(k) if isinstance(k, list) else k for k in keys])
        partials = state.get("partials")
        if partials:
            host = {k: np.asarray(v, dtype=np.float32)
                    for k, v in partials.items()}
            cap = next(iter(host.values())).shape[2]  # (R, panes, cap, k)
            self.gb.capacity = cap
            self.kt.capacity = max(self.kt.capacity, cap)
            self.state = self.gb.state_from_host(host)
        self.cur_pane = state.get("cur_pane", 0)
        self._rows_in_window = state.get("rows_in_window", 0)
