"""Fused window→GROUP BY→aggregate device node — the TPU-native replacement
for the reference's WindowIncAggOperator (window_inc_agg_op.go) and the
window+aggregate+project interpreter chain of the hot path (SURVEY §3.2).

Handles processing-time TUMBLING and HOPPING windows and non-overlapping
COUNT windows whose aggregates all compile to the device kernel
(ops/aggspec.py eligibility). Per micro-batch: encode GROUP BY keys to slots
(host dictionary), fold columns into device partials (one jitted XLA program);
per trigger: finalize on device, one transfer, emit GroupedTuplesSet whose
groups carry precomputed agg_values — downstream HAVING/ORDER/PROJECT read
them without recomputation.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..data.batch import ColumnBatch
from ..data.rows import GroupedTuples, GroupedTuplesSet, Tuple, WindowRange
from ..ops.aggspec import (
    HLL_COL_PREFIX,
    KernelPlan,
    _call_key,
    _hll_encode_numeric,
    hash_column_for_hll,
)
from ..ops.groupby import DeviceGroupBy
from ..ops.keytable import KeyTable
from ..sql import ast
from ..utils import timex
from ..utils.infra import logger
from .events import EOF, Trigger
from .node import Node


class FusedWindowAggNode(Node):
    def __init__(
        self,
        name: str,
        window: ast.Window,
        plan: KernelPlan,
        dims: List[ast.FieldRef],
        capacity: int = 16384,
        micro_batch: int = 4096,
        rule_id: str = "",
        direct_emit=None,  # ops.emit.DirectEmitPlan — vectorized tail
        mesh=None,  # jax.sharding.Mesh — run the kernel sharded (parallel/)
        **kw,
    ) -> None:
        super().__init__(name, op_type="op", **kw)
        self.window = window
        self.plan = plan
        self.dims = dims
        self.direct_emit = direct_emit
        self.wt = window.window_type
        self.length_ms = window.length_ms()
        self.interval_ms = window.interval_ms()
        if self.wt == ast.WindowType.HOPPING_WINDOW:
            iv = max(self.interval_ms, 1)
            self.n_panes = max((self.length_ms + iv - 1) // iv, 1)
        else:
            self.n_panes = 1
        if mesh is not None:
            from ..parallel.sharded import ShardedGroupBy

            self.gb = ShardedGroupBy(
                plan, mesh, capacity=capacity, n_panes=int(self.n_panes),
                micro_batch=micro_batch,
            )
        else:
            self.gb = DeviceGroupBy(
                plan, capacity=capacity, n_panes=int(self.n_panes),
                micro_batch=micro_batch,
            )
        # sharded path may round capacity up for even shard division
        self.kt = KeyTable(self.gb.capacity)
        self.state = None
        self.cur_pane = 0
        self._timer = None
        # count window
        self.count_len = window.length or 0
        self._rows_in_window = 0
        self._spec_keys = [_call_key(s.call) for s in plan.specs]
        self._dtypes_seen = False

    # --------------------------------------------------------------- lifecycle
    def on_open(self) -> None:
        if self.state is None:  # keep checkpoint-restored partials
            self.state = self.gb.init_state()
        # register the trigger timer BEFORE the (slow) warmup compile so the
        # first window boundary is anchored at open time, not compile-end
        if self.wt in (ast.WindowType.TUMBLING_WINDOW, ast.WindowType.HOPPING_WINDOW):
            self._schedule_next_tick()

    def on_worker_start(self) -> None:
        self._warmup()

    def _warmup(self) -> None:
        """Compile fold+finalize before data arrives so the first window
        doesn't pay 1-40s of jit latency."""
        try:
            # no valid masks: matches the common typed-schema batch pytree so
            # the compiled executable is the one real folds will hit
            cols = {
                name: np.zeros(1, dtype=np.float32) for name in self.plan.columns
            }
            slots = np.zeros(1, dtype=np.int32)
            self.state = self.gb.fold(self.state, cols, slots,
                                      pane_idx=self.cur_pane)
            self.gb.finalize(self.state, 1)
            self.state = self.gb.reset_pane(self.state, self.cur_pane)
        except Exception as exc:
            logger.debug("fused warmup failed (non-fatal): %s", exc)

    def on_close(self) -> None:
        if self._timer is not None:
            self._timer.stop()

    def _tick_interval(self) -> int:
        if self.wt == ast.WindowType.TUMBLING_WINDOW:
            return self.length_ms
        return self.interval_ms or self.length_ms

    def _schedule_next_tick(self) -> None:
        now = timex.now_ms()
        interval = self._tick_interval()
        next_end = timex.align_to_window(now + 1, interval)
        self._timer = timex.after(
            next_end - now, lambda ts: self.inq.put(Trigger(ts=ts))
        )

    # ------------------------------------------------------------------- data
    def process(self, item: Any) -> None:
        if not isinstance(item, ColumnBatch):
            if isinstance(item, Tuple):
                # stray row path: wrap into a single-row batch
                from ..data.batch import from_tuples

                item = from_tuples([item], emitter=item.emitter)
            else:
                self.emit(item)
                return
        if item.n == 0:
            return
        if self.wt == ast.WindowType.COUNT_WINDOW:
            self._fold_count_window(item)
        else:
            self._fold(item)

    def _fold(self, batch: ColumnBatch, start: int = 0, end: Optional[int] = None) -> int:
        """Fold rows [start:end) of the batch; returns rows folded."""
        end = batch.n if end is None else end
        if end <= start:
            return 0
        idx = np.arange(start, end)
        sub = batch if (start == 0 and end == batch.n) else batch.take(idx)
        # encode group key
        key_cols = []
        for d in self.dims:
            col = sub.columns.get(d.name)
            if col is None:
                col = np.full(sub.n, None, dtype=np.object_)
            key_cols.append(col)
        if key_cols:
            slots, grew = self.kt.encode_multi(key_cols)
            if grew:
                self.state = self.gb.grow(self.state, self.kt.capacity)
        else:
            slots = np.zeros(sub.n, dtype=np.int32)
            if self.kt.n_keys == 0:
                self.kt.encode_column(np.array(["__all__"], dtype=np.object_))
        cols: Dict[str, np.ndarray] = {}
        valid: Dict[str, np.ndarray] = {}
        for name in self.plan.columns:
            if name.startswith(HLL_COL_PREFIX):
                # derived hashed copy for hll; raw column stays numeric for
                # any other spec / WHERE / FILTER that shares it
                raw = name[len(HLL_COL_PREFIX):]
                col = sub.columns.get(raw)
                if col is None:
                    cols[name] = np.full(sub.n, np.nan, dtype=np.float32)
                elif col.dtype == np.object_:
                    cols[name] = hash_column_for_hll(col)
                else:
                    cols[name] = _hll_encode_numeric(col)
                v = sub.valid.get(raw)
                if v is not None:
                    valid[name] = v
                continue
            col = sub.columns.get(name)
            if col is None:
                cols[name] = np.full(sub.n, np.nan, dtype=np.float32)
                continue
            if col.dtype == np.object_:
                # mixed/object numeric column: coerce, NaN for bad rows
                coerced = np.full(sub.n, np.nan, dtype=np.float32)
                for i, v in enumerate(col):
                    if isinstance(v, (int, float)) and not isinstance(v, bool):
                        coerced[i] = v
                cols[name] = coerced
            else:
                cols[name] = col
            v = sub.valid.get(name)
            if v is not None:
                valid[name] = v
        if not self._dtypes_seen:
            self.gb.observe_dtypes(cols)
            self._dtypes_seen = True
        self.state = self.gb.fold(self.state, cols, slots, valid, self.cur_pane)
        return sub.n

    def _fold_count_window(self, batch: ColumnBatch) -> None:
        pos = 0
        while pos < batch.n:
            room = self.count_len - self._rows_in_window
            take = min(room, batch.n - pos)
            self._fold(batch, pos, pos + take)
            self._rows_in_window += take
            pos += take
            if self._rows_in_window >= self.count_len:
                self._emit(WindowRange(0, timex.now_ms()))
                self.state = self.gb.reset_pane(self.state, 0)
                self._rows_in_window = 0

    # ---------------------------------------------------------------- trigger
    def on_trigger(self, trig: Trigger) -> None:
        end = trig.ts
        self._emit(WindowRange(end - self.length_ms, end))
        if self.wt == ast.WindowType.TUMBLING_WINDOW:
            self.state = self.gb.reset_pane(self.state, 0)
        else:
            # advance to the next pane; expire it (it held the oldest slice)
            self.cur_pane = (self.cur_pane + 1) % self.n_panes
            self.state = self.gb.reset_pane(self.state, self.cur_pane)
        self._schedule_next_tick()

    def on_eof(self, eof: EOF) -> None:
        now = timex.now_ms()
        self._emit(WindowRange(now - self.length_ms, now))
        if self.wt == ast.WindowType.TUMBLING_WINDOW:
            self.state = self.gb.reset_pane(self.state, 0)
        self.broadcast(eof)

    # ------------------------------------------------------------------- emit
    def _emit(self, wr: WindowRange) -> None:
        n_keys = self.kt.n_keys
        if n_keys == 0:
            return
        outs, act = self.gb.finalize(self.state, n_keys)
        active = np.nonzero(act > 0)[0]
        if len(active) == 0:
            return
        if self.direct_emit is not None:
            self._emit_direct(outs, active, wr)
            return
        # bulk-convert once (C speed) instead of per-slot numpy scalar access —
        # emit latency is dominated by this host loop at 10k+ groups
        active_list = active.tolist()
        out_lists = []
        for col in outs:
            sel = col[active]
            if np.issubdtype(sel.dtype, np.floating):
                sel = np.where(np.isnan(sel), None, sel.astype(object))
            out_lists.append(sel.tolist())
        groups: List[GroupedTuples] = []
        dim_names = [d.name for d in self.dims]
        single_dim = dim_names[0] if len(dim_names) == 1 else None
        spec_keys = self._spec_keys
        decode = self.kt.decode
        ts = wr.window_end
        for j, slot in enumerate(active_list):
            key = decode(slot)
            if single_dim is not None:
                msg = {single_dim: key}
            elif dim_names:
                msg = dict(zip(dim_names, key))
            else:
                msg = {}
            agg_values = {
                spec_keys[i]: out_lists[i][j] for i in range(len(spec_keys))
            }
            groups.append(
                GroupedTuples(
                    content=[Tuple(emitter="", message=msg, timestamp=ts)],
                    group_key=str(key), window_range=wr, agg_values=agg_values,
                )
            )
        self.emit(GroupedTuplesSet(groups=groups, window_range=wr))

    def _emit_direct(self, outs, active: np.ndarray, wr: WindowRange) -> None:
        """Vectorized tail: HAVING/ORDER/LIMIT/projection computed over the
        finalize arrays; emits the final output messages directly."""
        dim_names = [d.name for d in self.dims]
        dim_cols: Dict[str, np.ndarray] = {}
        if dim_names:
            keys = self.kt.decode_all()
            if len(dim_names) == 1:
                col = np.empty(len(active), dtype=np.object_)
                col[:] = [keys[s] for s in active.tolist()]
                dim_cols[dim_names[0]] = col
            else:
                sel = [keys[s] for s in active.tolist()]
                for i, dn in enumerate(dim_names):
                    col = np.empty(len(active), dtype=np.object_)
                    col[:] = [k[i] for k in sel]
                    dim_cols[dn] = col
        agg_cols = [col[active] for col in outs]
        msgs = self.direct_emit.run(
            dim_cols, agg_cols, wr.window_start, wr.window_end
        )
        if msgs:
            self.emit(msgs if len(msgs) > 1 else msgs[0], count=len(msgs))

    # ------------------------------------------------------------------ state
    def snapshot_state(self) -> Optional[dict]:
        host = self.gb.state_to_host(self.state)
        return {
            "keys": self.kt.decode_all(),
            "partials": {k: v.tolist() for k, v in host.items()},
            "cur_pane": self.cur_pane,
            "rows_in_window": self._rows_in_window,
        }

    def restore_state(self, state: dict) -> None:
        keys = state.get("keys", [])
        self.kt.restore([tuple(k) if isinstance(k, list) else k for k in keys])
        partials = state.get("partials")
        if partials:
            host = {k: np.asarray(v, dtype=np.float32) for k, v in partials.items()}
            cap = next(iter(host.values())).shape[1]
            self.gb.capacity = cap
            self.kt.capacity = max(self.kt.capacity, cap)
            self.state = self.gb.state_from_host(host)
        self.cur_pane = state.get("cur_pane", 0)
        self._rows_in_window = state.get("rows_in_window", 0)
