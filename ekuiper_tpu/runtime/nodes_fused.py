"""Fused window→GROUP BY→aggregate device node — the TPU-native replacement
for the reference's WindowIncAggOperator (window_inc_agg_op.go) and the
window+aggregate+project interpreter chain of the hot path (SURVEY §3.2).

Handles processing-time TUMBLING and HOPPING windows and non-overlapping
COUNT windows whose aggregates all compile to the device kernel
(ops/aggspec.py eligibility). Per micro-batch: encode GROUP BY keys to slots
(host dictionary), fold columns into device partials (one jitted XLA program);
per trigger: finalize on device, one transfer, emit GroupedTuplesSet whose
groups carry precomputed agg_values — downstream HAVING/ORDER/PROJECT read
them without recomputation.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..data.batch import ColumnBatch
from ..data.rows import GroupedTuples, GroupedTuplesSet, Tuple, WindowRange
from ..ops.aggspec import (
    HH_COL_PREFIX,
    HLL_COL_PREFIX,
    KernelPlan,
    ValueDict,
    _call_key,
    _hll_encode_numeric,
    hash_column_for_hll,
)
from ..ops.groupby import DeviceGroupBy
from ..ops.keytable import KeyTable
from ..sql import ast
from ..utils import timex
from ..utils.infra import logger
from .events import EOF, PreTrigger, Trigger
from .node import Node


def _host_mask(ce, columns: Dict[str, np.ndarray], n: int) -> np.ndarray:
    """Vectorized host condition -> per-row bool mask. A batch missing the
    referenced column (or with uncoercible types) evaluates to all-false —
    null semantics, matching the host row evaluator."""
    try:
        return np.broadcast_to(np.asarray(ce(columns), dtype=np.bool_), (n,))
    except Exception:
        return np.zeros(n, dtype=np.bool_)


def _enc_arr(a: np.ndarray) -> dict:
    """Compact checkpoint encoding for a numpy array: raw bytes + dtype."""
    import base64

    a = np.ascontiguousarray(a)
    return {"d": str(a.dtype),
            "b": base64.b64encode(a.tobytes()).decode("ascii")}


def _dec_arr(v) -> np.ndarray:
    import base64

    if isinstance(v, dict) and "b" in v:
        return np.frombuffer(base64.b64decode(v["b"]),
                             dtype=np.dtype(v["d"])).copy()
    return np.asarray(v)  # legacy list-encoded checkpoints


class FusedWindowAggNode(Node):
    def __init__(
        self,
        name: str,
        window: ast.Window,
        plan: KernelPlan,
        dims: List[ast.FieldRef],
        capacity: int = 16384,
        micro_batch: int = 4096,
        rule_id: str = "",
        direct_emit=None,  # ops.emit.DirectEmitPlan — vectorized tail
        mesh=None,  # jax.sharding.Mesh — run the kernel sharded (parallel/)
        prefinalize_lead_ms: int = 250,  # latency-hiding emit (prefinalize.py)
        emit_columnar: bool = False,  # window result stays a ColumnBatch
        prefinalize_backstop: bool = True,  # host backstop: boundaries never block
        tail_mode: str = "device",  # window-tail rows: "device" | "host"
        is_event_time: bool = False,  # watermark-driven panes (see below)
        late_tolerance_ms: int = 0,
        dev_ring_budget_mb: int = 256,  # sliding device-state HBM cap
        sliding_impl: str = "daba",  # "daba" rings | "refold" legacy path
        ring_layout=None,  # ops.slidingring.RingLayout chosen at plan time
        tier_budget_mb: float = 0.0,  # tiered key state HBM budget (0=off)
        tier_scan_ms: int = 0,  # tier placement cadence (0=window-derived)
        **kw,
    ) -> None:
        super().__init__(name, op_type="op", **kw)
        self.window = window
        self.plan = plan
        self.dims = dims
        self.direct_emit = direct_emit
        self.emit_columnar = emit_columnar
        self.wt = window.window_type
        self.length_ms = window.length_ms()
        self.interval_ms = window.interval_ms()
        self.is_event_time = is_event_time
        if is_event_time and self.wt in (ast.WindowType.SESSION_WINDOW,
                                         ast.WindowType.COUNT_WINDOW,
                                         ast.WindowType.STATE_WINDOW):
            # event-time sessions/counts/state windows: one pane (sessions
            # fold one complete session at a time, counts/state fold the
            # open span into pane 0 and reset per emission); the
            # bucket/pane routing below is tumbling/hopping machinery
            self.n_panes = 1
            self._next_emit_bucket: Optional[int] = None
            self._max_bucket: Optional[int] = None
            self._dirty: set = set()
        elif is_event_time:
            # event-time tumbling/hopping on device: each row routes to the
            # pane of its time bucket (bucket = ts // bucket_ms, pane =
            # bucket % P) and watermarks drive emission — pane count covers
            # every bucket that can be live at once (window span + late
            # tolerance + slack), so recycled panes are always emitted+reset
            # before reuse
            self.bucket_ms = (self.interval_ms
                              if self.wt == ast.WindowType.HOPPING_WINDOW
                              and self.interval_ms else self.length_ms)
            if self.length_ms % max(self.bucket_ms, 1) != 0:
                # pane decomposition needs bucket | length; flooring the
                # span would silently aggregate less than the declared
                # window (the planner routes such shapes to the exact host
                # path — direct construction fails loudly instead)
                raise ValueError(
                    f"event-time window length {self.length_ms}ms is not a "
                    f"multiple of the pane bucket {self.bucket_ms}ms")
            span = max(self.length_ms // max(self.bucket_ms, 1), 1)
            slack = -(-max(late_tolerance_ms, 0) // max(self.bucket_ms, 1))
            self.n_panes = max(span + slack + 2, 4)
            if self.n_panes > 255:
                # pane ids ship as uint8; the planner routes such shapes to
                # the host path (device_path_eligible) — direct construction
                # fails loudly rather than corrupting pane routing
                raise ValueError(
                    f"event-time window needs {self.n_panes} panes "
                    "(max 255): widen the hop interval or reduce "
                    "lateTolerance")
            self.window_span = span
            self._next_emit_bucket: Optional[int] = None
            self._max_bucket: Optional[int] = None
            # buckets holding unexpired data — empty windows skip their
            # device round trip entirely, and time gaps fast-forward in
            # O(1) instead of emitting per empty bucket
            self._dirty: set = set()
        elif self.wt == ast.WindowType.HOPPING_WINDOW:
            iv = max(self.interval_ms, 1)
            self.n_panes = max((self.length_ms + iv - 1) // iv, 1)
        elif self.wt == ast.WindowType.SLIDING_WINDOW:
            # Device-path sliding windows (reference:
            # internal/topo/node/window_op.go:741 row-triggered semantics,
            # EXACT): rows fold into fine time panes by row timestamp; a
            # trigger row t emits window (t-L, t+delay] as
            #   merge(panes fully inside) ⊕ scratch-refold of the two
            #   partial edge buckets from a host-side columnar row ring.
            # Positive refolds only — every agg kind stays exact (no
            # subtraction), min/max/hll included.
            self.delay_ms = window.delay_ms()
            # ring geometry is a PLAN-time decision (the planner passes the
            # layout it chose; direct construction derives the same one):
            # finer buckets shrink the per-trigger edge corrections,
            # bounded by the uint8 pane budget AND by HBM — see
            # ops/slidingring.py plan_ring_layout
            from ..ops.slidingring import ring_layout_for

            if ring_layout is None:
                ring_layout = ring_layout_for(
                    window, plan, capacity=capacity,
                    budget_mb=dev_ring_budget_mb)
            self._ring_layout = ring_layout
            self.bucket_ms = ring_layout.bucket_ms
            self.n_ring_panes = ring_layout.n_ring_panes
            self.n_panes = ring_layout.n_panes
            self._scratch_pane = ring_layout.scratch_pane
            if sliding_impl not in ("daba", "refold"):
                raise ValueError(
                    f"slidingImpl must be 'daba' or 'refold', "
                    f"got {sliding_impl!r}")
            self._pane_bucket: Dict[int, int] = {}  # pane -> bucket held
            self._ring: Dict[int, list] = {}  # bucket -> [(cols,valid,slots,ts)]
            # device-side cache of the SAME segments (pre-padded fold
            # inputs kept alive on device): the trigger-time edge refold
            # then uploads one (mb,) bool mask per segment instead of
            # re-uploading the rows — the r04 paced 407ms p50 was mostly
            # this re-upload + its device folds. Entries align 1:1 with
            # _ring lists (None = no device copy, e.g. after restore).
            self._dev_ring: Dict[int, list] = {}
            # HBM budget for the cache: each qualifying batch pins
            # mb-padded float32 buffers per column for the whole ring
            # retention window, which at high batch rates on long windows
            # is GBs — past the cap the OLDEST entries drop to None and
            # their refolds fall back to the exact host path
            self.dev_ring_budget_bytes = int(dev_ring_budget_mb) << 20
            self._dev_ring_bytes = 0
            from collections import deque as _deque

            self._dev_ring_fifo = _deque()  # (bucket, idx, nbytes) in age order
            self._bucket_max_ts: Dict[int, int] = {}
            self._ring_max_bucket = -1
            self._pending_slides: Dict[int, int] = {}  # t -> fire_at_ms
            self._trigger_host = None
            if window.trigger_condition is not None:
                from ..sql.compiler import try_compile as _try_compile

                self._trigger_host = _try_compile(
                    window.trigger_condition, mode="host")
                if self._trigger_host is None:
                    raise ValueError(
                        "sliding device path needs a vectorizable OVER "
                        "(WHEN ...) trigger condition")
            else:
                raise ValueError(
                    "sliding device path requires a trigger condition: "
                    "per-row emission at device batch rates must be gated "
                    "(the exact host path handles unconditional sliding)")
        else:
            self.n_panes = 1
        if self.wt == ast.WindowType.STATE_WINDOW:
            # Condition-bounded windows on the device (reference: host
            # WindowNode STATE semantics — a begin-condition row opens the
            # window, rows fold until an emit-condition row closes it,
            # inclusive). Conditions evaluate VECTORIZED on the host
            # columns; only the open spans upload and fold.
            from ..sql.compiler import try_compile as _try_compile

            self._begin_host = _try_compile(window.begin_condition,
                                            mode="host")
            self._emitc_host = _try_compile(window.emit_condition,
                                            mode="host")
            if self._begin_host is None or self._emitc_host is None:
                raise ValueError(
                    "state device path needs vectorizable begin/emit "
                    "conditions (the host path handles the rest)")
            self._state_open = False
        if self.wt == ast.WindowType.SESSION_WINDOW:
            # Processing-time SESSION windows on the device (reference
            # semantics window_op.go: session is per-STREAM — any row
            # extends the session; gap silence or the length cap closes
            # it): rows fold into the single pane exactly like tumbling,
            # and the gap/cap timers drive emission + reset.
            # EVENT-time sessions buffer columnar batches and resolve the
            # session structure at each watermark with vectorized numpy
            # timestamp logic (argsort + diff > gap), then fold each
            # complete session on device and finalize — exact parity with
            # the host path's sort/scan (nodes_window.py on_watermark),
            # with the aggregation on the device instead of Python rows
            # (ref window_inc_agg_op.go:616).
            self.gap_ms = self.interval_ms or self.length_ms
            self._session_open = False
            self._session_start = 0
            self._last_row_ms = 0
            # stale-trigger guard: gap/cap triggers carry the session id
            # they were armed for; a trigger for a dead session is ignored
            self._session_id = 0
            self._gap_timer = None
            self._gap_gen = 0  # arm generation: one live gap check at a time
            self._cap_timer = None
            self._evs_batches: List[ColumnBatch] = []  # event-time buffer
        # heavy_hitters: per-column reversible dictionaries (codes -> values)
        # + the spec index -> raw column map for emit-time decoding. The hh
        # component is wide (sketches.HH_SIZE floats/key), so start small and
        # grow on demand instead of allocating the full default capacity.
        self._hh_cols: Dict[int, str] = {
            i: next(iter(s.arg.columns))[len(HH_COL_PREFIX):]
            for i, s in enumerate(plan.specs)
            if s.kind == "heavy_hitters"
        }
        self._hh_dicts: Dict[str, ValueDict] = {}
        self._hh_overflow_warned: set = set()
        if self._hh_cols and capacity > 2048:
            capacity = 2048
        # tiered key state (ops/tierstore.py, docs/TIERED_STATE.md):
        # geometry chosen here at plan/construction time from the HBM
        # budget and the actual pane count, like the sliding ring layout.
        # Eligible shapes: tumbling/hopping (processing or event time —
        # spilled per-pane partials stay exact across demotion windows)
        # and sliding (quiescent-only demotion). heavy_hitters plans and
        # mesh kernels keep the untiered path.
        self.tier = None
        self._tier_layout = None
        if tier_budget_mb and mesh is None and not self._hh_cols and \
                self.wt in (ast.WindowType.TUMBLING_WINDOW,
                            ast.WindowType.HOPPING_WINDOW,
                            ast.WindowType.SLIDING_WINDOW):
            from ..ops.tierstore import plan_tier_layout

            self._tier_layout = plan_tier_layout(
                plan, int(self.n_panes), capacity, float(tier_budget_mb),
                scan_interval_ms=int(tier_scan_ms),
                window_ms=self.interval_ms or self.length_ms)
            if self._tier_layout is not None:
                # the cold tier pins resident keys at the hot target, so
                # every per-capacity allocation (group-by state, sliding
                # rings — what lets a wide-hll rule keep DABA inside
                # slidingDevRingMb) builds at the capped capacity;
                # growth past it stays possible but becomes the last
                # resort the recycler works to avoid
                capacity = min(capacity,
                               self._tier_layout.hot_capacity())
        self.gb = self._make_gb(plan, capacity, micro_batch, mesh)
        # sliding implementation: DABA rings by default (constant-time
        # trigger emission, ops/slidingring.py), the legacy refold path as
        # the parity/escape-hatch fallback (`slidingImpl` rule option)
        self.ring = None
        self._ring_dev = None
        self.sliding_impl: Optional[str] = None
        if self.wt == ast.WindowType.SLIDING_WINDOW:
            self.sliding_impl = self._choose_sliding_impl(sliding_impl)
        # sharded path may round capacity up for even shard division
        self.kt = KeyTable(self.gb.capacity)
        if self._tier_layout is not None and \
                getattr(self.gb, "track_touch", False):
            from ..ops.tierstore import TierManager

            key_name = (dims[0].name if len(dims) == 1
                        and getattr(dims[0], "name", None) else None)
            sliding = self.wt == ast.WindowType.SLIDING_WINDOW
            self.tier = TierManager(
                self.gb, self.kt, self._tier_layout,
                rule_id=rule_id, key_name=key_name,
                submit=self._tier_submit,
                # sliding demotes only quiescent keys: idle past the whole
                # ring/row retention, so no pane, ring partial, or host
                # ring row still references the recycled slot
                quiescent_only=sliding,
                min_idle_ms=((self.n_ring_panes + 10) * self.bucket_ms
                             if sliding else 0),
                on_tier_event=self._on_tier_event)
        else:
            self._tier_layout = None  # kernel form ineligible (multirule)
        # shared-source fan-out slot reuse: None = undecided, True = our kt
        # mirrors the subtopo's neutral table, False = self-encode forever.
        # Tiered slot recycling breaks the neutral table's dense
        # insertion-order contract, so tiered rules always self-encode.
        self._shared_slots_ok = None if self.tier is None else False
        self._shared_nkt = None  # the neutral table our slots come from
        self._prep_registered = False  # upload spec handed to the prep ctx
        self.state = None
        self.cur_pane = 0
        self._timer = None
        # count window
        self.count_len = window.length or 0
        self._rows_in_window = 0
        self._spec_keys = [_call_key(s.call) for s in plan.specs]
        self._dtypes_seen = False
        # latency-hiding emit: pre-issued device finalize + host tail shadow.
        # Only for timer-driven windows (boundary known in advance), plans
        # whose expressions have numpy twins, and non-collective kernels.
        # _pipeline holds up to 3 (PendingFinalize, HostShadow) pairs: a
        # fresher pre-issue is stacked when an earlier fetch is still in
        # flight at the next pre-trigger (tunnel jitter), and the boundary
        # uses the newest READY one — emit latency decouples from device
        # round-trip variance.
        self._pipeline = []
        self._pre_timers = []
        self.prefinalize_lead_ms = int(prefinalize_lead_ms)
        self._prefinalize_ok = (
            not is_event_time  # watermark boundaries aren't clock-known
            and self.prefinalize_lead_ms > 0
            and self.gb.supports_prefinalize
            and plan.host_foldable
            # hh boundaries use the compact device-recovery finalize — the
            # pre-issue would ship the raw HH_SIZE-wide sketch instead
            and not self._hh_cols
            and self.wt in (ast.WindowType.TUMBLING_WINDOW,
                            ast.WindowType.HOPPING_WINDOW)
            and self.prefinalize_lead_ms < self._tick_interval()
        )
        # Window-tail handling after a pre-issue freezes a snapshot:
        #
        # "device" (default): tail rows keep folding into the device state
        #   AND into the pre-issue's host shadow. The emitted window =
        #   snapshot ⊕ shadow counts each row exactly once (the snapshot
        #   excludes tail rows, the shadow holds exactly them); the device
        #   state stays COMPLETE at all times, so checkpoints need no
        #   flush-back and hopping panes retain tail rows for later windows.
        #
        # "host": tumbling-only. Tail rows die at the boundary reset anyway,
        #   so once a pre-issue freezes the snapshot they fold into host
        #   shadows ONLY — zero upload traffic competing with the result
        #   fetch. Useful when the host→device link is SATURATED (a tunnel
        #   at full ingest rate): the fetch needs a quiet channel to land.
        #   A checkpoint barrier in the frozen span flushes the frozen
        #   span's shadow back to the device (absorb).
        if tail_mode not in ("device", "host"):
            raise ValueError(
                f"tail_mode must be 'device' or 'host', got {tail_mode!r}")
        self.tail_mode = tail_mode
        self._tail_host_only = (
            self._prefinalize_ok and tail_mode == "host"
            and self.wt == ast.WindowType.TUMBLING_WINDOW
        )
        self._device_frozen = False  # set at the first real pre-issue
        # backstop: every window opens with an always-ready identity entry
        # plus a window-spanning shadow, so a boundary NEVER blocks on the
        # device link — the device result is preferred whenever its fetch
        # lands (steady state), the backstop serves link-stall windows.
        # Tumbling-only: a hopping window spans panes older than the last
        # boundary, which a boundary-started shadow cannot represent.
        self._backstop_ok = (
            self._prefinalize_ok
            and self.wt == ast.WindowType.TUMBLING_WINDOW
        )
        self._backstop = bool(prefinalize_backstop) and self._backstop_ok
        # COUNT-window async emission: the boundary dispatches the device
        # finalize on an immutable state snapshot, resets, and keeps folding;
        # a worker thread fetches + emits when the result lands. Emission
        # latency (one device round trip) stops stalling ingest — essential
        # at 1M-key cardinality where the finalize fetch is MBs. Barriers
        # and EOF drain the queue first, so ordering contracts hold.
        self._async_count = (
            self.wt == ast.WindowType.COUNT_WINDOW
            and self.gb.supports_prefinalize
            and not self._hh_cols
            and prefinalize_lead_ms > 0
        )
        # heavy_hitters timer boundaries also emit asynchronously: the
        # compact _hh_fin result is dispatched on the pre-reset snapshot
        # and delivered by the worker — the boundary never stalls a
        # sync fetch (2-3 tunnel RTTs) in the fold stream
        self._async_hh = (
            bool(self._hh_cols)
            and self.wt in (ast.WindowType.TUMBLING_WINDOW,
                            ast.WindowType.HOPPING_WINDOW)
            and not is_event_time
            and self.gb.supports_prefinalize
            and prefinalize_lead_ms > 0
        )
        # vmapped rule-group boundaries (MultiRuleFusedNode) also emit
        # asynchronously: one (R, S+1, keys) transfer per family is MBs,
        # and a sync fetch at the boundary stalls every rider's fold stream
        self._async_mr = False  # set by MultiRuleFusedNode
        # deferred boundary emission: when no pre-issue has landed at a
        # tumbling/hopping boundary (and no host backstop can serve), the
        # merge wait moves to the emit worker instead of stalling folds —
        # crucial for wide sketch finalizes (hll components are KBs/key)
        # on hopping windows, which have no backstop
        self._emit_late_async = (
            self.wt in (ast.WindowType.TUMBLING_WINDOW,
                        ast.WindowType.HOPPING_WINDOW)
            and not is_event_time
            and self.gb.supports_prefinalize
            and not self._hh_cols
        )
        self._emit_q = None
        self._emit_worker = None
        # worker-installed slot->key decode pin for deferred deliveries
        # (tiered slot recycling; see _keys_snapshot)
        self._kt_keys_override = None
        # telemetry: the last boundary found no landed device fetch
        self._storm = False
        # per-boundary record: {"source": "device"|"backstop"|"sync",
        #  "fetch_ms": issue→landed ms of the chosen fetch (-1 in flight),
        #  "ages_ms": [age of each real pre-issue at the boundary]}
        self.last_emit_info: Optional[dict] = None
        self._identity = None  # cached IdentityFinalize (immutable, per capacity)

    def _make_gb(self, plan, capacity: int, micro_batch: int, mesh):
        """Build the group-by kernel; subclasses override (MultiRuleFusedNode
        builds a BatchedGroupBy with the already-computed self.n_panes)."""
        if mesh is not None:
            from ..parallel.sharded import ShardedGroupBy

            return ShardedGroupBy(
                plan, mesh, capacity=capacity, n_panes=int(self.n_panes),
                micro_batch=micro_batch,
            )
        return DeviceGroupBy(
            plan, capacity=capacity, n_panes=int(self.n_panes),
            micro_batch=micro_batch,
            track_touch=getattr(self, "_tier_layout", None) is not None,
        )

    # --------------------------------------------------------------- lifecycle
    def on_open(self) -> None:
        if self.state is None:  # keep checkpoint-restored partials
            self.state = self.gb.init_state()
        # HBM accounting (observability/memwatch.py): the three pools this
        # node owns — group-by partial state, the sliding device batch
        # cache, and the host key table — become kuiper_device_bytes rows
        from ..observability import memwatch

        rule = getattr(self._topo, "rule_id", "") if self._topo else ""
        memwatch.register(
            "groupby_state", self,
            lambda n: sum(int(getattr(a, "nbytes", 0) or 0)
                          for a in (n.state or {}).values()),
            rule=rule)
        memwatch.register("key_table", self,
                          lambda n: n.kt.approx_bytes(), rule=rule)
        if self.wt == ast.WindowType.SLIDING_WINDOW:
            memwatch.register("dev_ring", self,
                              lambda n: n._dev_ring_bytes, rule=rule)
            if self.sliding_impl == "daba":
                # the DABA partials replace the _dev_ring batch cache in
                # HBM — they get their own kuiper_device_bytes row so
                # /diagnostics/memory sees the ring state, not a silently
                # double-budgeted dev_ring
                memwatch.register("sliding_ring", self,
                                  lambda n: n.ring_dev_bytes(), rule=rule)
        # register the trigger timer BEFORE the (slow) warmup compile so the
        # first window boundary is anchored at open time, not compile-end
        if not self.is_event_time and self.wt in (
            ast.WindowType.TUMBLING_WINDOW, ast.WindowType.HOPPING_WINDOW
        ):
            self._schedule_next_tick()

    def on_worker_start(self) -> None:
        self._warmup()

    def _warmup(self) -> None:
        """Probe the AOT executable cache for every jit site this node
        will exercise, on a THROWAWAY state, before data arrives. Against
        a warm disk cache (runtime/aotcache.py) this is a deserialization
        sweep — tens of ms, zero traces; against a cold one it is the
        build (1-40s of jit latency the first window would otherwise
        pay). Runs inside aotcache.building() so the builds it triggers
        are accounted as deliberate, not serve-time misses. Must never
        touch self.state — it may hold partials restored from a
        checkpoint."""
        from . import aotcache

        self._warmup_stage = "init"
        try:
            with aotcache.building():
                self._warmup_probe()
        except Exception as exc:
            # a swallowed warmup failure is a guaranteed serve-time
            # compile stall on the first real window — count it
            # (kuiper_warmup_failures_total), leave a flight event, and
            # say which stage died so it bisects
            stage = getattr(self, "_warmup_stage", "?")
            rule = getattr(self._topo, "rule_id", "") if self._topo else ""
            logger.warning(
                "fused warmup failed at stage %r (rule %s will pay "
                "serve-time compiles): %s", stage, rule or "?", exc)
            aotcache.note_warmup_failure(rule, stage, exc)

    def _warmup_probe(self) -> None:
        # no valid masks: matches the common typed-schema batch pytree so
        # the compiled executable is the one real folds will hit
        # (dtype-correct per column — expression-IR derived columns
        # are int32, ops/groupby.py col_np_dtype)
        from ..ops.groupby import warmup_cols

        self._warmup_stage = "fold"
        cols = warmup_cols(self.plan)
        slots = np.zeros(1, dtype=np.int32)
        dummy = self.gb.init_state()
        if self.is_event_time or self.wt == ast.WindowType.SLIDING_WINDOW:
            # event-time and sliding folds ship per-row pane VECTORS
            # for multi-bucket batches and the SCALAR pane for
            # single-bucket ones (the in-order common case) — warm both
            # executables, and the traced-mask finalize
            dummy = self.gb.fold(dummy, cols, slots,
                                 pane_idx=np.zeros(1, dtype=np.int64))
            dummy = self.gb.fold(dummy, cols, slots, pane_idx=0)
            self._warmup_stage = "finalize"
            self.gb.finalize(dummy, 1, panes=[0])
            if self.wt == ast.WindowType.SLIDING_WINDOW:
                # implementation-aware trigger-path warmup: the DABA
                # rounds warm the ring kernels, the refold rounds warm
                # fold_masked — never a dead kernel's executable
                if self.sliding_impl == "daba":
                    self._warmup_stage = "ring"
                    self._warmup_ring(dummy)
                else:
                    # compile the mask-only edge refold (fold_masked)
                    # with the exact runtime pytree: pre-padded device
                    # inputs + (mb,) bool mask — a first real trigger
                    # must not pay a 20-40s jit stall mid-stream.
                    # force=True bypasses the small-batch HBM guard,
                    # which would silently reject this 1-row batch and
                    # skip the compile
                    dev = self._upload_sliding_inputs(
                        warmup_cols(self.plan),
                        {}, np.zeros(1, dtype=np.int32), force=True)
                    self._warmup_stage = "fold_masked"
                    if dev is not None:
                        mask = np.zeros(self.gb.micro_batch,
                                        dtype=np.bool_)
                        dummy = self.gb.fold_masked(
                            dummy, dev[3], dev[2], mask,
                            self.n_ring_panes)
        else:
            dummy = self.gb.fold(dummy, cols, slots,
                                 pane_idx=self.cur_pane)
            self._warmup_stage = "finalize"
            self.gb.finalize(dummy, 1)
        if self._prefinalize_ok:
            self._warmup_stage = "prefinalize"
            pending = self.gb.prefinalize_begin(dummy)
            self.gb.prefinalize_merge(pending, None, 1)
        if self._tail_host_only:
            self._warmup_stage = "absorb"
            # compile absorb with an identity (empty) shadow
            from ..ops.prefinalize import HostShadow

            hs = HostShadow(self.plan, self.gb.comp_specs, self.gb.capacity)
            dummy = self.gb.absorb(dummy, hs.data, 0)
        if self.tier is not None:
            self._warmup_stage = "tier"
            # compile the demote/promote sites so the first boundary
            # with a plan doesn't pay the jit stall
            dummy, pk = self.tier.ts.demote(
                dummy, np.zeros(1, dtype=np.int32))
            dummy = self.tier.ts.promote(
                dummy, np.asarray(pk)[:1], np.zeros(1, dtype=np.int32))
        self._warmup_stage = "reset_pane"
        self.gb.reset_pane(dummy, self.cur_pane)

    def _warmup_ring(self, dummy) -> None:
        """Probe/compile the DABA trigger path (advance/flip/query +
        the traced-mask components fallback) on throwaway state."""
        from ..ops.slidingring import QUERY_ADJ

        if self._ring_dev is None:  # follow a checkpoint-restored capacity
            self.ring.capacity = int(self.gb.capacity)
        ring = self.ring.init_state()
        ring = self.ring.advance(ring, dummy, 0, True, 0, False)
        ring = self.ring.flip(ring, dummy, 0,
                              np.zeros(self.n_ring_panes, dtype=np.bool_))
        pend = self.ring.query_begin(
            ring, dummy, body_on=False, f_on=False, f_slot=0,
            adj_slots=np.zeros(QUERY_ADJ, dtype=np.int32),
            adj_weights=np.zeros(QUERY_ADJ, dtype=np.float32),
            adj_mm=np.zeros(QUERY_ADJ, dtype=np.bool_))
        pend.get()
        self.gb.components_begin_dyn(
            dummy, np.zeros(self.gb.n_panes, dtype=np.bool_)).get()

    def on_close(self) -> None:
        if self._timer is not None:
            self._timer.stop()
        for t in self._pre_timers:
            t.stop()
        if self.wt == ast.WindowType.SESSION_WINDOW:
            for t in (self._gap_timer, self._cap_timer):
                if t is not None:
                    t.stop()
        self._drain_async_emits()
        if self._emit_q is not None and self._emit_worker is not None \
                and self._emit_worker.is_alive():
            self._emit_q.put(None)
            self._emit_worker.join(timeout=5)

    def _tick_interval(self) -> int:
        if self.wt == ast.WindowType.TUMBLING_WINDOW:
            return self.length_ms
        return self.interval_ms or self.length_ms

    def _schedule_next_tick(self) -> None:
        now = timex.now_ms()
        interval = self._tick_interval()
        next_end = timex.align_to_window(now + 1, interval)
        self._timer = timex.after(
            next_end - now, lambda ts: self.put_control(Trigger(ts=ts))
        )
        if self._prefinalize_ok:
            # two chances per boundary: the 2x-lead pre-issue covers tunnel
            # jitter, the 1x-lead one refreshes if the first already landed
            self._pre_timers = []
            lead = self.prefinalize_lead_ms
            for k in (2, 1):
                if next_end - now > k * lead:
                    self._pre_timers.append(timex.after(
                        next_end - now - k * lead,
                        lambda ts, end=next_end: self.put_control(PreTrigger(ts=end)),
                    ))

    # ------------------------------------------------------------------- data
    def process(self, item: Any) -> None:
        if not isinstance(item, ColumnBatch):
            if isinstance(item, Tuple):
                # stray row path: wrap into a single-row batch
                from ..data.batch import from_tuples

                item = from_tuples([item], emitter=item.emitter)
            else:
                self.emit(item)
                return
        if item.n == 0:
            return
        if self.wt == ast.WindowType.COUNT_WINDOW:
            self._fold_count_window(item)
        elif self.wt == ast.WindowType.SESSION_WINDOW:
            if self.is_event_time:
                # session structure resolves at watermark time: buffer the
                # COLUMNAR batch as-is (no device work yet — folds happen
                # per complete session so pane 0 is always one session)
                self._evs_batches.append(item)
            else:
                self._fold(item)
                self._touch_session()
        elif self.wt == ast.WindowType.STATE_WINDOW:
            self._fold_state_window(item)
        else:
            self._fold(item)

    def _fold(self, batch: ColumnBatch, start: int = 0, end: Optional[int] = None) -> int:
        """Fold rows [start:end) of the batch; returns rows folded."""
        end = batch.n if end is None else end
        if end <= start:
            return 0
        idx = np.arange(start, end)
        sub = batch if (start == 0 and end == batch.n) else batch.take(idx)
        if self.is_event_time and self.wt not in (
                ast.WindowType.COUNT_WINDOW, ast.WindowType.STATE_WINDOW):
            # event-time COUNT/STATE fold like processing time: the
            # upstream watermark node already late-dropped and ordered the
            # rows, and their boundaries are row-driven (count / condition
            # toggles), not bucket-driven
            return self._fold_event(sub)
        if self.wt == ast.WindowType.SLIDING_WINDOW:
            return self._fold_sliding(sub)
        return self._fold_rows(sub, self.cur_pane)

    def _shared_encode(self, sub: ColumnBatch,
                       frozen: bool) -> Optional[np.ndarray]:
        """Shared-source fan-out: reuse the subtopo's one-per-batch key
        encode (subtopo.py SharedPrepCtx) instead of re-encoding per rule.
        The neutral table's slot ids are dense insertion-ordered, so
        feeding our own table the same key sequence (keys_slice of the
        new tail) yields identical ids — our table stays self-contained
        for emit decode and checkpoints. Returns None (caller self-encodes)
        when no shared ctx rides the batch or our table diverged (e.g.
        restored from a checkpoint predating the shared pipeline)."""
        ctx = getattr(sub, "shared_ctx", None)
        if ctx is None or self._shared_slots_ok is False:
            return None
        key_name = getattr(self.dims[0], "name", None)
        if not key_name:
            self._shared_slots_ok = False
            return None
        try:
            slots, n_keys, nkt = ctx.encode(sub, key_name)
        except Exception as exc:
            logger.debug("%s: shared key encode failed (%s) — self-encoding",
                         self.name, exc)
            self._shared_slots_ok = False
            return None
        if self._shared_slots_ok is None:  # one-time compatibility check
            self._shared_slots_ok = self.kt.n_keys == 0 or (
                self.kt.decode_all() == nkt.keys_slice(0, self.kt.n_keys))
            if not self._shared_slots_ok:
                return None
        self._shared_nkt = nkt
        if self.kt.n_keys < n_keys:
            new = np.array(nkt.keys_slice(self.kt.n_keys, n_keys),
                           dtype=np.object_)
            _, grew = self.kt.encode_column(new)
            if grew and not frozen:
                self.state = self.gb.grow(self.state, self.kt.capacity)
        if self.kt.n_keys < n_keys:
            # truly diverged (sync could not reach the snapshot): self-
            # encode from now on. n_keys ABOVE the snapshot is normal with
            # the pipelined upload stage — pool workers may encode batch
            # k+1 before batch k's snapshot is consumed, so our table can
            # legitimately run ahead of an older batch's n_keys; its slot
            # values are all below the snapshot and stay valid.
            self._shared_slots_ok = False
            return None
        return slots

    def pane_occupancy(self) -> "Optional[float]":
        """Event-time pane-ring occupancy (dirty buckets / ring size),
        None on clock-driven paths where the ring has no backlog notion.
        Health-evaluator probe (observability/health.py): occupancy near
        1.0 means the watermark lags far enough that panes risk the
        counted `pane_recycle` loss mode. Session/count/state windows
        fold into ONE pane but track dirtiness per absolute time bucket
        — a dirty-count/1 ratio is not a recycle-risk fraction, so they
        report None like the clock-driven paths."""
        dirty = getattr(self, "_dirty", None)
        if dirty is None:
            return None
        if self.wt in (ast.WindowType.SESSION_WINDOW,
                       ast.WindowType.COUNT_WINDOW,
                       ast.WindowType.STATE_WINDOW):
            return None
        return len(dirty) / max(self.n_panes, 1)

    def prep_spec(self):
        """(key_name, kernel columns, micro_batch, derived, sharding,
        mesh_tag) for the ingest prep's upload stage — the ONE definition
        of what precompute() should build for this node (the planner
        registers it at plan time, the first _shared_device_inputs call
        covers un-plumbed paths). `derived` is (expr_tag, DerivedCol
        tuple): the expression IR's host-derived columns, pre-encoded and
        pre-uploaded by the pool under share keys that include the IR
        hash — plans whose expressions differ can never alias. Sharded
        kernels add their row sharding + mesh tag: the pool then places
        each padded column/slot vector ACROSS the mesh (per-shard H2D)
        under tag-suffixed share keys, so a sharded and an unsharded
        consumer of one stream can never alias an upload."""
        from ..sql.expr_ir import is_derived_expr_col

        key_name = (self.dims[0].name
                    if len(self.dims) == 1
                    and getattr(self.dims[0], "name", None) else None)
        # mesh placement only when the kernel actually CONSUMES device
        # inputs: a multi-process mesh can't device_put onto
        # non-addressable devices (ShardedGroupBy uses its own
        # local-slice _put and opts out of device inputs) — registering
        # its sharding would make every precompute() raise per batch
        shard_ok = (getattr(self.gb, "mesh_tag", "")
                    and getattr(self.gb, "accepts_device_inputs", False))
        return (key_name,
                [n for n in self.plan.columns
                 if not n.startswith(HLL_COL_PREFIX)
                 and not n.startswith(HH_COL_PREFIX)
                 and not is_derived_expr_col(n)],
                self.gb.micro_batch,
                ((self.plan.expr_tag, self.plan.derived)
                 if self.plan.derived else None),
                self.gb.batch_sharding if shard_ok else None,
                self.gb.mesh_tag if shard_ok else "")

    def _shared_device_inputs(self, sub: ColumnBatch, cols, valid, slots):
        """One device upload per column/slot vector for ALL fan-out
        consumers of this batch: pad to the static micro-batch shape once,
        device_put once, and let every rider fold from the same HBM
        buffers. Only plain numeric columns share (hll/hh derivations are
        node-specific); only single-chunk batches qualify (n <= micro_batch
        — guaranteed by micro-batch-aligned source flushes). Returns
        (dev_cols, dev_valid, dev_slots|None) or None."""
        ctx = getattr(sub, "shared_ctx", None)
        mb = self.gb.micro_batch
        if ctx is None or sub.n > mb or \
                not getattr(self.gb, "accepts_device_inputs", False):
            return None
        if not self._prep_registered:
            # hand the upload spec to the prep ctx once: from then on the
            # decode pool's upload stage pre-builds these device inputs and
            # every share() below is a cache hit off the fused worker
            self._prep_registered = True
            reg = getattr(ctx, "register_upload", None)
            if reg is not None:
                reg(*self.prep_spec())
        # canonical builders + key scheme shared with the prep ctx's
        # pool-side pre-upload (runtime/ingest.py): same keys, same bytes
        from ..sql.expr_ir import is_derived_expr_col
        from .ingest import (pad_col_for_device, pad_slots_for_device,
                             share_key, slot_wire_u16)

        dcols: Dict[str, Any] = {}
        dvalid: Dict[str, Any] = {}
        expr_tag = getattr(self.plan, "expr_tag", "")
        # mesh-aware uploads: a sharded kernel's inputs are placed with
        # its row sharding (per-shard H2D) under tag-suffixed share keys
        # — the replicated single-chip upload and the mesh placement can
        # never serve each other
        mesh_tag = getattr(self.gb, "mesh_tag", "")
        shd = getattr(self.gb, "batch_sharding", None) if mesh_tag else None

        def _key(*parts):
            return share_key(*parts, mesh_tag=mesh_tag)

        for name in self.plan.columns:
            if name.startswith(HLL_COL_PREFIX) or \
                    name.startswith(HH_COL_PREFIX):
                continue
            if is_derived_expr_col(name):
                # expression-IR derived column (already materialized in
                # `cols` by _build_kernel_inputs): share key carries the
                # plan's IR hash — a peer plan with different
                # expressions derives different bytes under a different
                # key, never a false cache hit
                host = cols[name]
                dt = str(host.dtype)
                dv, _ = sub.share(_key("dexpr", expr_tag, name, mb),
                                  lambda h=host, d=dt:
                                  pad_col_for_device(h, None, mb,
                                                     dtype=d,
                                                     sharding=shd))
                dcols[name] = dv
                continue
            src_col = sub.columns.get(name)
            if src_col is None or src_col.dtype == np.object_:
                continue
            host, vm = cols[name], valid.get(name)
            dv, dm = sub.share(_key("dcol", name, mb),
                               lambda h=host, v=vm:
                               pad_col_for_device(h, v, mb,
                                                  sharding=shd))
            dcols[name] = dv
            if dm is not None:
                dvalid[name] = dm
        dslots = None
        if slots is not None and self._shared_slots_ok and \
                len(self.dims) == 1:
            from ..ops.groupby import slot_dtype

            # dtype follows the NEUTRAL table's capacity (the slots' value
            # domain — and what the prep ctx keyed its pre-upload on, so
            # the lookup below hits); our own kt may be pre-sized larger
            # without invalidating a uint16 wire format. Sharded kernels
            # always ship int32 (the certified shard_map wire dtype).
            cap = (self._shared_nkt.capacity
                   if self._shared_nkt is not None else self.kt.capacity)
            u16 = slot_wire_u16(slot_dtype(cap) is np.uint16, mesh_tag)
            dslots = sub.share(
                _key("dslots", self.dims[0].name, mb, u16),
                lambda s=slots, u=u16: pad_slots_for_device(
                    s, mb, u, sharding=shd))
        if not dcols and dslots is None:
            return None
        return dcols, dvalid, dslots

    def _build_kernel_inputs(self, sub: ColumnBatch, frozen: bool = False):
        """Encode group keys + materialize the kernel's numeric columns and
        validity masks for `sub`. Returns (cols, valid, slots)."""
        key_cols = []
        for d in self.dims:
            col = sub.columns.get(d.name)
            if col is None:
                col = np.full(sub.n, None, dtype=np.object_)
            key_cols.append(col)
        if key_cols:
            slots = (self._shared_encode(sub, frozen)
                     if len(self.dims) == 1 else None)
            if slots is None:
                slots, grew = self.kt.encode_multi(key_cols)
                if grew and not frozen:
                    self.state = self.gb.grow(self.state, self.kt.capacity)
        else:
            slots = np.zeros(sub.n, dtype=np.int32)
            if self.kt.n_keys == 0:
                self.kt.encode_column(np.array(["__all__"], dtype=np.object_))
        cols: Dict[str, np.ndarray] = {}
        valid: Dict[str, np.ndarray] = {}
        # expression-IR derived columns (__sd_*/__ts32_*): dictionary
        # codes + rebased event time, host prep with self-describing
        # null sentinels (sql/expr_ir.py) — built once per batch here,
        # shared by the device upload AND the host shadows
        if self.plan.derived:
            from ..sql.expr_ir import materialize_derived

            materialize_derived(self.plan.derived, cols, sub,
                                expr_tag=self.plan.expr_tag)
        for name in self.plan.columns:
            if name in cols:
                continue  # derived expr column, just materialized
            if name.startswith(HLL_COL_PREFIX):
                # derived hashed copy for hll; raw column stays numeric for
                # any other spec / WHERE / FILTER that shares it
                raw = name[len(HLL_COL_PREFIX):]
                col = sub.columns.get(raw)
                if col is None:
                    cols[name] = np.full(sub.n, np.nan, dtype=np.float32)
                elif col.dtype == np.object_:
                    cols[name] = hash_column_for_hll(col)
                else:
                    cols[name] = _hll_encode_numeric(col)
                v = sub.valid.get(raw)
                if v is not None:
                    valid[name] = v
                continue
            if name.startswith(HH_COL_PREFIX):
                # heavy_hitters: dictionary-encode to dense codes the sketch
                # can bit-recover; the dict decodes them back at emit
                raw = name[len(HH_COL_PREFIX):]
                col = sub.columns.get(raw)
                vd = self._hh_dicts.setdefault(raw, ValueDict())
                if col is None:
                    cols[name] = np.full(sub.n, np.nan, dtype=np.float32)
                else:
                    cols[name] = vd.encode(col)
                    if vd.overflowed and raw not in self._hh_overflow_warned:
                        self._hh_overflow_warned.add(raw)
                        self.stats.inc_exception(
                            f"heavy_hitters dictionary overflow on '{raw}': "
                            "values past the code budget are no longer "
                            "counted")
                        logger.warning(
                            "heavy_hitters(%s): value dictionary exceeded "
                            "%d distinct values; new values are invisible "
                            "to the sketch", raw,
                            len(vd.snapshot()))
                v = sub.valid.get(raw)
                if v is not None:
                    valid[name] = v
                continue
            col = sub.columns.get(name)
            if col is None:
                cols[name] = np.full(sub.n, np.nan, dtype=np.float32)
                continue
            if col.dtype == np.object_:
                # mixed/object numeric column: coerce, NaN for bad rows
                coerced = np.full(sub.n, np.nan, dtype=np.float32)
                for i, v in enumerate(col):
                    if isinstance(v, (int, float)) and not isinstance(v, bool):
                        coerced[i] = v
                cols[name] = coerced
            else:
                cols[name] = col
            v = sub.valid.get(name)
            if v is not None:
                valid[name] = v
        if not self._dtypes_seen:
            self.gb.observe_dtypes(cols)
            self._dtypes_seen = True
        return cols, valid, slots

    def _fold_rows(self, sub: ColumnBatch, pane_arg) -> int:
        """Encode keys + build kernel columns + device fold for `sub`,
        folding into `pane_arg` (scalar pane or per-row pane vector).
        Stage accounting: "upload" covers key encode + kernel-input build +
        shared device puts (the host-side work feeding the link), "fold"
        the jitted fold dispatch (which carries the implicit H2D copy when
        inputs weren't pre-uploaded) — together with the source's "decode"
        these expose the ingest-pipeline balance per node."""
        import time as _time

        frozen = self._device_frozen and bool(self._pipeline)
        t0 = _time.perf_counter()
        cols, valid, slots = self._build_kernel_inputs(sub, frozen)
        dev = None
        if not frozen:
            if self.gb.capacity < self.kt.capacity:
                # deferred grow (keys first seen in an earlier frozen span)
                self.state = self.gb.grow(self.state, self.kt.capacity)
            if self.tier is not None:
                # admission point: returning demoted keys (this batch's
                # new-key log) get their spilled partials merged back
                # into their fresh slots before the fold lands
                self.state = self.tier.admit(self.state)
            dev = self._shared_device_inputs(sub, cols, valid, slots)
        t1 = _time.perf_counter()
        self.stats.observe_stage("upload", (t1 - t0) * 1e6, sub.n)
        if not frozen:
            if dev is not None:
                # shared uploads: device columns/slots computed once serve
                # every fan-out consumer; host copies still feed the shadows
                dcols, dvalid, dslots = dev
                self.state = self.gb.fold(
                    self.state, {**cols, **dcols},
                    dslots if dslots is not None else slots,
                    {**valid, **dvalid}, pane_arg, n_rows=sub.n)
            else:
                self.state = self.gb.fold(self.state, cols, slots, valid,
                                          pane_arg)
            self.stats.observe_stage(
                "fold", (_time.perf_counter() - t1) * 1e6, sub.n)
            if hasattr(self.gb, "note_rows"):
                # per-shard accounting (kuiper_shard_*): the kernel counts
                # host slot vectors itself; the prep path hands it DEVICE
                # slots, so count off the host copy here — and refresh the
                # key-occupancy hint either way
                if dev is not None and dev[2] is not None:
                    self.gb.note_rows(slots, sub.n, n_keys=self.kt.n_keys)
                else:
                    self.gb.n_keys_hint = self.kt.n_keys
        # every live shadow mirrors the fold (dedup: frozen-span retries and
        # the backstop may share shadow objects)
        seen = set()
        for _, shadow in self._pipeline:
            if id(shadow) not in seen:
                seen.add(id(shadow))
                shadow.fold(cols, slots, valid)
        return sub.n

    # ------------------------------------------------------------ event time
    def _fold_event(self, sub: ColumnBatch) -> int:
        """Per-row pane routing for event-time windows: bucket = ts //
        bucket_ms, pane = bucket % P. Rows for already-emitted buckets drop
        (their pane may be recycled). A batch spanning more buckets than the
        pane budget folds IN ORDER: fold what fits, emit the oldest pending
        window to free its pane, continue — so a recycled pane is always
        emitted+reset before new rows land in it."""
        ts = sub.timestamps
        if ts is None:
            ts = np.zeros(sub.n, dtype=np.int64)
        buckets = ts // self.bucket_ms
        if self._next_emit_bucket is None:
            self._next_emit_bucket = int(buckets.min())
        late = buckets < self._next_emit_bucket
        if late.any():
            n_late = int(late.sum())
            self.stats.inc_dropped("stale_watermark", n=n_late,
                                   detail="bucket already emitted")
            keep = np.nonzero(~late)[0]
            if len(keep) == 0:
                return 0
            sub = sub.take(keep)
            buckets = buckets[keep]
        self._max_bucket = max(int(buckets.max()),
                               self._max_bucket
                               if self._max_bucket is not None else -1)
        total = 0
        while sub.n:
            # pane-reuse safety: bucket b is foldable once bucket b-P
            # expired, i.e. b <= next_emit + P - W
            limit = (self._next_emit_bucket
                     + self.n_panes - self.window_span)
            mask = buckets <= limit
            idx = np.nonzero(mask)[0]
            if len(idx):
                seg = buckets[idx]
                ub = np.unique(seg)
                # single-bucket batch (in-order streams, bucket >> batch
                # span — the common case): scalar pane, no per-row pane
                # vector upload, the same fast executable as processing time
                pane_arg = (int(ub[0]) % self.n_panes if len(ub) == 1
                            else (seg % self.n_panes).astype(np.uint8))
                total += self._fold_rows(
                    sub if mask.all() else sub.take(idx), pane_arg)
                self._dirty.update(int(b) for b in ub)
            if mask.all():
                break
            # make room for the rest: emit data windows in order, jump
            # over empty stretches without device round trips. NOTE: rows
            # within late tolerance that arrive AFTER a pane-pressure
            # forced emission drop (counted) — bounded panes trade the
            # host path's unbounded buffering for device residence.
            rest = np.nonzero(~mask)[0]
            sub = sub.take(rest)
            buckets = buckets[rest]
            self._advance_one(int(buckets.min()))
        return total

    def _advance_one(self, needed_bucket: int) -> None:
        """Advance the emission cursor toward making `needed_bucket`
        foldable: emit the next window when it can contain data, otherwise
        JUMP the empty stretch in O(1) (an outlier timestamp must not spin
        one iteration per empty bucket)."""
        nxt = self._next_emit_bucket
        if not self._dirty:
            self._next_emit_bucket = max(
                nxt + 1,
                needed_bucket - (self.n_panes - self.window_span))
            return
        first = min(self._dirty)
        if nxt < first:
            # windows ending before `first` see no data
            self._next_emit_bucket = first
            return
        self._emit_event_bucket(nxt)

    def _emit_event_bucket(self, b: int) -> None:
        """Emit the window ENDING at bucket b's boundary (tumbling: just b;
        hopping: the window spanning buckets [b-W+1 .. b]), then expire the
        oldest pane of that window. Windows with no dirty buckets skip the
        device round trip entirely."""
        W = self.window_span
        window_buckets = range(b - W + 1, b + 1)
        has_data = any(x in self._dirty for x in window_buckets)
        n_keys = self.kt.n_keys
        end_ms = (b + 1) * self.bucket_ms
        wr = WindowRange(end_ms - self.length_ms, end_ms)
        panes = sorted({(x % self.n_panes) for x in window_buckets})
        if has_data and n_keys:
            outs, act = self.gb.finalize(self.state, n_keys, panes=panes)
            active = np.nonzero(act > 0)[0]
            if len(active):
                if self.direct_emit is not None:
                    self._emit_direct(outs, active, wr)
                else:
                    self._emit_grouped(outs, active, wr)
        # spilled keys demoted with data in this window's buckets emit
        # host-side (their pane epochs gate validity)
        self._emit_tier_extras(wr, panes=panes)
        expiring = b - W + 1
        if expiring in self._dirty:
            self._dirty.discard(expiring)
            self._reset_pane_tiered(expiring % self.n_panes)
        self._tier_boundary()
        self._next_emit_bucket = b + 1

    def on_watermark(self, wm) -> None:
        if self.is_event_time and self.wt == ast.WindowType.SESSION_WINDOW:
            self._evs_watermark(wm.ts)
            self.broadcast(wm)
            return
        if self.is_event_time and self._next_emit_bucket is not None:
            floor_b = wm.ts // self.bucket_ms - 1  # buckets fully below wm
            while self._next_emit_bucket <= floor_b:
                if not self._dirty:
                    self._next_emit_bucket = floor_b + 1
                    break
                first = min(self._dirty)
                if self._next_emit_bucket < first:
                    # nothing can emit before the first dirty bucket
                    self._next_emit_bucket = min(first, floor_b + 1)
                    continue
                self._emit_event_bucket(self._next_emit_bucket)
        self.broadcast(wm)

    def _fold_count_window(self, batch: ColumnBatch) -> None:
        pos = 0
        while pos < batch.n:
            room = self.count_len - self._rows_in_window
            take = min(room, batch.n - pos)
            self._fold(batch, pos, pos + take)
            self._rows_in_window += take
            pos += take
            if self._rows_in_window >= self.count_len:
                wr = WindowRange(0, timex.now_ms())
                if self._async_count:
                    self._emit_count_async(wr)
                else:
                    self._emit(wr)
                self.state = self.gb.reset_pane(self.state, 0)
                self._rows_in_window = 0

    # ---------------------------------------------------------- state window
    def _fold_state_window(self, batch: ColumnBatch) -> None:
        """Walk the batch's begin/emit toggle points (both masks computed
        in one vectorized pass); fold only open spans, emit + reset at
        each emit row (inclusive, mirroring the host row path — which does
        NOT evaluate the emit condition on the row that just opened the
        window)."""
        begin_m = _host_mask(self._begin_host, batch.columns, batch.n)
        emit_m = _host_mask(self._emitc_host, batch.columns, batch.n)
        pos = 0
        while pos < batch.n:
            scan_from = pos
            if not self._state_open:
                opens = np.nonzero(begin_m[pos:])[0]
                if not len(opens):
                    return  # closed and no begin row in the rest
                pos += int(opens[0])
                self._state_open = True
                scan_from = pos + 1  # opening row can't also close it
            closes = np.nonzero(emit_m[scan_from:])[0]
            if not len(closes):
                self._fold(batch, pos, batch.n)
                return  # window stays open across batches
            end = scan_from + int(closes[0]) + 1  # emit row is inclusive
            self._fold(batch, pos, end)
            self._emit(WindowRange(0, timex.now_ms()))
            self.state = self.gb.reset_pane(self.state, 0)
            self._state_open = False
            pos = end

    # ------------------------------------------------- event-time sessions
    def _evs_watermark(self, wm_ts: int) -> None:
        """Emit every COMPLETE leading session below the watermark — the
        vectorized mirror of the host path's sort/scan (nodes_window.py
        on_watermark SESSION branch): sort buffered rows by event time,
        split where consecutive gaps exceed the session gap, and emit a
        session only when last + gap <= wm. Each emitted session folds on
        device into pane 0 and finalizes through the normal emit tail."""
        if not self._evs_batches:
            return
        timeout = self.gap_ms
        big = (self._evs_batches[0] if len(self._evs_batches) == 1
               else ColumnBatch.concat(self._evs_batches))
        ts = big.timestamps
        if ts is None:
            ts = np.zeros(big.n, dtype=np.int64)
        order = np.argsort(ts, kind="stable")
        ts_sorted = ts[order]
        # session boundaries: index i ends a session when the next row is
        # more than `timeout` later
        bounds = np.nonzero(np.diff(ts_sorted) > timeout)[0]
        start = 0
        for end in [*(bounds + 1).tolist(), len(ts_sorted)]:
            last = int(ts_sorted[end - 1])
            if last + timeout > wm_ts:
                break  # leading incomplete session: stop, like the host
            sub = big.take(order[start:end])
            self._fold_rows(sub, 0)
            self._emit(WindowRange(int(ts_sorted[start]), last + timeout))
            self.state = self.gb.reset_pane(self.state, 0)
            start = end
        if start == 0:
            self._evs_batches = [big]  # compacted, nothing emitted
        elif start >= len(ts_sorted):
            self._evs_batches = []
        else:
            self._evs_batches = [big.take(np.sort(order[start:]))]

    def _evs_flush(self) -> None:
        """EOF flush: all buffered rows as ONE window [now-L, now) — host
        path parity (nodes_window.py on_eof)."""
        if not self._evs_batches:
            return
        big = (self._evs_batches[0] if len(self._evs_batches) == 1
               else ColumnBatch.concat(self._evs_batches))
        self._evs_batches = []
        now = timex.now_ms()
        self._fold_rows(big, 0)
        self._emit(WindowRange(now - self.length_ms, now))
        self.state = self.gb.reset_pane(self.state, 0)

    # ---------------------------------------------------------- session time
    def _touch_session(self) -> None:
        """A batch arrived: open the session if closed (arming the length
        cap) and record the last-row time. ONE inactivity-check timer per
        gap window — it re-arms itself against `_last_row_ms` instead of a
        timer per batch (a timer thread per batch would accumulate
        batch_rate x gap_seconds sleepers on the hot path)."""
        now = timex.now_ms()
        if not self._session_open:
            self._session_open = True
            self._session_start = now
            self._session_id += 1
            if self.length_ms > 0:
                sid = self._session_id
                self._cap_timer = timex.after(
                    self.length_ms,
                    lambda ts, _s=sid: self.put_control(
                        Trigger(ts=ts, tag=("session_cap", _s))))
        self._last_row_ms = now
        if (self._gap_timer is None or self._gap_timer.fired
                or self._gap_timer.stopped):
            self._arm_gap_check(self.gap_ms)

    def _arm_gap_check(self, delay_ms: int) -> None:
        # a fired-but-undrained previous check may still deliver its trigger;
        # the generation tag makes that stale trigger a no-op, so re-arming
        # here can never leave two live gap checks for one session
        if self._gap_timer is not None:
            self._gap_timer.stop()
        self._gap_gen += 1
        sid, gen = self._session_id, self._gap_gen
        self._gap_timer = timex.after(
            max(delay_ms, 1),
            lambda ts, _s=sid, _g=gen: self.put_control(
                Trigger(ts=ts, tag=("session_gap", _s, _g))))

    def _on_session_trigger(self, trig: Trigger) -> None:
        kind, sid = trig.tag[0], trig.tag[1]
        if not self._session_open or sid != self._session_id:
            return  # stale trigger for a session that already closed
        if kind == "session_cap":
            self._close_session(trig.ts)
            return
        if trig.tag[2] != self._gap_gen:
            return  # superseded gap check — a newer one is armed
        # gap check: close only if the session has truly been idle for a
        # full gap; otherwise re-arm for the remaining quiet time (a row
        # may have arrived after this timer fired but before it drained)
        idle = timex.now_ms() - self._last_row_ms
        if idle >= self.gap_ms:
            self._close_session(self._last_row_ms + self.gap_ms)
        else:
            self._arm_gap_check(self.gap_ms - idle)

    def _touch_session_timers_only(self) -> None:
        """Arm gap (+ remaining cap) timers for an already-open session
        (checkpoint restore)."""
        now = timex.now_ms()
        self._last_row_ms = now
        self._session_id += 1
        if self.length_ms > 0:
            remaining = max(self._session_start + self.length_ms - now, 1)
            sid = self._session_id
            self._cap_timer = timex.after(
                remaining,
                lambda ts, _s=sid: self.put_control(
                    Trigger(ts=ts, tag=("session_cap", _s))))
        self._arm_gap_check(self.gap_ms)

    def _close_session(self, end_ts: int) -> None:
        self._emit(WindowRange(self._session_start, end_ts))
        self.state = self.gb.reset_pane(self.state, 0)
        self._session_open = False
        for t in (self._gap_timer, self._cap_timer):
            if t is not None:
                t.stop()
        self._gap_timer = self._cap_timer = None

    # ------------------------------------------------- async count emission
    def _emit_count_async(self, wr: WindowRange) -> None:
        """Dispatch the device finalize on the (immutable) current state and
        hand the fetch+emit to the worker thread; the fold stream continues
        without waiting a device round trip."""
        import time as _time

        if self.kt.n_keys == 0:
            self.last_emit_info = None
            return
        self._emit_async(
            "count",
            self.gb._finalize(self.state, (True,) * self.gb.n_panes), wr)

    def _emit_hh_async(self, wr: WindowRange) -> None:
        """Heavy-hitters boundary: dispatch the compact device recovery on
        the immutable state and hand delivery to the worker."""
        if self.kt.n_keys == 0:
            self.last_emit_info = None
            return
        self._emit_async(
            "hh",
            self.gb._hh_fin(self.state,
                            np.ones(self.gb.n_panes, dtype=np.bool_)), wr)

    def _keys_snapshot(self):
        """Slot->key decode snapshot for a DEFERRED delivery: tiered
        rules retire/recycle slots at boundaries (ops/tierstore.py), so
        a worker delivery decoding the LIVE table could attribute the
        window to a slot's next tenant. Untiered tables are append-only
        — no snapshot needed. Sliding stays live too: it demotes only
        quiescent keys (act 0 in every pane — never in a delivery's
        active set), and a per-trigger million-entry copy would be real
        overhead."""
        if self.tier is None or self.wt == ast.WindowType.SLIDING_WINDOW:
            return None
        return self.kt.decode_all()

    def _emit_async(self, kind: str, stacked_dev, wr: WindowRange) -> None:
        """Shared async-emit protocol: start the device→host copy, enqueue
        for the worker. The dispatched program sees an immutable snapshot,
        so the caller is free to reset panes immediately after."""
        import time as _time

        try:
            stacked_dev.copy_to_host_async()
        except AttributeError:
            pass
        self._ensure_emit_worker()
        # ingest provenance captured AT ISSUE (this is the dispatch
        # thread): the worker must not read the live _cur_ingest_ms,
        # which keeps advancing with post-boundary folds
        self._emit_q.put((kind, stacked_dev, self.kt.n_keys, wr,
                          _time.perf_counter(), self._cur_ingest_ms,
                          self._keys_snapshot()))

    def _ensure_emit_worker(self) -> None:
        import queue
        import threading

        if self._emit_q is None:
            self._emit_q = queue.Queue()
        if self._emit_worker is None or not self._emit_worker.is_alive():
            self._emit_worker = threading.Thread(
                target=self._emit_worker_loop, name=f"{self.name}-emit",
                daemon=True)
            self._emit_worker.start()

    def _emit_worker_loop(self) -> None:
        import time as _time

        from ..ops.groupby import apply_int_semantics

        from .node import _NO_OVERRIDE, _emit_ctx

        while True:
            item = self._emit_q.get()
            if item is None:
                break
            (kind, stacked_dev, n_keys, wr, t_issue, issue_ing,
             keys_snap) = item
            # install the issue-time provenance for every emit() this
            # delivery makes (node.py reads it ahead of _cur_ingest_ms;
            # issue_ing=None means "stamp nothing", not "read live");
            # keys_snap pins the slot->key decode to dispatch time so a
            # tiered boundary's slot retire/recycle between dispatch and
            # delivery cannot misattribute the window
            _emit_ctx.ingest_ms = issue_ing
            self._kt_keys_override = keys_snap
            try:
                if kind == "tier":
                    # tiered-state maintenance (ops/tierstore.py): harvest
                    # a landed demote block / run the placement scan —
                    # off the fold thread, by design
                    self.tier.worker_task(stacked_dev)
                    continue
                if kind == "pf":
                    pipeline, frozen, backup = stacked_dev
                    self._deliver_pf(pipeline, frozen, backup, n_keys, wr,
                                     t_issue)
                    continue
                if kind == "ring":
                    # sliding DABA trigger: fetch the O(1) body combine,
                    # merge the host edge shadow, final values in numpy —
                    # the same component tail as the prefinalize emit
                    pending, shadow = stacked_dev
                    outs, act = self.gb.prefinalize_merge(
                        pending, shadow, n_keys)
                    self.last_emit_info = {
                        "source": "device-ring",
                        "fetch_ms": (pending.fetch_ms()
                                     if hasattr(pending, "fetch_ms") else
                                     (_time.perf_counter() - t_issue)
                                     * 1000.0),
                        "ages_ms": [],
                    }
                    active = np.nonzero(act > 0)[0]
                    if len(active):
                        if self.direct_emit is not None:
                            self._emit_direct(outs, active, wr)
                        else:
                            self._emit_grouped(outs, active, wr)
                    continue
                # kuiperlint: ignore[host-sync]: emit worker thread — THE intended sync point; the fold thread already dispatched and moved on
                arr = np.asarray(stacked_dev)
                if kind == "mr":
                    self._deliver_mr(arr, n_keys, wr)
                    self.last_emit_info = {
                        "source": "device-async",
                        "fetch_ms": (_time.perf_counter() - t_issue) * 1000.0,
                        "ages_ms": [],
                    }
                    continue
                if kind == "hh":
                    outs, act = self.gb.hh_assemble(arr, n_keys)
                else:
                    outs = [arr[i][:n_keys]
                            for i in range(len(self.plan.specs))]
                    outs = apply_int_semantics(self.plan.specs, outs)
                    # kuiperlint: ignore[host-sync]: `arr` already landed on host two lines up
                    act = np.asarray(arr[-1][:n_keys])
                self.last_emit_info = {
                    "source": "device-async",
                    "fetch_ms": (_time.perf_counter() - t_issue) * 1000.0,
                    "ages_ms": [],
                }
                active = np.nonzero(act > 0)[0]
                if len(active):
                    if self.direct_emit is not None:
                        self._emit_direct(outs, active, wr)
                    else:
                        self._emit_grouped(outs, active, wr)
            except Exception as exc:
                logger.error("async %s emit failed on %s: %s",
                             kind, self.name, exc)
                # count it: a window dropped here must show in /rules
                # metrics, not just a log line (the sync path raised into
                # the node's normal exception accounting)
                self.stats.inc_exception(f"async {kind} emit failed: {exc}")
            finally:
                _emit_ctx.ingest_ms = _NO_OVERRIDE
                self._kt_keys_override = None
                self._emit_q.task_done()

    # bounded drain deadline; tests shrink it to exercise the abort path
    drain_deadline_s: float = 30.0

    def _drain_async_emits(self, deadline_s: Optional[float] = None,
                           must_complete: bool = False) -> None:
        """Block until in-flight async emissions have been delivered —
        called before checkpoints, EOF flush, and close so ordering and
        snapshot contracts hold. Bounded: a wedged device fetch (stalled
        tunnel RTT) must not hang checkpoints/EOF/close forever. On
        timeout: the snapshot path (must_complete=True) RAISES so the
        checkpoint fails and a later one retries — committing now would
        advance source offsets past rows whose window output exists only
        in this process's queue (a crash would lose it). EOF/close paths
        log and proceed: the worker is still alive and delivers whenever
        the fetch unwedges."""
        q = self._emit_q
        if q is None:
            return
        if deadline_s is None:
            deadline_s = self.drain_deadline_s
        deadline = time.perf_counter() + deadline_s
        with q.all_tasks_done:
            while q.unfinished_tasks:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    if must_complete:
                        raise RuntimeError(
                            f"{self.name}: async emit drain timed out after "
                            f"{deadline_s:.0f}s with {q.unfinished_tasks} "
                            "emission(s) in flight — aborting this "
                            "checkpoint (a later one will retry)")
                    logger.error(
                        "%s: async emit drain timed out after %.0fs with %d "
                        "emission(s) still in flight; proceeding without "
                        "waiting (the emit worker delivers them when the "
                        "device fetch unwedges)",
                        self.name, deadline_s, q.unfinished_tasks)
                    return
                q.all_tasks_done.wait(remaining)

    # -------------------------------------------------------- tiered state
    def _tier_submit(self, payload: tuple) -> None:
        """Hand a tier task (demote harvest / policy scan) to the
        prefinalize/emit worker — the policy and the packed-row fetch
        never run on the fold thread."""
        import time as _time

        self._ensure_emit_worker()
        self._emit_q.put(("tier", payload, 0, None, _time.perf_counter(),
                          None, None))

    def _on_tier_event(self, kind: str, n: int = 0) -> None:
        """Tier transition hook: demotions/promotions invalidate the
        sliding ring's running partials (the panes stay the truth — the
        next trigger rebuilds via flip or the components_dyn fallback),
        and demotions leave a flight-recorder breadcrumb."""
        if self.wt == ast.WindowType.SLIDING_WINDOW and \
                self.sliding_impl == "daba":
            self._rg_dirty = True
        if kind == "demote":
            from .events import recorder

            recorder().record(
                "tier_demote", rule=self.stats.rule_id, severity="info",
                component="tier_store", node=self.name, keys=n)

    def _reset_pane_tiered(self, pane: int) -> None:
        """reset_pane + the tier epoch bump: spilled rows remember the
        per-pane epoch they were packed under, so a reset here marks
        their slice of that pane stale (ops/tierstore.py)."""
        self.state = self.gb.reset_pane(self.state, pane)
        if self.tier is not None:
            self.tier.note_pane_reset(pane)

    def _tier_boundary(self) -> None:
        """Pane-boundary tier hook (fold thread): apply the worker's
        pending demote plan and dispatch the next touch scan."""
        if self.tier is not None:
            self.state = self.tier.on_boundary(self.state)

    def _emit_tier_extras(self, wr: WindowRange,
                          panes: Optional[List[int]] = None) -> None:
        """Emit the spilled (cold-tier) keys' contribution to a closing
        window: their still-valid per-pane partials finalize host-side
        (the prefinalize numpy tail) and ride the same emit tail as the
        device groups — as a second message for the window, after (or
        concurrent with) the device groups."""
        if self.tier is None:
            return
        res = self.tier.window_groups(self.plan, panes)
        if res is None:
            return
        keys, outs, _act = res
        if self.direct_emit is not None:
            dim_names = [d.name for d in self.dims]
            dim_cols: Dict[str, np.ndarray] = {}
            if dim_names:
                if len(dim_names) == 1:
                    col = np.empty(len(keys), dtype=np.object_)
                    col[:] = keys
                    dim_cols[dim_names[0]] = col
                else:
                    for i, dn in enumerate(dim_names):
                        col = np.empty(len(keys), dtype=np.object_)
                        col[:] = [k[i] for k in keys]
                        dim_cols[dn] = col
            if self.emit_columnar:
                cb = self.direct_emit.run_columnar(
                    dim_cols, outs, wr.window_start, wr.window_end)
                if cb is not None and cb.n:
                    self.emit(cb, count=cb.n)
            else:
                msgs = self.direct_emit.run(
                    dim_cols, outs, wr.window_start, wr.window_end)
                if msgs:
                    self.emit(msgs, count=len(msgs))
            return
        out_lists = []
        for col in outs:
            sel = col
            if np.issubdtype(sel.dtype, np.floating):
                sel = np.where(np.isnan(sel), None, sel.astype(object))
            out_lists.append(sel.tolist())
        groups: List[GroupedTuples] = []
        dim_names = [d.name for d in self.dims]
        single_dim = dim_names[0] if len(dim_names) == 1 else None
        spec_keys = self._spec_keys
        ts = wr.window_end
        for j, key in enumerate(keys):
            if single_dim is not None:
                msg = {single_dim: key}
            elif dim_names:
                msg = dict(zip(dim_names, key))
            else:
                msg = {}
            agg_values = {spec_keys[i]: out_lists[i][j]
                          for i in range(len(spec_keys))}
            groups.append(GroupedTuples(
                content=[Tuple(emitter="", message=msg, timestamp=ts)],
                group_key=str(key), window_range=wr,
                agg_values=agg_values))
        self.emit(GroupedTuplesSet(groups=groups, window_range=wr))

    # ------------------------------------------------------------- sliding
    def _choose_sliding_impl(self, requested: str) -> str:
        """Resolve the sliding implementation at construction: DABA rings
        when the kernel supports the component-merge tail (plain
        DeviceGroupBy — sharded folds and heavy_hitters finalizes keep the
        exact refold path) and the ring's static HBM footprint fits the
        sliding_dev_ring_mb budget; the refold path otherwise."""
        if requested != "daba":
            return "refold"
        if getattr(self.gb, "watch_prefix", "") != "groupby" or \
                not getattr(self.gb, "supports_prefinalize", False) or \
                getattr(self.gb, "_host_finalize_only", False):
            # structured + attributable (ISSUE 15 satellite): the silent
            # auto-fallback hid that a sharded rule's sliding triggers
            # still refold — the flight event names the reason, and the
            # explain "sliding" section mirrors it at plan time
            reason = ("sharded_kernel"
                      if getattr(self.gb, "watch_prefix", "") == "sharded"
                      else "heavy_hitters"
                      if getattr(self.gb, "_host_finalize_only", False)
                      else "kernel_form")
            from .events import recorder

            recorder().record(
                "sliding_impl_fallback", rule=self.stats.rule_id,
                severity="info", component="sliding_ring", node=self.name,
                requested="daba", action="refold", reason=reason)
            logger.info(
                "%s: sliding ring unavailable for this kernel form "
                "(%s) — using the refold path (mesh DABA ring is future "
                "work)", self.name, reason)
            return "refold"
        from ..ops.slidingring import SlidingRing

        try:
            ring = SlidingRing(self.gb, self._ring_layout)
        except ValueError as exc:
            logger.warning("%s: sliding ring rejected (%s) — using the "
                           "refold path", self.name, exc)
            return "refold"
        est = ring.estimate_bytes(self.gb.capacity)
        if est > self.dev_ring_budget_bytes:
            # structured flight event either way: a wide-hll rule that
            # still exceeds slidingDevRingMb after bucket coarsening
            # either got its capacity capped by the cold tier (tiered
            # construction shrinks it to the hot target, so this branch
            # means even THAT didn't fit) or silently refolding would
            # hide the regression class PR 11 left open
            from .events import recorder

            recorder().record(
                "sliding_ring_budget", rule=self.stats.rule_id,
                severity="warn", component="sliding_ring", node=self.name,
                estimate_bytes=int(est),
                budget_bytes=int(self.dev_ring_budget_bytes),
                tiered=self._tier_layout is not None, action="refold")
            logger.warning(
                "%s: sliding ring needs %.1fMB > slidingDevRingMb=%.0fMB "
                "budget — using the refold path (raise the budget, "
                "coarsen the window, or tighten the tier hot target)",
                self.name, est / 2**20, self.dev_ring_budget_bytes / 2**20)
            return "refold"
        if self._tier_layout is not None:
            # DABA accepted at the tier-capped capacity: record that the
            # cold tier (not refolding) is what absorbs excess
            # cardinality for this rule
            from .events import recorder

            recorder().record(
                "sliding_tier_demote", rule=self.stats.rule_id,
                severity="info", component="sliding_ring", node=self.name,
                estimate_bytes=int(est),
                budget_bytes=int(self.dev_ring_budget_bytes),
                hot_slots=int(self._tier_layout.hot_slots),
                action="daba_tiered")
        self.ring = ring
        self._ring_reset_tracking()
        # the running total retains one spare bucket beyond the window
        # span: eviction must subtract a pane BEFORE its slot can be
        # recycled by bucket b+R in the same fold call (R = span + 3)
        self._span_tot = self._ring_layout.span_buckets + 1
        return "daba"

    def _ring_reset_tracking(self) -> None:
        """Host-side ring bookkeeping to a cold (dirty) state: the next
        trigger rebuilds the device partials from the panes in one flip."""
        from collections import deque as _deque

        self._rg_head = -1       # newest bucket any row has folded into
        self._rg_closed = -1     # last bucket absorbed into the partials
        self._rg_dirty = True    # cache needs a flip before serving
        self._rg_flip_lo = -1    # front-stack span [flip_lo, flip_hi]
        self._rg_flip_hi = -1
        self._rg_closes = 0      # advance count (drift re-anchor cadence)
        self._rg_anchor = 0
        self._rg_tot = _deque()  # (bucket, slot, absorbed) in the total

    def _ring_state_now(self):
        """The live device ring state, lazily allocated and kept at the
        kernel's (possibly grown) key capacity."""
        if self._ring_dev is None:
            self.ring.capacity = int(self.gb.capacity)
            self._ring_dev = self.ring.init_state()
        elif self.ring.capacity < self.gb.capacity:
            self._ring_dev = self.ring.grow(self._ring_dev,
                                            self.gb.capacity)
        return self._ring_dev

    def ring_dev_bytes(self) -> int:
        """memwatch probe: live HBM bytes of the DABA ring partials."""
        if self._ring_dev is None:
            return 0
        from ..ops.slidingring import SlidingRing

        return SlidingRing.state_nbytes(self._ring_dev)

    def _ring_advance_buckets(self, buckets: np.ndarray) -> None:
        """Bucket-close maintenance after a fold: absorb newly closed
        panes into the running partials (O(1) device work per bucket,
        ~1/bucket_ms per second — off the trigger path). Late rows into
        already-absorbed buckets and time gaps mark the cache dirty; the
        next trigger heals it with one flip (the panes stay the truth)."""
        ubs = np.unique(buckets).tolist()
        nh = int(ubs[-1])
        if self._rg_closed >= 0 and int(ubs[0]) <= self._rg_closed:
            self._rg_dirty = True
        if nh <= self._rg_head:
            return
        if self._rg_head < 0 or nh - self._rg_head > 8:
            # cold start or a time gap: skip per-bucket advances and let
            # the next trigger rebuild everything in one flip
            self._rg_dirty = True
            self._rg_tot.clear()
            self._rg_head = nh
            self._rg_closed = nh - 1
            return
        for b in range(self._rg_head, nh):
            self._ring_close_bucket(b)
        self._rg_head = nh

    def _ring_close_bucket(self, b: int) -> None:
        slot = b % self.n_ring_panes
        on = self._pane_bucket.get(slot) == b
        ev_slot, ev_on = 0, False
        self._rg_tot.append((b, slot, on))
        if len(self._rg_tot) > self._span_tot:
            ob, oslot, oon = self._rg_tot.popleft()
            if oon and self._pane_bucket.get(oslot) != ob:
                # the evicted bucket's pane was already recycled (burst
                # batch) — its contribution cannot be subtracted; rebuild
                # from the panes at the next trigger instead
                self._rg_dirty = True
            else:
                ev_slot, ev_on = oslot, bool(oon)
        if not self._rg_dirty:
            self._ring_dev = self.ring.advance(
                self._ring_state_now(), self.state, slot, bool(on),
                ev_slot, ev_on)
        self._rg_closes += 1
        self._rg_closed = b

    def _fold_sliding(self, sub: ColumnBatch) -> int:
        """Sliding device path: fold rows into time panes keyed by row
        timestamp, mirror them into the host ring (for edge-bucket refolds
        at emission), and fire trigger rows."""
        ts = sub.timestamps
        if ts is None:
            now = timex.now_ms()
            ts = np.full(sub.n, now, dtype=np.int64)
        buckets = ts // self.bucket_ms
        # a single batch spanning >= n_ring_panes buckets would alias two
        # buckets onto one pane WITHIN one fold call (replay/backfill
        # bursts); split into alias-free chunks folded in bucket order so
        # each recycle lands before its pane receives new rows
        if int(buckets.max() - buckets.min()) >= self.n_ring_panes:
            order = np.argsort(buckets, kind="stable")
            sorted_b = buckets[order]
            start = 0
            base = int(sorted_b[0])
            for i in range(1, len(order) + 1):
                if i == len(order) or int(sorted_b[i]) - base >= self.n_ring_panes:
                    self._fold_sliding(sub.take(order[start:i]))
                    if i < len(order):
                        base = int(sorted_b[i])
                        start = i
            return sub.n
        # late guard: drop a row ONLY when its pane has been recycled past
        # its bucket (folding it would corrupt newer live data). Rows merely
        # out of order — pane still holds their bucket, or an older one the
        # recycle loop will reset — fold exactly like the host path.
        if self._ring_max_bucket >= 0:
            drop_buckets = []
            for b in np.unique(buckets).tolist():
                held = self._pane_bucket.get(int(b) % self.n_ring_panes)
                if held is not None and held > int(b):
                    drop_buckets.append(int(b))
            if drop_buckets:
                late = np.isin(buckets, drop_buckets)
                n_late = int(late.sum())
                self.stats.inc_dropped(
                    "pane_recycle", n=n_late,
                    detail="sliding pane retention")
                keep = np.nonzero(~late)[0]
                if len(keep) == 0:
                    return 0
                sub = sub.take(keep)
                ts = ts[keep]
                buckets = buckets[keep]
        # recycle panes: reset any pane about to receive a newer bucket.
        # The recycled bucket's ROWS stay in the ring a while longer — a
        # trigger whose window still needs that bucket detects the recycled
        # pane and refolds the whole window from the ring (exact fallback)
        for b in np.unique(buckets).tolist():
            pane = int(b) % self.n_ring_panes
            held = self._pane_bucket.get(pane)
            if held is not None and held != int(b):
                self._reset_pane_tiered(pane)
            self._pane_bucket[pane] = int(b)
        self._ring_max_bucket = max(self._ring_max_bucket,
                                    int(buckets.max()))
        # ring outlives panes by a margin so the stale-window fallback can
        # always reconstruct; beyond that the window is unrecoverable anyway
        floor_b = self._ring_max_bucket - self.n_ring_panes - 8
        expired = [b for b in self._ring if b < floor_b]
        for b in expired:
            del self._ring[b]
            dropped = self._dev_ring.pop(b, None)
            if dropped:
                self._dev_ring_bytes -= sum(
                    self._dev_entry_nbytes(e) for e in dropped)
            self._bucket_max_ts.pop(b, None)
        if expired:
            # purge the expired buckets' fifo bookkeeping too: the evict
            # loop only drains it when OVER budget, so an under-budget rule
            # would otherwise grow the deque for the life of the stream
            self._dev_ring_fifo = type(self._dev_ring_fifo)(
                t for t in self._dev_ring_fifo if t[0] >= floor_b)
        import time as _time

        daba = self.sliding_impl == "daba"
        t0 = _time.perf_counter()
        cols, valid, slots = self._build_kernel_inputs(sub)
        if self.tier is not None:
            self.state = self.tier.admit(self.state)
        # the DABA path needs no device batch cache: triggers combine
        # running partials, edges fold on host from the row ring
        dev = (None if daba
               else self._upload_sliding_inputs(cols, valid, slots))
        pane_vec = (buckets % self.n_ring_panes).astype(np.uint8)
        fold_cols, fold_valid, fold_slots, n_rows = (
            (dev[0], dev[1], dev[2], sub.n) if dev is not None
            else (cols, valid, slots, None))
        t1 = _time.perf_counter()
        self.stats.observe_stage("upload", (t1 - t0) * 1e6, sub.n)
        if len(np.unique(pane_vec)) == 1:
            # single-bucket batch: scalar-pane fast path (the common case —
            # a batch spans far less time than one pane)
            self.state = self.gb.fold(self.state, fold_cols, fold_slots,
                                      fold_valid, int(pane_vec[0]),
                                      n_rows=n_rows)
        else:
            self.state = self.gb.fold(self.state, fold_cols, fold_slots,
                                      fold_valid, pane_vec, n_rows=n_rows)
        self.stats.observe_stage(
            "fold", (_time.perf_counter() - t1) * 1e6, sub.n)
        if hasattr(self.gb, "note_rows"):
            self.gb.n_keys_hint = self.kt.n_keys  # fold counted host slots
        for b in np.unique(buckets).tolist():
            m = buckets == b
            sel = np.nonzero(m)[0]
            seg = (
                {k: v[sel] for k, v in cols.items()},
                {k: v[sel] for k, v in valid.items()},
                slots[sel], ts[sel],
            ) if not m.all() else (cols, valid, slots, ts)
            self._ring.setdefault(int(b), []).append(seg)
            if not daba:
                # aligned device entry: whole-batch refs + this bucket's
                # row mask (the refold ANDs the window time cut into it)
                entry = None if dev is None else (dev[3], dev[2], m, ts)
                lst = self._dev_ring.setdefault(int(b), [])
                lst.append(entry)
                if entry is not None:
                    nb = self._dev_entry_nbytes(entry)
                    self._dev_ring_bytes += nb
                    self._dev_ring_fifo.append((int(b), len(lst) - 1, nb))
                    self._dev_ring_evict()
            bmax = int(ts[sel].max())
            if bmax > self._bucket_max_ts.get(int(b), -1):
                self._bucket_max_ts[int(b)] = bmax
        if daba:
            self._ring_advance_buckets(buckets)
        # tier maintenance at bucket granularity (sliding's pane
        # boundary): throttled by the scan cadence inside
        self._tier_boundary()
        # trigger rows: vectorized OVER(WHEN ...) on the raw batch columns;
        trig_mask = _host_mask(self._trigger_host, sub.columns, sub.n)
        for i in np.nonzero(trig_mask)[0].tolist():
            t = int(ts[i])
            if self.delay_ms > 0:
                self._schedule_sliding(t, timex.now_ms() + self.delay_ms)
            else:
                self._emit_sliding(t)
        return sub.n

    def _upload_sliding_inputs(self, cols, valid, slots, force: bool = False):
        """Pre-pad + upload one batch's fold inputs, so (a) the fold uses
        them without its own upload and (b) the ring keeps the device refs
        for mask-only edge refolds. Returns (dev_cols, dev_valid, s_dev,
        dev_all) or None when the batch can't ship as one chunk.
        dev_all is the combined {col, __valid_col} dict fold_masked takes.
        `force` bypasses the small-batch HBM guard — the warmup uses it so
        fold_masked actually compiles (a 1-row warmup batch would otherwise
        be rejected and the first real trigger would pay the jit stall)."""
        mb = self.gb.micro_batch
        n = len(slots)
        if n > mb or not getattr(self.gb, "accepts_device_inputs", False) \
                or getattr(self.gb, "mesh_tag", ""):
            # sharded sliding keeps the host-path edge refold: fold_masked
            # is uncertified for the sharded kernel and the _dev_ring
            # would pin replicated (unsharded) copies across the mesh
            return None
        if n < mb // 4 and not force:
            # small batches would pin a full mb-padded device buffer each
            # for the whole ring retention window — HBM cost out of all
            # proportion; their edge refolds are cheap host uploads anyway
            return None
        import jax.numpy as jnp

        from ..ops.aggspec import materialize_hll_columns

        from ..ops.groupby import col_np_dtype

        cols = materialize_hll_columns(self.plan.columns, cols, n)
        pad = mb - n
        dev_cols, dev_valid, dev_all = {}, {}, {}
        for name in self.plan.columns:
            arr = np.asarray(cols[name], dtype=col_np_dtype(self.plan, name))
            if pad:
                arr = np.pad(arr, (0, pad))
            d = jnp.asarray(arr)
            dev_cols[name] = d
            dev_all[name] = d
            vm = valid.get(name)
            if vm is not None:
                vm = np.pad(vm, (0, pad)) if pad else vm
                vm = jnp.asarray(vm)
                dev_valid[name] = vm
            dev_all["__valid_" + name] = vm
        s = slots
        if pad:
            s = np.pad(s, (0, pad))
        from ..ops.groupby import slot_dtype

        # capacity here is post-grow for this batch (_build_kernel_inputs
        # ran first), so a mid-stream doubling past 65,535 switches NEW
        # cached entries to int32; earlier uint16 entries in _dev_ring stay
        # valid — their slot values predate the grow (fold_masked casts)
        s_dev = jnp.asarray(s.astype(slot_dtype(self.gb.capacity),
                                     copy=False))
        return dev_cols, dev_valid, s_dev, dev_all

    @staticmethod
    def _dev_entry_nbytes(entry) -> int:
        """Device footprint of one _dev_ring entry. Multi-bucket batches
        share the same whole-batch buffers across their entries, so this
        over-counts them — the budget errs toward evicting early, never
        toward exceeding HBM."""
        if entry is None:
            return 0
        dev_all, s_dev = entry[0], entry[1]

        def nb(a):
            if a is None:
                return 0
            v = getattr(a, "nbytes", None)
            return int(v) if v is not None else int(
                a.size * a.dtype.itemsize)

        return sum(nb(a) for a in dev_all.values()) + nb(s_dev)

    def _dev_ring_evict(self) -> None:
        """Drop the oldest cached device entries until the cache fits the
        HBM budget; their refolds fall back to the exact host path (the
        aligned _ring rows are always retained)."""
        freed = evicted = 0
        while (self._dev_ring_bytes > self.dev_ring_budget_bytes
               and self._dev_ring_fifo):
            b, idx, nbytes = self._dev_ring_fifo.popleft()
            lst = self._dev_ring.get(b)
            if lst is None or idx >= len(lst) or lst[idx] is None:
                continue  # already gone (bucket expired past the ring floor)
            lst[idx] = None
            self._dev_ring_bytes -= nbytes
            freed += nbytes
            evicted += 1
        if evicted:
            # flight-recorder breadcrumb: budget pressure is why refolds
            # slowed down (host-path fallback), worth a line in a bundle
            from .events import recorder

            recorder().record(
                "memory_evict", rule=self.stats.rule_id, severity="warn",
                component="dev_ring", node=self.name, entries=evicted,
                bytes_freed=freed, bytes_now=self._dev_ring_bytes,
                budget_bytes=self.dev_ring_budget_bytes)

    def _schedule_sliding(self, t: int, fire_at: int) -> None:
        """Register a delayed sliding emission; tracked in _pending_slides
        so a checkpoint/restore re-arms it instead of dropping the window."""
        self._pending_slides[t] = fire_at
        delay = max(fire_at - timex.now_ms(), 0)
        timex.after(delay, lambda _ts, t0=t: self.put_control(
            Trigger(ts=t0, tag=("sliding", t0))))

    def _emit_sliding(self, t: int) -> None:
        """Emit the exact window (t-L, t+delay] for trigger time t."""
        if self.sliding_impl == "daba":
            return self._emit_sliding_ring(t)
        n_keys = self.kt.n_keys
        if n_keys == 0:
            return
        lo = t - self.length_ms  # exclusive
        hi = t + self.delay_ms  # inclusive
        b_lo, b_hi = lo // self.bucket_ms, hi // self.bucket_ms
        full = []
        stale = False
        for b in range(b_lo + 1, b_hi):
            if self._pane_bucket.get(b % self.n_ring_panes) == b:
                full.append(b)
            elif b in self._ring:
                stale = True  # pane recycled but ring rows still present
        scratch_rows = []

        def ring_rows(b, lo_excl=None, hi_incl=None):
            devs = self._dev_ring.get(b, [])
            for i, (cols, valid, slots, ts) in enumerate(self._ring.get(b, [])):
                dev = devs[i] if i < len(devs) else None
                if dev is not None:
                    # mask-only refold: AND the window time cut into the
                    # bucket mask over the cached whole-batch device input
                    dev_all, s_dev, bmask, full_ts = dev
                    m = bmask.copy()
                    if lo_excl is not None:
                        m &= full_ts > lo_excl
                    if hi_incl is not None:
                        m &= full_ts <= hi_incl
                    if m.any():
                        mb = self.gb.micro_batch
                        if len(m) < mb:
                            m = np.pad(m, (0, mb - len(m)))
                        scratch_rows.append(("dev", dev_all, s_dev, m))
                    continue
                m = np.ones(len(ts), dtype=np.bool_)
                if lo_excl is not None:
                    m &= ts > lo_excl
                if hi_incl is not None:
                    m &= ts <= hi_incl
                if m.any():
                    sel = np.nonzero(m)[0]
                    scratch_rows.append(("host",
                        {k: v[sel] for k, v in cols.items()},
                        {k: v[sel] for k, v in valid.items()},
                        slots[sel]))

        if stale:
            # fallback: a needed pane was recycled under emission backlog —
            # refold the WHOLE window from the ring (exact, just slower)
            full = []
            for b in range(b_lo, b_hi + 1):
                ring_rows(b, lo_excl=lo, hi_incl=hi)
            self.stats.inc_exception("sliding pane recycled; ring refold")
        else:
            if b_lo == b_hi:
                ring_rows(b_lo, lo_excl=lo, hi_incl=hi)
            else:
                ring_rows(b_lo, lo_excl=lo)
                # high edge served straight from its PANE when exact: the
                # pane holds precisely bucket b_hi's rows folded so far,
                # which equals (b_hi*B, hi] when no received row exceeds hi
                # and the pane's span clears the window's low cut
                if (self._pane_bucket.get(b_hi % self.n_ring_panes) == b_hi
                        and b_hi * self.bucket_ms > lo
                        and self._bucket_max_ts.get(b_hi, hi + 1) <= hi):
                    full.append(b_hi)
                else:
                    ring_rows(b_hi, hi_incl=hi)
        used_scratch = False
        for entry in scratch_rows:
            if entry[0] == "dev":
                _, dev_all, s_dev, m = entry
                self.state = self.gb.fold_masked(
                    self.state, dev_all, s_dev, m, self._scratch_pane)
            else:
                _, cols, valid, slots = entry
                self.state = self.gb.fold(self.state, cols, slots, valid,
                                          self._scratch_pane)
            used_scratch = True
        panes = sorted({b % self.n_ring_panes for b in full})
        if used_scratch:
            panes.append(self._scratch_pane)
        if panes and getattr(self.gb, "_host_finalize_only", False):
            # host-only components: keep the exact synchronous path
            outs, act = self.gb.finalize(self.state, n_keys, panes=panes)
            active = np.nonzero(act > 0)[0]
            if len(active):
                wr = WindowRange(lo, hi)
                if self.direct_emit is not None:
                    self._emit_direct(outs, active, wr)
                else:
                    self._emit_grouped(outs, active, wr)
        elif panes:
            # dispatch-and-defer: the finalize launches here, IN ORDER on
            # the device stream (after the scratch folds, before the
            # scratch reset below), and the emit worker fetches+delivers —
            # a sync fetch would stall the fold stream ~1+ RTT per trigger
            # (the r03-recorded 0.3-1s sliding emit latencies were exactly
            # these blocking fetches). The traced (runtime) pane mask keeps
            # one compiled executable no matter which panes are live.
            pane_mask = np.zeros(self.gb.n_panes, dtype=np.bool_)
            pane_mask[panes] = True
            self._emit_async(
                "count", self.gb._finalize_dyn(self.state, pane_mask),
                WindowRange(lo, hi))
        if used_scratch:
            self._reset_pane_tiered(self._scratch_pane)

    # ---------------------------------------------------- sliding (DABA)
    def _emit_sliding_ring(self, t: int) -> None:
        """DABA-ring emission for trigger time t: the full-pane window
        body is ONE device combine of the ring's running partials (plus at
        most QUERY_ADJ pane slices); the partial edge buckets fold on HOST
        from the row ring into a HostShadow merged by the emit worker — no
        per-trigger device refold of cached batch history, no
        window-length pane merge. Exactness matches the refold path: the
        panes remain the ground truth and every off-discipline shape
        (delay, recycled panes, restores) takes an exact fallback."""
        import time as _time

        from ..ops.prefinalize import HostShadow, IdentityFinalize

        n_keys = self.kt.n_keys
        if n_keys == 0:
            return
        lo = t - self.length_ms  # exclusive
        hi = t + self.delay_ms  # inclusive
        b_lo, b_hi = lo // self.bucket_ms, hi // self.bucket_ms
        shadow = HostShadow(self.plan, self.gb.comp_specs, self.kt.capacity)
        include_head = False
        if b_lo == b_hi:
            # window inside one bucket: the host edge fold IS the window
            self._shadow_ring_rows(shadow, b_lo, lo_excl=lo, hi_incl=hi)
            body = None
        else:
            self._shadow_ring_rows(shadow, b_lo, lo_excl=lo)
            body = (b_lo + 1, b_hi - 1)
            # high edge served straight from the live PANE when exact: it
            # holds precisely bucket b_hi's rows folded so far, which
            # equals (b_hi*B, hi] when no received row exceeds hi
            if (self._pane_bucket.get(b_hi % self.n_ring_panes) == b_hi
                    and self._bucket_max_ts.get(b_hi, hi + 1) <= hi):
                include_head = True
            else:
                self._shadow_ring_rows(shadow, b_hi, hi_incl=hi)
        pending = self._ring_body_query(body, include_head, b_hi, shadow)
        if pending is None:
            pending = IdentityFinalize(self.gb.comp_specs, self.kt.capacity)
        self._ensure_emit_worker()
        self._emit_q.put(("ring", (pending, shadow), n_keys,
                          WindowRange(lo, hi), _time.perf_counter(),
                          self._cur_ingest_ms, None))

    def _shadow_ring_rows(self, shadow, b: int, lo_excl: Optional[int] = None,
                          hi_incl: Optional[int] = None) -> None:
        """Numpy-fold bucket b's retained rows (optionally time-cut) into
        the trigger's HostShadow — bounded by ONE bucket of rows, not the
        window history."""
        for cols, valid, slots, ts in self._ring.get(b, []):
            m = np.ones(len(ts), dtype=np.bool_)
            if lo_excl is not None:
                m &= ts > lo_excl
            if hi_incl is not None:
                m &= ts <= hi_incl
            if not m.any():
                continue
            if m.all():
                shadow.fold(cols, slots, valid)
            else:
                sel = np.nonzero(m)[0]
                shadow.fold({k: v[sel] for k, v in cols.items()},
                            slots[sel],
                            {k: v[sel] for k, v in valid.items()})

    def _ring_body_query(self, body, include_head: bool, b_hi: int,
                         shadow):
        """Dispatch the device body combine for one trigger: the O(1)
        ring query when the running partials cover the body, a one-off
        flip (rebuild from panes) when they don't, and the traced-mask
        components fallback for shapes outside the in-order discipline
        (delayed emissions, recycled panes). Returns a PendingFinalize or
        None (empty body, nothing on device)."""
        from ..ops.slidingring import QUERY_ADJ

        head_slot = b_hi % self.n_ring_panes
        if body is None:
            return None
        j, e = body
        if j > e:
            if not include_head:
                return None
            adj_slots = np.zeros(QUERY_ADJ, dtype=np.int32)
            adj_w = np.zeros(QUERY_ADJ, dtype=np.float32)
            adj_mm = np.zeros(QUERY_ADJ, dtype=np.bool_)
            adj_slots[0] = head_slot
            adj_w[0] = 1.0
            adj_mm[0] = True
            return self.ring.query_begin(
                self._ring_state_now(), self.state, body_on=False,
                f_on=False, f_slot=0, adj_slots=adj_slots,
                adj_weights=adj_w, adj_mm=adj_mm)
        if self._rg_closed == e and self._rg_head == b_hi:
            ok = not self._rg_dirty and self._ring_fast_ok(j)
            if not ok:
                self._ring_flip(j, e)
                ok = not self._rg_dirty and self._ring_fast_ok(j)
            if ok:
                return self._ring_query_fast(j, include_head, head_slot)
        return self._ring_query_dyn(j, e, include_head, head_slot, shadow)

    def _ring_fast_ok(self, j: int) -> bool:
        """Can the running partials serve a body starting at bucket j?"""
        from ..ops.slidingring import QUERY_ADJ

        if self._rg_closes - self._rg_anchor > 4 * self._span_tot:
            # periodic re-anchor: rebuild the float totals from the panes
            # before subtract-on-evict drift can accumulate
            return False
        if self.ring.mm_comps:
            if self._rg_flip_lo < 0 or j < self._rg_flip_lo \
                    or j > self._rg_flip_hi + 1:
                return False
        if not self._rg_tot or self._rg_tot[0][0] > j:
            return False  # the total no longer covers the window start
        n_sub = sum(1 for (b, _s, on) in self._rg_tot if b < j and on)
        return n_sub <= QUERY_ADJ - 1

    def _ring_flip(self, j: int, e: int) -> None:
        """Rebuild every running partial from the live panes over [j, e]
        (one fused device scan — the amortized DABA flip). A bucket whose
        pane was recycled while its rows are still retained cannot flip
        (the pane is gone); the caller then takes the dyn fallback."""
        from collections import deque as _deque

        valid = np.zeros(self.n_ring_panes, dtype=np.bool_)
        tot_entries = []
        for b in range(j, e + 1):
            s = b % self.n_ring_panes
            live = self._pane_bucket.get(s) == b
            if not live and b in self._ring:
                return  # rows exist but the pane is gone — dyn fallback
            valid[b - j] = live
            tot_entries.append((b, s, live))
        self._ring_dev = self.ring.flip(
            self._ring_state_now(), self.state, j % self.n_ring_panes,
            valid)
        self._rg_tot = _deque(tot_entries)
        self._rg_flip_lo, self._rg_flip_hi = j, e
        self._rg_anchor = self._rg_closes
        self._rg_dirty = False

    def _ring_query_fast(self, j: int, include_head: bool,
                         head_slot: int):
        """The constant-time trigger: combine(front[j], back) for the
        two-stack components, the running total ± at most two trailing
        pane slices for the additive ones, plus the live head pane."""
        from ..ops.slidingring import QUERY_ADJ

        adj_slots = np.zeros(QUERY_ADJ, dtype=np.int32)
        adj_w = np.zeros(QUERY_ADJ, dtype=np.float32)
        adj_mm = np.zeros(QUERY_ADJ, dtype=np.bool_)
        k = 0
        for b, s, on in self._rg_tot:
            if b < j and on:
                adj_slots[k] = s
                adj_w[k] = -1.0
                k += 1
        if include_head:
            adj_slots[k] = head_slot
            adj_w[k] = 1.0
            adj_mm[k] = True
        f_on = bool(self.ring.mm_comps) and j <= self._rg_flip_hi
        return self.ring.query_begin(
            self._ring_state_now(), self.state, body_on=True, f_on=f_on,
            f_slot=j % self.n_ring_panes, adj_slots=adj_slots,
            adj_weights=adj_w, adj_mm=adj_mm)

    def _ring_query_dyn(self, j: int, e: int, include_head: bool,
                        head_slot: int, shadow):
        """Exact fallback body: merge the window's live panes under a
        traced mask (one executable, O(window span) reads — only for
        off-discipline triggers); buckets whose pane was recycled refold
        their retained rows on host into the trigger's shadow."""
        pane_mask = np.zeros(self.gb.n_panes, dtype=np.bool_)
        missing = 0
        for b in range(j, e + 1):
            s = b % self.n_ring_panes
            if self._pane_bucket.get(s) == b:
                pane_mask[s] = True
            elif b in self._ring:
                self._shadow_ring_rows(shadow, b)
                missing += 1
        if missing:
            self.stats.inc_exception("sliding pane recycled; ring refold")
        if include_head:
            pane_mask[head_slot] = True
        if not pane_mask.any():
            return None
        return self.gb.components_begin_dyn(self.state, pane_mask)

    # ---------------------------------------------------------------- trigger
    def on_pre_trigger(self, pre: PreTrigger) -> None:
        """Ahead of the window boundary: dispatch finalize on the state
        snapshot (jax immutability = free double buffer) and start shadowing
        tail rows on host. If an earlier pre-issue for this boundary has
        already landed, this refresh is unnecessary and skipped; if it's
        still in flight (tunnel jitter), stack a fresher one. See
        ops/prefinalize.py."""
        if not self._prefinalize_ok or self.kt.n_keys == 0:
            return
        from ..ops.prefinalize import HostShadow, IdentityFinalize

        real = [e for e in self._pipeline
                if not isinstance(e[0], IdentityFinalize)]
        # a landed REAL fetch serves the boundary — no refresh needed; the
        # backstop identity never suppresses probes
        if real and real[-1][0].ready():
            return
        # at most 2 un-landed device fetches: each is a full components
        # download occupying the (serialized, RTT-bound) device link —
        # stacking more on a congested link compounds the backlog until
        # fetches lag the stream by whole windows (r02 bench post-mortem)
        if len(self._pipeline) >= 4 or len(real) >= 2:
            return
        if real and self._device_frozen:
            # device state unchanged since the first real pre-issue (frozen
            # span rows are host-only): retry the fetch on the same
            # snapshot, sharing that span's shadow
            self._pipeline.append((
                self.gb.prefinalize_begin(self.state), real[0][1],
            ))
            return
        self._pipeline.append((
            self.gb.prefinalize_begin(self.state),
            HostShadow(self.plan, self.gb.comp_specs, self.kt.capacity),
        ))
        self._device_frozen = self._tail_host_only

    def on_trigger(self, trig: Trigger) -> None:
        if self.wt == ast.WindowType.SLIDING_WINDOW:
            # delayed sliding emission scheduled at trigger-row time + delay
            if isinstance(trig.tag, tuple) and trig.tag[0] == "sliding":
                self._pending_slides.pop(trig.tag[1], None)
                self._emit_sliding(trig.tag[1])
            return
        if self.wt == ast.WindowType.SESSION_WINDOW:
            if isinstance(trig.tag, tuple) and trig.tag[0] in (
                    "session_gap", "session_cap"):
                self._on_session_trigger(trig)
            return
        end = trig.ts
        wr = WindowRange(end - self.length_ms, end)
        if self._async_hh:
            self._emit_hh_async(wr)
        elif self._async_mr:
            self._emit_mr_async(wr)
        else:
            self._boundary_emit(wr)
        # spilled (cold-tier) keys with live pane data contribute to this
        # window host-side, BEFORE the pane expiry marks them stale
        self._emit_tier_extras(wr)
        if self.wt == ast.WindowType.TUMBLING_WINDOW:
            self._reset_pane_tiered(0)
        else:
            # advance to the next pane; expire it (it held the oldest slice)
            self.cur_pane = (self.cur_pane + 1) % self.n_panes
            self._reset_pane_tiered(self.cur_pane)
        self._tier_boundary()
        self.begin_window_backstop()
        self._schedule_next_tick()

    def begin_window_backstop(self) -> None:
        """Open the next window with an always-ready identity entry plus a
        window-spanning host shadow, so its boundary can never block on the
        device link. Active for every window when the backstop is enabled;
        otherwise only after a boundary whose fetches all missed (storm).
        Real pre-issues still run and are preferred when they land."""
        if not (self._backstop_ok and self.kt.n_keys):
            return
        if not self._backstop:
            # prefinalize_backstop=False means strictly synchronous
            # boundaries: the caller chose to WAIT on the device fetch
            # (throughput benches, strict device-served accounting) — a
            # storm must not silently re-arm host-shadow serving
            return
        from ..ops.prefinalize import HostShadow, IdentityFinalize

        if self._identity is None or self._identity.capacity != self.kt.capacity:
            # immutable (merge never writes into it) -> safe to reuse; wide
            # sketch components make a fresh one per boundary real churn
            self._identity = IdentityFinalize(self.gb.comp_specs,
                                              self.kt.capacity)
        self._pipeline = [(
            self._identity,
            HostShadow(self.plan, self.gb.comp_specs, self.kt.capacity),
        )]
        self._device_frozen = False

    def on_eof(self, eof: EOF) -> None:
        if self.is_event_time and self.wt == ast.WindowType.SESSION_WINDOW:
            self._drain_async_emits()
            self._evs_flush()
            self.broadcast(eof)
            return
        if self.is_event_time and self.wt not in (
                ast.WindowType.COUNT_WINDOW, ast.WindowType.STATE_WINDOW):
            # flush every window that can still contain data (bounded
            # runs / trials) — iterate the dirty set, never bucket-by-bucket
            # across gaps. COUNT/STATE fold into pane 0 like processing
            # time and flush through the shared path below (their _dirty
            # set is never populated — returning here would silently drop
            # the open span)
            while self._dirty:
                first = min(self._dirty)
                nxt = self._next_emit_bucket
                self._next_emit_bucket = first if nxt is None else max(nxt,
                                                                       first)
                self._emit_event_bucket(self._next_emit_bucket)
            self.broadcast(eof)
            return
        if self.wt == ast.WindowType.SLIDING_WINDOW:
            # sliding emits only on trigger rows; nothing to flush
            self.broadcast(eof)
            return
        now = timex.now_ms()
        self._drain_async_emits()  # deliver queued count windows in order
        if self.wt == ast.WindowType.SESSION_WINDOW:
            if self._session_open:
                self._close_session(now)
            self.broadcast(eof)
            return
        wr_eof = WindowRange(now - self.length_ms, now)
        self._emit(wr_eof)
        self._emit_tier_extras(wr_eof)
        if self.wt == ast.WindowType.TUMBLING_WINDOW:
            self._reset_pane_tiered(0)
        self.broadcast(eof)

    # ------------------------------------------------------------------- emit
    def _boundary_emit(self, wr: WindowRange) -> None:
        """Window-boundary emission that never blocks the fold stream.

        If some pre-issue is ready (a landed device fetch, or the tumbling
        host backstop), emit synchronously — the fast path, identical to
        before. Otherwise the merge would WAIT on an un-landed fetch (a
        wide sketch finalize is tens of MB; on a slow link that stalls
        ingest for seconds — the reference's window trigger emits inline
        and has the same stall, window_op.go:235), so hand the wait to the
        emit worker and keep folding: the pre-issue snapshot is immutable,
        and the boundary's pane reset cannot disturb it. A worker backlog
        also defers, so windows always deliver in order."""
        if not self._emit_late_async:
            return self._emit(wr)
        q = self._emit_q
        backlog = q is not None and q.unfinished_tasks > 0
        ready_any = any(p.ready() for p, _ in self._pipeline)
        if not backlog and (ready_any or not self.kt.n_keys):
            return self._emit(wr)
        import time as _time

        n_keys = self.kt.n_keys
        pipeline, self._pipeline = self._pipeline, []
        frozen, self._device_frozen = self._device_frozen, False
        self._ensure_emit_worker()
        if pipeline:
            # backup finalize dispatched NOW, before on_trigger's
            # reset_pane donates the state buffers: if the deferred merge
            # later fails (wedged fetch), the worker recovers from this
            # snapshot — a device launch whose transfer happens only on
            # that fallback
            backup = self.gb._finalize(self.state, (True,) * self.gb.n_panes)
            self._emit_q.put(("pf", (pipeline, frozen, backup), n_keys, wr,
                              _time.perf_counter(), self._cur_ingest_ms,
                              self._keys_snapshot()))
        else:
            # no pre-issue in flight: dispatch the finalize on the
            # immutable state and let the worker fetch + deliver
            self._emit_async(
                "count",
                self.gb._finalize(self.state, (True,) * self.gb.n_panes),
                wr)

    def _deliver_pf(self, pipeline, frozen, backup, n_keys: int,
                    wr: WindowRange, t_issue: float) -> None:
        """Emit-worker delivery of a deferred boundary: wait for the best
        pre-issue to land, merge, emit. Runs off the fold thread; touches
        only the immutable pre-issue snapshots and the closed window's
        shadow, never self.state. `backup` is a full finalize dispatched
        on the pre-reset snapshot — the recovery path when the merge
        fails, mirroring the sync path's finalize fallback."""
        import time as _time

        from ..ops.groupby import apply_int_semantics
        from ..ops.prefinalize import IdentityFinalize

        real = [e for e in pipeline if not isinstance(e[0], IdentityFinalize)]
        chosen = next(
            ((p, s) for p, s in reversed(real) if p.ready()), None,
        ) or (real[0] if real else pipeline[0])
        try:
            outs, act = self.gb.prefinalize_merge(chosen[0], chosen[1], n_keys)
        except Exception as exc:
            logger.warning("%s: deferred boundary merge failed (%s) — "
                           "recovering from the backup finalize", self.name,
                           exc)
            try:
                # kuiperlint: ignore[host-sync]: recovery path on the emit worker — fetching the backup finalize IS the point
                arr = np.asarray(backup)
                outs = [arr[i][:n_keys]
                        for i in range(len(self.plan.specs))]
                outs = apply_int_semantics(self.plan.specs, outs)
                # kuiperlint: ignore[host-sync]: `arr` already landed on host above
                act = np.asarray(arr[-1][:n_keys])
            except Exception as exc2:
                logger.error(
                    "%s: backup finalize also failed (%s) — window [%s, %s) "
                    "lost to the sink", self.name, exc2, wr.window_start,
                    wr.window_end)
                self.stats.inc_exception(f"deferred emit failed: {exc2}")
                return
        self.last_emit_info = {
            "source": "device-async-late",
            "fetch_ms": (chosen[0].fetch_ms()
                         if hasattr(chosen[0], "fetch_ms")
                         else (_time.perf_counter() - t_issue) * 1000.0),
            "ages_ms": [],
        }
        active = np.nonzero(act > 0)[0]
        if len(active) == 0:
            return
        if self.direct_emit is not None:
            self._emit_direct(outs, active, wr)
        else:
            self._emit_grouped(outs, active, wr)

    def _emit(self, wr: WindowRange) -> None:
        pipeline, self._pipeline = self._pipeline, []
        frozen, self._device_frozen = self._device_frozen, False
        n_keys = self.kt.n_keys
        if n_keys == 0:
            self.last_emit_info = None  # no stale record for empty windows
            return
        if pipeline:
            from ..ops.prefinalize import IdentityFinalize

            # newest READY pre-issue wins (prefer real device fetches over
            # the backstop identity); if nothing is ready, wait on the
            # oldest (its fetch was registered first, it completes first)
            real = [e for e in pipeline
                    if not isinstance(e[0], IdentityFinalize)]
            chosen = next(
                ((p, s) for p, s in reversed(real) if p.ready()), None,
            ) or next(
                ((p, s) for p, s in reversed(pipeline) if p.ready()),
                pipeline[0],
            )
            self._storm = self._backstop_ok and bool(real) and not any(
                p.ready() for p, _ in real
            )
            # engine-clock ms, matching PendingFinalize.t_created — ages
            # are deterministic under the mock clock
            now = timex.now_ms()
            self.last_emit_info = {
                "source": ("backstop"
                           if isinstance(chosen[0], IdentityFinalize)
                           else "device"),
                "fetch_ms": (chosen[0].fetch_ms()
                             if hasattr(chosen[0], "fetch_ms") else 0.0),
                "ages_ms": [float(now - p.t_created)
                            for p, _ in real if hasattr(p, "t_created")],
            }
            try:
                outs, act = self.gb.prefinalize_merge(
                    chosen[0], chosen[1], n_keys)
                if hasattr(chosen[0], "fetch_ms"):
                    # merge may have blocked on an un-landed fetch; record
                    # the real issue→landed latency, not the -1 sentinel
                    self.last_emit_info["fetch_ms"] = chosen[0].fetch_ms()
            except Exception as exc:
                logger.warning("prefinalize merge failed, sync fallback: %s", exc)
                if frozen and real:
                    self._flush_shadow(real[0][1])
                outs, act = self.gb.finalize(self.state, n_keys)
                self.last_emit_info["source"] = "sync"
        else:
            outs, act = self.gb.finalize(self.state, n_keys)
            self.last_emit_info = {"source": "sync", "fetch_ms": 0.0,
                                   "ages_ms": []}
        active = np.nonzero(act > 0)[0]
        if len(active) == 0:
            self.last_emit_info = None  # nothing emitted this boundary
            return
        if self.direct_emit is not None:
            self._emit_direct(outs, active, wr)
            return
        self._emit_grouped(outs, active, wr)

    def _decode_hh(self, outs):
        """Map heavy_hitters (code, count) pairs back to original values."""
        if not self._hh_cols:
            return outs
        outs = list(outs)
        for i, raw in self._hh_cols.items():
            vd = self._hh_dicts.get(raw)
            col = outs[i]
            dec = np.empty(len(col), dtype=np.object_)
            dec[:] = [
                [{"value": vd.decode(c) if vd else None, "count": n}
                 for c, n in row]
                for row in col
            ]
            outs[i] = dec
        return outs

    def _emit_grouped(self, outs, active: np.ndarray, wr: WindowRange) -> None:
        """Row-path emit tail: build GroupedTuplesSet for downstream
        HAVING/ORDER/PROJECT nodes."""
        outs = self._decode_hh(outs)
        # bulk-convert once (C speed) instead of per-slot numpy scalar access —
        # emit latency is dominated by this host loop at 10k+ groups
        active_list = active.tolist()
        out_lists = []
        for col in outs:
            sel = col[active]
            if np.issubdtype(sel.dtype, np.floating):
                sel = np.where(np.isnan(sel), None, sel.astype(object))
            out_lists.append(sel.tolist())
        groups: List[GroupedTuples] = []
        dim_names = [d.name for d in self.dims]
        single_dim = dim_names[0] if len(dim_names) == 1 else None
        spec_keys = self._spec_keys
        snap = self._kt_keys_override
        decode = snap.__getitem__ if snap is not None else self.kt.decode
        ts = wr.window_end
        for j, slot in enumerate(active_list):
            key = decode(slot)
            if single_dim is not None:
                msg = {single_dim: key}
            elif dim_names:
                msg = dict(zip(dim_names, key))
            else:
                msg = {}
            agg_values = {
                spec_keys[i]: out_lists[i][j] for i in range(len(spec_keys))
            }
            groups.append(
                GroupedTuples(
                    content=[Tuple(emitter="", message=msg, timestamp=ts)],
                    group_key=str(key), window_range=wr, agg_values=agg_values,
                )
            )
        self.emit(GroupedTuplesSet(groups=groups, window_range=wr))

    def _emit_direct(self, outs, active: np.ndarray, wr: WindowRange) -> None:
        """Vectorized tail: HAVING/ORDER/LIMIT/projection computed over the
        finalize arrays; emits the final output messages directly."""
        outs = self._decode_hh(outs)
        dim_names = [d.name for d in self.dims]
        dim_cols: Dict[str, np.ndarray] = {}
        if dim_names:
            keys = (self._kt_keys_override
                    if self._kt_keys_override is not None
                    else self.kt.decode_all())
            if len(dim_names) == 1:
                col = np.empty(len(active), dtype=np.object_)
                col[:] = [keys[s] for s in active.tolist()]
                dim_cols[dim_names[0]] = col
            else:
                sel = [keys[s] for s in active.tolist()]
                for i, dn in enumerate(dim_names):
                    col = np.empty(len(active), dtype=np.object_)
                    col[:] = [k[i] for k in sel]
                    dim_cols[dn] = col
        agg_cols = [col[active] for col in outs]
        if self.emit_columnar:
            cb = self.direct_emit.run_columnar(
                dim_cols, agg_cols, wr.window_start, wr.window_end
            )
            if cb is not None and cb.n:
                self.emit(cb, count=cb.n)
            return
        msgs = self.direct_emit.run(
            dim_cols, agg_cols, wr.window_start, wr.window_end
        )
        if msgs:
            # Fused direct-emit contract: always a list of message dicts,
            # never a bare dict, so consumers of this path see one shape per
            # mode (list here, ColumnBatch when emit_columnar) — ref
            # internal/xsql/collection.go:70, WindowTuples is one type.
            self.emit(msgs, count=len(msgs))

    def _flush_shadow(self, shadow) -> None:
        """Fold frozen-span (host-only) rows back into the device state
        (tumbling only — hopping shadows duplicate device content)."""
        if not self._tail_host_only or shadow is None or not shadow.n_rows:
            return
        if self.gb.capacity < shadow.capacity:
            self.state = self.gb.grow(self.state, shadow.capacity)
        self.state = self.gb.absorb(self.state, shadow.data, 0)

    def _flush_tail(self) -> None:
        """Make the device state complete before a checkpoint snapshot or
        any sync finalize; drops the pre-issue pipeline. Only the frozen
        span's shadow is device-missing (the backstop's window-spanning
        shadow duplicates rows the device already folded)."""
        from ..ops.prefinalize import IdentityFinalize

        pipeline, self._pipeline = self._pipeline, []
        frozen, self._device_frozen = self._device_frozen, False
        if not (frozen and pipeline):
            return
        real = [e for e in pipeline if not isinstance(e[0], IdentityFinalize)]
        if real:
            self._flush_shadow(real[0][1])

    # ------------------------------------------------------------------ state
    def snapshot_state(self) -> Optional[dict]:
        self._drain_async_emits(must_complete=True)
        self._flush_tail()
        host = self.gb.state_to_host(self.state)
        snap = {
            "keys": self.kt.decode_all(),
            "partials": {k: v.tolist() for k, v in host.items()},
            "cur_pane": self.cur_pane,
            "rows_in_window": self._rows_in_window,
        }
        if self._hh_dicts:
            # code order indexes the saved sketch counters — must persist
            snap["hh_dicts"] = {
                c: vd.snapshot() for c, vd in self._hh_dicts.items()
            }
        if self.tier is not None:
            # both tiers persist: the device partials above already carry
            # the hot tier (keys list encodes retired slots as None
            # holes); this is the cold tier — spilled rows + epochs, so
            # a key demoted at kill time comes back queryable
            snap["tier"] = self.tier.snapshot()
        if self.wt == ast.WindowType.SESSION_WINDOW:
            snap["session_open"] = self._session_open
            snap["session_start"] = self._session_start
        if self.wt == ast.WindowType.STATE_WINDOW:
            snap["state_open"] = self._state_open
        if self.is_event_time:
            snap["next_emit_bucket"] = self._next_emit_bucket
            snap["max_bucket"] = self._max_bucket
            snap["dirty_buckets"] = sorted(self._dirty)
        if self.wt == ast.WindowType.SESSION_WINDOW and self.is_event_time \
                and self._evs_batches:
            snap["evs"] = [
                {"cols": {k: v.tolist() for k, v in b.columns.items()},
                 "valid": {k: v.tolist() for k, v in b.valid.items()},
                 "ts": (b.timestamps.tolist()
                        if b.timestamps is not None else None),
                 "emitter": b.emitter, "n": b.n}
                for b in self._evs_batches
            ]
        if self.wt == ast.WindowType.SLIDING_WINDOW:
            snap["pane_bucket"] = dict(self._pane_bucket)
            snap["ring_max_bucket"] = self._ring_max_bucket
            snap["pending_slides"] = dict(self._pending_slides)
            # the ring is a window's worth of raw rows (same magnitude as
            # the host path's buffer snapshot) — base64 of the raw array
            # bytes keeps serialization at memcpy speed instead of building
            # millions of Python objects via tolist()
            snap["ring"] = {
                str(b): [
                    {"cols": {k: _enc_arr(v) for k, v in cols.items()},
                     "valid": {k: _enc_arr(v) for k, v in valid.items()},
                     "slots": _enc_arr(slots), "ts": _enc_arr(ts)}
                    for cols, valid, slots, ts in segs
                ]
                for b, segs in self._ring.items()
            }
        return snap

    def restore_state(self, state: dict) -> None:
        keys = state.get("keys", [])
        self.kt.restore([tuple(k) if isinstance(k, list) else k for k in keys])
        partials = state.get("partials")
        if partials:
            host, cap = self.gb.host_from_partials(partials)
            self.gb.capacity = cap
            # a sharded kernel may round the restored capacity UP for
            # even shard division (mesh-size-change tolerance: an 8-shard
            # restore of a 1-chip snapshot, or vice versa) — state_from_
            # host owns that decision, the key table follows it
            self.state = self.gb.state_from_host(host)
            self.kt.capacity = max(self.kt.capacity, self.gb.capacity)
        if self.tier is not None and state.get("tier"):
            self.tier.restore(state["tier"])
        self.cur_pane = state.get("cur_pane", 0)
        self._rows_in_window = state.get("rows_in_window", 0)
        for c, values in state.get("hh_dicts", {}).items():
            vd = ValueDict()
            vd.restore(values)
            self._hh_dicts[c] = vd
        if self.wt == ast.WindowType.STATE_WINDOW:
            self._state_open = bool(state.get("state_open", False))
        if self.wt == ast.WindowType.SESSION_WINDOW \
                and state.get("session_open"):
            # re-open with fresh timers: a restored session's rows count,
            # and the gap restarts from the restore instant
            self._session_open = True
            self._session_start = int(state.get("session_start", 0))
            self._touch_session_timers_only()
        if self.is_event_time:
            self._next_emit_bucket = state.get("next_emit_bucket")
            self._max_bucket = state.get("max_bucket")
            self._dirty = set(state.get("dirty_buckets", []))
        if self.wt == ast.WindowType.SESSION_WINDOW and self.is_event_time:
            self._evs_batches = []
            for d in state.get("evs", []):
                cols = {}
                for k, v in d["cols"].items():
                    arr = np.asarray(v)
                    if arr.dtype.kind in ("U", "O"):  # strings stay object
                        arr = np.array(v, dtype=np.object_)
                    cols[k] = arr
                self._evs_batches.append(ColumnBatch(
                    n=int(d["n"]), columns=cols,
                    valid={k: np.asarray(v, dtype=np.bool_)
                           for k, v in d.get("valid", {}).items()},
                    timestamps=(np.asarray(d["ts"], dtype=np.int64)
                                if d.get("ts") is not None else None),
                    emitter=d.get("emitter", "")))
        if self.wt == ast.WindowType.SLIDING_WINDOW:
            self._pane_bucket = {int(k): v for k, v in
                                 state.get("pane_bucket", {}).items()}
            self._ring_max_bucket = state.get("ring_max_bucket", -1)
            self._bucket_max_ts = {}
            self._ring = {
                int(b): [
                    ({k: _dec_arr(v) for k, v in seg["cols"].items()},
                     {k: _dec_arr(v) for k, v in seg["valid"].items()},
                     _dec_arr(seg["slots"]), _dec_arr(seg["ts"]))
                    for seg in segs
                ]
                for b, segs in state.get("ring", {}).items()
            }
            # device input cache + max-ts tracking don't survive a restore:
            # refolds fall back to host uploads (exact), pane-serving stays
            # off for pre-restore buckets (missing max-ts fails the check).
            # Pad with None placeholders so post-restore appends stay
            # 1:1-aligned with the restored _ring segment lists — this must
            # run AFTER the ring is rebuilt (building it from the
            # pre-restore ring left restored segments unpadded, so the
            # first post-restore append landed at device index 0 while its
            # rows sat at ring index k: refolds then served the wrong
            # segment from the cache)
            self._dev_ring = {b: [None] * len(segs)
                              for b, segs in self._ring.items()}
            self._dev_ring_bytes = 0
            self._dev_ring_fifo.clear()
            if self.sliding_impl == "daba":
                # the ring partials are caches of the pane state — never
                # checkpointed; a restore starts dirty and the first
                # trigger rebuilds them from the restored panes in one flip
                self._ring_dev = None
                self._ring_reset_tracking()
                self._rg_head = self._ring_max_bucket
                self._rg_closed = (self._rg_head - 1
                                   if self._rg_head >= 0 else -1)
            # re-arm delayed emissions that were pending at the checkpoint
            # (past-due ones fire immediately) — without this, windows for
            # triggers inside the restart gap would silently never emit
            self._pending_slides = {}
            for t, fire_at in state.get("pending_slides", {}).items():
                self._schedule_sliding(int(t), int(fire_at))
