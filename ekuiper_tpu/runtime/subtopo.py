"""Shared-source subtopology — one source + decode pipeline serving N rules.

The reference refcounts a SrcSubTopo per source so 300 rules over one MQTT
stream subscribe once and fan out in-process (reference:
internal/topo/subtopo.go:38-60, subtopo_pool.go:34). Here the shared unit is
the SourceNode (ingest → decode → schema coercion → micro-batch), whose tail
broadcasts ColumnBatches to each attached rule's entry node. Attach/detach
are refcounted; the pipeline opens on the first attach and closes when the
last rule detaches.

Sharing is restricted to qos=0 rules (the planner enforces it): checkpoint
barriers are injected at sources, and a shared source cannot carry
rule-private barriers. This matches the reference's default deployments —
its fan-out benchmark rules are all at-most-once.

Thread-safety: broadcast iterates the tail's `outputs` list, so attach and
detach REPLACE the list instead of mutating it (copy-on-write) — a broadcast
running concurrently keeps iterating its own snapshot.
"""
from __future__ import annotations

import json
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.infra import logger
from .node import Node


class _FanoutTopoShim:
    """Stands in as `_topo` for nodes owned by a subtopo: errors fan out to
    every attached rule's topo (each supervisor decides restart policy).
    Shared pipelines serve many rules at once, so their log records route
    to one __shared__ file rather than a single rule's (utils/rulelog)."""

    rule_id = "__shared__"

    def __init__(self, subtopo: "SrcSubTopo") -> None:
        self._subtopo = subtopo

    def drain_error(self, err: BaseException, origin: str = "") -> None:
        for topo in self._subtopo.attached_topos():
            topo.drain_error(err, f"shared:{origin}")

    def checkpoint_ack(self, node_name, barrier, state) -> None:
        # shared subtopos serve qos=0 rules only; no barriers flow here
        pass


class SubTopoRef:
    """Plan-time handle: the subtopo instance is resolved at Topo.open, not
    at plan time — a pooled instance may have closed (last rule detached)
    between planning and opening, and a fresh one must be built then."""

    def __init__(self, key: str, builder: Callable[[], List[Node]]) -> None:
        self.key = key
        self.builder = builder

    def resolve_and_attach(self, rule_id: str, entry: Node, topo: Any) -> "SrcSubTopo":
        # retry: get_or_create may return an instance that loses its last
        # rule and closes before our attach lands; closed instances refuse
        # the attach and are already evicted, so the next lookup builds fresh
        for _ in range(8):
            st = get_or_create(self.key, self.builder)
            if st.attach(rule_id, entry, topo):
                return st
        raise RuntimeError(f"cannot attach to subtopo {self.key}")


# Per-subtopo shared ingest prep — one key encode + one device upload per
# batch for every fan-out consumer. The implementation moved to
# runtime/ingest.py (IngestPrepCtx) when the decode pool gained the
# pipelined upload stage; this name stays for the subtopo-facing role.
from .ingest import IngestPrepCtx as SharedPrepCtx  # noqa: E402


class SrcSubTopo:
    def __init__(self, key: str, nodes: List[Node]) -> None:
        self.key = key
        self.nodes = nodes  # [source, *chain]; tail broadcasts to entries
        self._shim = _FanoutTopoShim(self)
        for n in nodes:
            n._topo = self._shim
            # shared nodes never pass through Topo.add_*: stamp the same
            # rule label the Prometheus exposition uses, so their
            # drop-burst flight events filter consistently
            n.stats.rule_id = "__shared__"
        self._lock = threading.RLock()
        self._attached: Dict[str, Tuple[Node, Any]] = {}
        self._opened = False
        self._closed = False
        # adopt the source's prep ctx when it has one (prep-enabled source:
        # its decode pool precomputes into the SAME ctx the entries attach
        # to batches), else create the subtopo-local one as before
        self.prep_ctx = (getattr(self.source, "prep_ctx", None)
                         or SharedPrepCtx())

    @property
    def tail(self) -> Node:
        return self.nodes[-1]

    @property
    def source(self) -> Node:
        return self.nodes[0]

    def attached_topos(self) -> List[Any]:
        with self._lock:
            return [t for _, t in self._attached.values()]

    def ref_count(self) -> int:
        with self._lock:
            return len(self._attached)

    def attach(self, rule_id: str, entry: Node, topo: Any) -> bool:
        """Returns False when this instance already closed (caller resolves
        a fresh one from the pool)."""
        with self._lock:
            if self._closed:
                return False
            if rule_id in self._attached:
                raise ValueError(f"rule {rule_id} already attached to {self.key}")
            self._attached[rule_id] = (entry, topo)
            entry.prep_ctx = self.prep_ctx  # shared fan-out ingest prep
            # plan-time upload specs stashed on the entry reach the shared
            # ctx here (the subtopo instance resolves only at open)
            reg = getattr(self.prep_ctx, "register_upload", None)
            if reg is not None:
                for spec in getattr(entry, "prep_specs", ()):
                    reg(*spec)
            self.tail.outputs = self.tail.outputs + [entry]  # copy-on-write
            if not self._opened:
                # chain first, source last, so the first payload finds the
                # downstream queues live (same order Topo.open uses)
                for n in reversed(self.nodes):
                    n.open()
                self._opened = True
                logger.debug("subtopo %s opened", self.key)
            return True

    def detach(self, rule_id: str) -> None:
        close_now = False
        with self._lock:
            got = self._attached.pop(rule_id, None)
            if got is None:
                return
            entry, _ = got
            self.tail.outputs = [o for o in self.tail.outputs if o is not entry]
            if not self._attached and self._opened:
                # mark closed + evict BEFORE releasing the lock: a concurrent
                # attach on this instance now returns False, and a concurrent
                # get_or_create builds a fresh instance
                self._closed = True
                close_now = True
                _pool_remove(self.key, self)
        if close_now:
            for n in self.nodes:
                n.close()
            for n in self.nodes:
                n.join(timeout=2.0)
            logger.debug("subtopo %s closed (last rule detached)", self.key)

    def status(self) -> Dict[str, Any]:
        return {n.name: n.stats for n in self.nodes}


class SharedEntryNode(Node):
    """Per-rule entry behind a shared source: a pass-through hop that gives
    the rule its own queue (backpressure isolation — one slow rule drops its
    own oldest items, reference subtopo semantics) and its own stats.

    Column pruning happens HERE for shared sources: the pooled pipeline
    serves rules with different column needs, so each rule prunes its own
    copy of the stream (planner/optimizer.py)."""

    def __init__(self, name: str, project_columns=None, **kw) -> None:
        super().__init__(name, op_type="op", **kw)
        self.project_columns = (set(project_columns)
                                if project_columns is not None else None)
        self.prep_ctx = None  # set by SrcSubTopo.attach
        self.prep_specs: List[tuple] = []  # plan-time upload specs

    def register_prep_spec(self, spec) -> None:
        """Stash a plan-time upload spec; SrcSubTopo.attach forwards it to
        the shared prep ctx once this entry joins a live subtopo."""
        self.prep_specs.append(spec)

    def process(self, item: Any) -> None:
        cols = self.project_columns
        from ..data.batch import ColumnBatch

        if isinstance(item, ColumnBatch) and item.shared_ctx is None:
            item.ensure_share_state()  # BEFORE any pruned copy forks it
            item.shared_ctx = self.prep_ctx
        if cols is not None:
            from ..data.rows import Tuple as Row

            if isinstance(item, ColumnBatch) and not (
                set(item.columns) <= cols
            ):
                # pruned COPY rides the same share cache: the original
                # column objects are identical, so slots/device uploads
                # computed by one rider serve every other rider too
                item = ColumnBatch(
                    n=item.n,
                    columns={k: v for k, v in item.columns.items()
                             if k in cols},
                    valid={k: v for k, v in item.valid.items() if k in cols},
                    timestamps=item.timestamps, emitter=item.emitter,
                    shared_ctx=item.shared_ctx,
                    share_state=item.share_state,
                    ingest_ms=item.ingest_ms,
                )
            elif isinstance(item, Row) and not (
                set(item.message) <= cols
            ):
                # COPY, never mutate: the shared tail broadcasts the same
                # object to every rider, each with its own pruning set
                item = Row(
                    emitter=item.emitter,
                    message={k: v for k, v in item.message.items()
                             if k in cols},
                    timestamp=item.timestamp,
                    metadata=getattr(item, "metadata", None) or {},
                )
        self.emit(item)


# ------------------------------------------------------------------- pool
_pool: Dict[str, SrcSubTopo] = {}
_pool_lock = threading.Lock()


def subtopo_key(stream_name: str, props: Dict[str, Any]) -> str:
    """Stable identity of a shareable source pipeline: the stream plus every
    config knob that changes what the pipeline emits."""
    return stream_name + ":" + json.dumps(props, sort_keys=True, default=str)


def get_or_create(key: str, builder: Callable[[], List[Node]]) -> SrcSubTopo:
    with _pool_lock:
        st = _pool.get(key)
    if st is not None:
        return st
    # build OUTSIDE the lock: connector construction/configure may do I/O,
    # and one slow source must not stall planning of unrelated rules
    candidate = SrcSubTopo(key, builder())
    with _pool_lock:
        st = _pool.get(key)
        if st is None:
            _pool[key] = candidate
            return candidate
    return st  # lost the race; unopened candidate is garbage-collected


def _pool_remove(key: str, subtopo: SrcSubTopo) -> None:
    with _pool_lock:
        if _pool.get(key) is subtopo:
            del _pool[key]


def pool_size() -> int:
    with _pool_lock:
        return len(_pool)


def reset() -> None:
    """Test hook: close and drop every pooled subtopo."""
    with _pool_lock:
        topos = list(_pool.values())
        _pool.clear()
    for st in topos:
        for n in st.nodes:
            n.close()
