"""AOT executable cache — zero-compile serving off the jitcert manifest.

The engine's compile lifecycle used to be lazy: every rule create,
recover() and capacity-ladder grow paid seconds of trace+compile before
first emit — the exact stall class TiLT (arxiv 2301.12030) argues a
compilation-based stream engine must move out of the serve path. jitcert
(observability/jitcert.py) already proves compilation is fully determined
at plan time: each kernel carries a CLOSED certificate of every
(shape, dtype) signature it may legally trace with, and certificate
signature strings are byte-identical to devwatch's observed
`_arg_signature` strings. That identity is the cache key.

`aot_jit(fn, op=...)` replaces `watched_jit` at every kernel jit site.
Dispatch goes through a per-site table of pre-compiled XLA executables
keyed by the call's shape/dtype signature:

- table hit: run the executable — no jax.jit dispatch, no trace risk;
- table miss, disk hit: `deserialize_and_load` the persisted executable
  (~tens of ms, amortized once per site×signature per process) — this is
  what makes restart a non-event;
- disk miss: `jax.jit(fn).lower(...).compile()` the signature now,
  persist it, and leave a paper trail — a serve-time compile after a warm
  boot is a bug, so outside a `building()` scope it records a flight
  event on top of the devwatch trace accounting.

The disk layer lives under `KUIPER_AOT_CACHE_DIR` (opt-in: unset means
in-memory pinning only, which preserves test determinism). Entries are
keyed by `sha256(op × signature × jax/jaxlib version × platform × device
count × mesh shape)` so a toolchain or topology change yields a clean
miss, never a stale-executable load. jitcert's certify output doubles as
the build manifest: `python -m tools.aot build` drives the certification
battery with the disk layer on, and `verify` checks every certified
signature resolves to a cache entry (docs/AOT_CACHE.md).

devwatch accounting is unchanged: every aot_jit site owns the same
OpWatch record watched_jit would have registered, compiles count as
traces (kuiper_xla_compile_total), and jitcert diff_live still holds the
observed-signatures ⊆ certificate invariant — a serve-time trace outside
the manifest remains a hard failure, now with a cache-miss event
attached.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time as _time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

#: per-site executable-table cap — certificates bound the legal signature
#: set well below this; a site past the cap has shape churn (devwatch
#: flags the storm) and stops pinning new executables rather than leak
TABLE_CAP = 128


def enabled() -> bool:
    """AOT dispatch kill switch (KUIPER_AOT=0 restores plain watched_jit
    semantics at every site)."""
    return os.environ.get("KUIPER_AOT", "1") != "0"


def cache_dir() -> Optional[str]:
    """On-disk layer root, or None when the disk layer is off."""
    d = os.environ.get("KUIPER_AOT_CACHE_DIR", "").strip()
    return d or None


# ------------------------------------------------------------ cache keys
def _fingerprint_parts() -> Tuple[str, ...]:
    """Everything outside (op, signature) that can invalidate a compiled
    executable: toolchain versions, backend, device topology. Split out
    so tests can monkeypatch one part and assert a clean miss."""
    import jax
    import jaxlib

    return (
        f"jax={jax.__version__}",
        f"jaxlib={jaxlib.__version__}",
        f"platform={jax.default_backend()}",
        f"devices={jax.device_count()}",
        f"mesh={os.environ.get('KUIPER_MESH', 'auto')}",
    )


def fingerprint() -> str:
    return "×".join(_fingerprint_parts())


def cache_key(op: str, signature: str, fp: Optional[str] = None) -> str:
    """Content address of one executable: hash(cert signature ×
    jaxlib/XLA version × mesh shape × platform). `signature` is the
    jitcert certificate string (== devwatch `_arg_signature`)."""
    fp = fingerprint() if fp is None else fp
    h = hashlib.sha256(f"{op}\n{signature}\n{fp}".encode())
    return h.hexdigest()


def _entry_path(root: str, key: str) -> str:
    return os.path.join(root, f"{key}.aotx")


def is_cached(op: str, signature: str, fp: Optional[str] = None) -> bool:
    """Disk-layer probe by certificate string alone — no kernel, no
    lowering. This is what admission pricing (runtime/control.py
    price.compile) and explain's "aot" section use: certified-but-
    uncached signatures are the compile debt a candidate rule carries."""
    root = cache_dir()
    if root is None:
        return False
    return os.path.exists(_entry_path(root, cache_key(op, signature, fp)))


# ----------------------------------------------------------------- stats
class _Stats:
    """Engine-wide counters behind kuiper_aot_* (all monotonic except
    `executables`, recomputed from live sites at scrape time)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.hits = 0          # calls served by a pre-built executable
        self.misses = 0        # lower+compile events (build or serve)
        self.serve_misses = 0  # misses outside a building() scope
        self.disk_loads = 0    # executables deserialized from disk
        self.builds = 0        # executables compiled + persisted
        self.build_seconds = 0.0
        self.warmup_failures = 0

    def snapshot(self) -> Dict[str, Any]:
        with self.lock:
            return {
                "enabled": enabled(), "dir": cache_dir(),
                "hits": self.hits, "misses": self.misses,
                "serve_misses": self.serve_misses,
                "disk_loads": self.disk_loads, "builds": self.builds,
                "build_seconds": round(self.build_seconds, 3),
                "executables": executables_live(),
                "warmup_failures": self.warmup_failures,
            }


_stats = _Stats()
_tls = threading.local()


def stats() -> _Stats:
    return _stats


@contextmanager
def building():
    """Marks the current thread as running a deliberate cache build
    (boot prebuild, worker warmup, `tools/aot build`): misses inside the
    scope are the build doing its job and skip the serve-time flight
    event. Nests."""
    depth = getattr(_tls, "building", 0)
    _tls.building = depth + 1
    try:
        yield
    finally:
        _tls.building = depth


def in_build() -> bool:
    return getattr(_tls, "building", 0) > 0


def note_warmup_failure(rule: str, stage: str, exc: BaseException) -> None:
    """A failed warmup is a guaranteed serve-time compile stall later —
    count it (kuiper_warmup_failures_total) and leave a flight event so
    it bisects to a stage, never a silent logger.debug."""
    from .events import recorder

    with _stats.lock:
        _stats.warmup_failures += 1
    recorder().record(
        "warmup_failure", rule=rule or "", severity="warn", stage=stage,
        error=f"{type(exc).__name__}: {exc}"[:256])


# ---------------------------------------------------------- site registry
class _SiteRegistry:
    """Weakref index of live _AotJit sites (explain "aot" section,
    kuiper_aot_executables, /diagnostics rollups). Ownership stays with
    the kernel object, exactly like devwatch's watch registry."""

    def __init__(self) -> None:
        import weakref

        self._weakref = weakref
        self._lock = threading.Lock()
        self._sites: List = []  # weakref.ref[_AotJit]

    def register(self, site: "_AotJit") -> None:
        with self._lock:
            self._sites.append(self._weakref.ref(site))
            if len(self._sites) % 64 == 0:
                self._sites = [r for r in self._sites if r() is not None]

    def sites(self) -> List["_AotJit"]:
        with self._lock:
            refs = list(self._sites)
        return [s for s in (r() for r in refs) if s is not None]

    def clear(self) -> None:
        with self._lock:
            self._sites.clear()


_sites = _SiteRegistry()


def executables_live() -> int:
    return sum(len(s._table) for s in _sites.sites())


def site_report(rule: Optional[str] = None) -> List[Dict[str, Any]]:
    """Per-site hit/miss rollup (explain "aot" section, /status)."""
    out = []
    for s in _sites.sites():
        if rule is not None and (s.rec.rule or "") != rule:
            continue
        out.append({
            "op": s.rec.op, "rule": s.rec.rule or "",
            "hits": s.hits, "misses": s.misses,
            "disk_loads": s.disk_loads, "executables": len(s._table),
            "degraded": s._degraded,
        })
    out.sort(key=lambda r: (r["op"], r["rule"]))
    return out


# ------------------------------------------------------------- the wrapper
def _fast_key(args: tuple, kwargs: dict) -> tuple:
    """Executable-table key: hashable twin of devwatch._arg_signature
    (arrays by (dtype, shape), statics by value). Kept allocation-light —
    this runs on the hot fold path where the jit dispatch used to be."""
    import jax

    key: List[Any] = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs)):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            key.append((dtype, tuple(shape)))
        else:
            try:
                hash(leaf)
                key.append(leaf)
            except TypeError:
                key.append(repr(leaf)[:48])
    return tuple(key)


class _AotJit:
    """The callable aot_jit returns. Semantically a jax.jit(fn,
    **jit_kwargs) — identical outputs, identical donation — but dispatch
    rides an explicit signature→Compiled table so executables can be
    installed from disk before the first call ever traces."""

    def __init__(self, fn: Callable, rec, jit_kwargs: dict) -> None:
        import jax

        self.rec = rec  # devwatch.OpWatch — shared accounting spine
        self._fn = fn
        self._jit_kwargs = dict(jit_kwargs)
        static = jit_kwargs.get("static_argnums", ())
        if isinstance(static, int):
            static = (static,)
        self._static = frozenset(static)
        self._jit = jax.jit(fn, **jit_kwargs)  # lowering seam only
        self._table: Dict[tuple, Any] = {}  # fast key -> Compiled
        self._lock = threading.Lock()
        self._fallback = None  # devwatch._WatchedJit, built on first need
        self._degraded = False  # AOT machinery failed — plain jit path
        self.hits = 0
        self.misses = 0
        self.disk_loads = 0
        _sites.register(self)

    # ------------------------------------------------------------ helpers
    def _strip_static(self, args: tuple) -> tuple:
        if not self._static:
            return args
        return tuple(a for i, a in enumerate(args)
                     if i not in self._static)

    def _ensure_fallback(self):
        if self._fallback is None:
            from ..observability import devwatch

            self._fallback = devwatch._WatchedJit.__new__(
                devwatch._WatchedJit)
            devwatch._WatchedJit.__init__(
                self._fallback, self._fn, self.rec, self._jit_kwargs)
        return self._fallback

    def _signature(self, args: tuple, kwargs: dict) -> str:
        from ..observability import devwatch

        try:
            return devwatch._arg_signature(args, kwargs)
        except Exception:
            return "<unavailable>"

    def _load_from_disk(self, sig: str):
        """Deserialize one persisted executable, or None. A corrupt or
        foreign entry is unlinked and treated as a miss — never a
        stale-executable load (the key already pins op × signature ×
        toolchain × topology; the meta check is belt and braces)."""
        root = cache_dir()
        if root is None:
            return None
        path = _entry_path(root, cache_key(self.rec.op, sig))
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as fh:
                blob = pickle.load(fh)
            meta = blob.get("meta", {})
            if (meta.get("fingerprint") != fingerprint()
                    or meta.get("op") != self.rec.op
                    or meta.get("signature") != sig):
                raise ValueError("cache entry metadata mismatch")
            from jax.experimental import serialize_executable

            compiled = serialize_executable.deserialize_and_load(
                blob["payload"], blob["in_tree"], blob["out_tree"])
        except Exception:
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        cost = meta.get("cost")
        if cost:
            try:
                self.rec.kern.set_cost(cost.get("flops"),
                                       cost.get("bytes"))
            except Exception:
                pass
        return compiled

    def _persist(self, compiled, sig: str, compile_s: float,
                 cost: Optional[dict]) -> None:
        root = cache_dir()
        if root is None:
            return
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled)
            blob = {
                "payload": payload, "in_tree": in_tree,
                "out_tree": out_tree,
                "meta": {
                    "op": self.rec.op, "signature": sig,
                    "fingerprint": fingerprint(),
                    "compile_s": round(compile_s, 4), "cost": cost,
                },
            }
            os.makedirs(root, exist_ok=True)
            path = _entry_path(root, cache_key(self.rec.op, sig))
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                pickle.dump(blob, fh)
            os.replace(tmp, path)  # atomic: concurrent builders race safely
        except Exception as exc:
            from ..utils.infra import logger

            logger.debug("aot persist failed for %s (non-fatal): %s",
                         self.rec.op, exc)

    def _build(self, key: tuple, sig: str, args: tuple, kwargs: dict):
        """The true-miss path: lower (accepts ShapeDtypeStruct leaves in
        place of arrays), compile, persist, account. Returns Compiled."""
        rec = self.rec
        t0 = _time.perf_counter()
        lowered = self._jit.lower(*args, **kwargs)
        compiled = lowered.compile()
        dt = _time.perf_counter() - t0
        rec.on_compile(dt * 1e6, args, kwargs)
        rec.kern.on_compile(_Prelowered(lowered), args, kwargs)
        cost = None
        try:
            ca = lowered.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if isinstance(ca, dict):
                cost = {"flops": ca.get("flops"),
                        "bytes": ca.get("bytes accessed")}
        except Exception:
            pass
        self._persist(compiled, sig, dt, cost)
        with _stats.lock:
            _stats.misses += 1
            _stats.builds += 1
            _stats.build_seconds += dt
            serve = not in_build()
            if serve:
                _stats.serve_misses += 1
        self.misses += 1
        if serve:
            # a compile AFTER warm boot is the bug this cache exists to
            # kill — paper trail, not just a counter
            from .events import recorder

            recorder().record(
                "aot_cache_miss", rule=rec.rule or "", severity="warn",
                op=rec.op, signature=sig[:256],
                compile_ms=round(dt * 1e3, 1),
                disk=cache_dir() is not None)
        self._install(key, compiled)
        return compiled

    def _install(self, key: tuple, compiled) -> None:
        with self._lock:
            if len(self._table) < TABLE_CAP:
                self._table[key] = compiled

    # ------------------------------------------------------------ dispatch
    def probe(self, *args, **kwargs) -> str:
        """Ensure the executable for this argument signature exists
        WITHOUT executing anything — leaves may be ShapeDtypeStructs.
        This is what nodes_fused warmup runs at worker start: a warm
        disk cache makes it a deserialization sweep (tens of ms); a cold
        one makes it the build. Returns "mem" | "disk" | "built"
        ("jit" when AOT is degraded/disabled for the site)."""
        if self._degraded:
            return "jit"
        key = _fast_key(args, kwargs)
        with self._lock:
            if key in self._table:
                return "mem"
        sig = self._signature(args, kwargs)
        try:
            compiled = self._load_from_disk(sig)
            if compiled is not None:
                self.disk_loads += 1
                with _stats.lock:
                    _stats.disk_loads += 1
                self._install(key, compiled)
                return "disk"
            self._build(key, sig, args, kwargs)
            return "built"
        except Exception as exc:
            self._degrade(exc)
            return "jit"

    def _degrade(self, exc: BaseException) -> None:
        """AOT machinery failure (serializer gap, backend quirk): fall
        back to the plain watched jit path for this site, permanently
        and loudly — correctness first, zero-compile second."""
        from ..utils.infra import logger
        from .events import recorder

        self._degraded = True
        logger.warning("aot cache degraded for %s (plain jit path): %s",
                       self.rec.op, exc)
        recorder().record(
            "aot_degraded", rule=self.rec.rule or "", severity="warn",
            op=self.rec.op, error=f"{type(exc).__name__}: {exc}"[:256])

    def __call__(self, *args, **kwargs):
        rec = self.rec
        if self._degraded:
            return self._ensure_fallback()(*args, **kwargs)
        kern = rec.kern
        sampled = kern.tick()
        key = _fast_key(args, kwargs)
        compiled = self._table.get(key)
        if compiled is None:
            sig = self._signature(args, kwargs)
            try:
                compiled = self._load_from_disk(sig)
                if compiled is not None:
                    self.disk_loads += 1
                    with _stats.lock:
                        _stats.disk_loads += 1
                    self._install(key, compiled)
                else:
                    compiled = self._build(key, sig, args, kwargs)
            except Exception as exc:
                self._degrade(exc)
                return self._ensure_fallback()(*args, **kwargs)
        t0 = _time.perf_counter()
        try:
            out = compiled(*self._strip_static(args), **kwargs)
        except TypeError as exc:
            # calling-convention drift (args/kwargs split differs from
            # the lowered structure) surfaces as a pytree mismatch BEFORE
            # dispatch — donation has not fired; degrade, don't crash
            self._degrade(exc)
            return self._ensure_fallback()(*args, **kwargs)
        t1 = _time.perf_counter()
        rec.calls += 1
        self.hits += 1
        with _stats.lock:
            _stats.hits += 1
        if sampled:
            kern.sample(out, t0, t1, args, kwargs)
        return out


class _Prelowered:
    """Adapter handing kernwatch.on_compile an already-lowered program
    (its contract is `jitted.lower(*args, **kwargs).cost_analysis()`;
    re-lowering here would double the trace cost of every build)."""

    def __init__(self, lowered) -> None:
        self._lowered = lowered

    def lower(self, *args, **kwargs):
        return self._lowered


def aot_jit(fn: Callable, op: str, kind: str = "hot",
            **jit_kwargs) -> Callable:
    """Drop-in watched_jit with AOT-cached dispatch. Same accounting
    (devwatch OpWatch, kernwatch record), same jit semantics (donation,
    static argnums), plus: executables install from the on-disk cache
    before any trace, and serve-time compiles leave a flight event.
    KUIPER_AOT=0 returns the plain watched path."""
    from ..observability import devwatch

    if not enabled():
        return devwatch.watched_jit(fn, op, kind=kind, **jit_kwargs)
    from ..utils.rulelog import current_rule

    rec = devwatch.registry().register(op, current_rule(), kind)
    return _AotJit(fn, rec, jit_kwargs)


# ------------------------------------------------------------ admission
def plan_compile_price(certs) -> Dict[str, Any]:
    """Admission's compile ledger for one candidate plan: how many
    certified signatures its kernels may trace, and how many already
    have a persisted executable. Admission prices the DIFFERENCE — a
    warm fleet image admits rules against near-zero compile debt.
    `certs` is a list of jitcert.SiteCert."""
    fp = fingerprint()
    root = cache_dir()
    certified = cached = 0
    truncated = False
    sites = []
    for c in certs:
        n_cached = 0
        if root is not None and not c.truncated:
            n_cached = sum(1 for s in c.signatures if is_cached(c.op, s, fp))
        certified += c.full_count
        cached += n_cached
        truncated = truncated or c.truncated
        sites.append({"op": c.op, "certified": c.full_count,
                      "cached": n_cached})
    return {
        "enabled": root is not None,
        "certified": certified,
        "cached": cached,
        "uncached": max(certified - cached, 0),
        "truncated": truncated,
        "sites": sites,
    }


# ----------------------------------------------------------- observability
def render_prometheus(out: List[str], esc) -> None:
    """Append the kuiper_aot_* families (+ the warmup-failure counter)
    to a /metrics scrape."""
    snap = _stats.snapshot()
    fams = (
        ("kuiper_aot_hits_total", "counter",
         "calls served by a pre-built AOT executable", snap["hits"]),
        ("kuiper_aot_misses_total", "counter",
         "jit sites lowered+compiled at runtime (build or serve)",
         snap["misses"]),
        ("kuiper_aot_serve_misses_total", "counter",
         "AOT compiles OUTSIDE a build/warmup scope — warm-boot bugs",
         snap["serve_misses"]),
        ("kuiper_aot_disk_loads_total", "counter",
         "executables deserialized from the on-disk AOT cache",
         snap["disk_loads"]),
        ("kuiper_aot_build_seconds", "counter",
         "cumulative XLA compile seconds spent building AOT executables",
         snap["build_seconds"]),
        ("kuiper_aot_executables", "gauge",
         "pre-built executables pinned across live jit sites",
         snap["executables"]),
        ("kuiper_warmup_failures_total", "counter",
         "worker warmup/cache-probe failures (future serve-time "
         "compile stalls)", snap["warmup_failures"]),
    )
    for name, mtype, help_txt, value in fams:
        out.append(f"# TYPE {name} {mtype}")
        out.append(f"# HELP {name} {help_txt}")
        out.append(f"{name} {value}")


def reset() -> None:
    """Test hook: drop all counters and site registrations (the sites
    themselves live on their kernels and keep working)."""
    global _stats
    _stats = _Stats()
    _sites.clear()
