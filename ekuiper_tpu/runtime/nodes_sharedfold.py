"""Shared pane-fold node — one device fold serving N correlated rules.

The subtopo pool (runtime/subtopo.py) already shares the source, decode,
key encode and device upload across rules of one stream; the expensive
part — the ops/groupby.py device fold — still ran once per rule. This
node closes that gap for rules the planner proves correlated
(planner/sharing.py: identical GROUP BY key set + WHERE, unionable
aggregate specs, window length/interval integer multiples of a common
pane): every batch folds ONCE into a shared pane ring (ops/panestore.py),
and each member rule gets a lightweight emit hop that combines the panes
spanning its window and runs its own vectorized tail into its own sink
chain.

Topology: the store rides the shared subtopo as ONE rider (rider id
"__fold__:<key>"), so the pool's refcounting, prep-ctx forwarding and
copy-on-write fan-out all apply unchanged:

    SrcSubTopo tail ─► [WatermarkNode]? ─► SharedFoldNode ─► rule A emit hop ─► A's sinks
                                                          └► rule B emit hop ─► B's sinks

Attach/detach are refcounted per member rule: a late-joining rule warms
from the LIVE panes (its first window may cover rows folded before it
attached — documented warmup semantics, docs/SHARING.md) without
restarting peers; the last detach tears the store down and releases the
subtopo rider. Shared folds serve qos=0 rules only (same restriction as
the subtopo pool — rule-scoped barriers cannot flow through a shared
pipeline); snapshot/restore still exists at node level (per-rule emit
cursors + pane partials) for save/restore tooling and tests.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..data.batch import ColumnBatch
from ..data.rows import Tuple as Row, WindowRange
from ..ops.aggspec import HH_COL_PREFIX, HLL_COL_PREFIX, KernelPlan
from ..ops.panestore import PaneStore, build_value_columns, spec_map_into
from ..utils import timex
from ..utils.infra import logger
from .events import EOF, Trigger, Watermark
from .node import Node


@dataclass
class MemberSpec:
    """Everything the store needs to emit one rule's windows."""

    rule_id: str
    length_ms: int
    interval_ms: int  # == length_ms for tumbling
    plan: KernelPlan  # the rule's OWN plan (spec order = direct_emit order)
    direct_emit: Any  # ops/emit.py DirectEmitPlan
    dims: List[str] = field(default_factory=list)
    emit_columnar: bool = True
    #: predicate lifting (ops/aggspec.py lift_predicate): index (into
    #: `plan.specs`) of the synthetic `count(*) FILTER(WHERE <pred>)`
    #: activity spec this member's group existence reads from; None =
    #: the store's global `act` (member folds every row)
    act_idx: Any = None


class _Member:
    __slots__ = ("spec", "entry", "topo", "span", "spec_map", "last_end_ms",
                 "attach_bucket")

    def __init__(self, spec: MemberSpec, entry: Node, topo: Any,
                 span: int, spec_map: List[int],
                 last_end_ms: Optional[int], attach_bucket: int) -> None:
        self.spec = spec
        self.entry = entry
        self.topo = topo
        self.span = span
        self.spec_map = spec_map
        self.last_end_ms = last_end_ms  # event-time emit cursor
        self.attach_bucket = attach_bucket


class SharedEmitNode(Node):
    """Per-rule emit hop behind a shared fold: gives the rule its own
    queue (backpressure isolation — one slow sink chain cannot stall the
    shared fold or its peers) and its own stats. Window results arrive
    fully combined; HAVING/ORDER/projection already ran in the member's
    vectorized tail inside the store."""

    def __init__(self, name: str, **kw) -> None:
        super().__init__(name, op_type="op", **kw)

    def process(self, item: Any) -> None:
        self.emit(item)


class _StoreShim:
    """Stands in as `_topo` for the store + its watermark node: errors fan
    out to every member rule's topo; log records route to the __shared__
    file (same contract as subtopo._FanoutTopoShim)."""

    rule_id = "__shared__"

    def __init__(self, store: "SharedFoldNode") -> None:
        self._store = store

    def drain_error(self, err: BaseException, origin: str = "") -> None:
        for topo in self._store.member_topos():
            topo.drain_error(err, f"sharedfold:{origin}")

    def checkpoint_ack(self, node_name, barrier, state) -> None:
        pass  # shared folds serve qos=0 rules only; no barriers flow here


class SharedFoldNode(Node):
    def __init__(
        self,
        key: str,
        name: str,
        plan: KernelPlan,
        pane_ms: int,
        n_panes: int,
        subtopo_ref=None,  # runtime/subtopo.py SubTopoRef; None = standalone
        capacity: int = 16384,
        micro_batch: int = 4096,
        is_event_time: bool = False,
        late_tolerance_ms: int = 0,
        buffer_length: int = 1024,
        mesh_cfg=None,
    ) -> None:
        super().__init__(name, op_type="op", buffer_length=buffer_length)
        self.key = key
        self.rider_id = "__fold__:" + key
        self.plan = plan
        self.pane_ms = int(pane_ms)
        self.n_panes = int(n_panes)
        self.is_event_time = bool(is_event_time)
        self.late_tolerance_ms = int(late_tolerance_ms)
        # key-range-sharded store (ISSUE 15): same-mesh members pool a
        # pane ring partitioned over the mesh's "keys" axis; an
        # unavailable mesh degrades to the single-chip store with a log
        # (the store key's mesh facet kept mismatched peers apart)
        mesh = None
        if mesh_cfg:
            from ..parallel.mesh import mesh_from_options, resolve_auto_cfg

            try:
                resolved = resolve_auto_cfg(dict(mesh_cfg))
                mesh = (mesh_from_options(resolved)
                        if resolved is not None else None)
            except Exception as exc:
                logger.warning(
                    "%s: shared pane store mesh %s unavailable (%s) — "
                    "single-chip store", name, mesh_cfg, exc)
        self.store = PaneStore(plan, pane_ms, n_panes, capacity=capacity,
                               micro_batch=micro_batch, mesh=mesh)
        self.dims: List[str] = []  # set by first attach (compat-keyed)
        self._members: Dict[str, _Member] = {}
        self._mlock = threading.RLock()
        self._subtopo = None
        self._subtopo_ref = subtopo_ref
        self._wm_node = None
        if is_event_time:
            from .nodes_window import WatermarkNode

            self._wm_node = WatermarkNode(
                f"{name}_wm", late_tolerance_ms=late_tolerance_ms,
                buffer_length=buffer_length)
            self._wm_node.connect(self)
        self._topo = _StoreShim(self)
        # shared store nodes are emitted under rule="__shared__" in the
        # scrape; their flight events (pane_recycle bursts) carry the
        # same label so /diagnostics/events?rule= filtering lines up
        self.stats.rule_id = "__shared__"
        if self._wm_node is not None:
            self._wm_node._topo = self._topo
            self._wm_node.stats.rule_id = "__shared__"
        self._opened = False
        self._closed = False
        self._tick_timer = None
        # pane bookkeeping: bucket = (time or event ts) // pane_ms,
        # pane = bucket % n_panes
        self._cur_bucket = timex.now_ms() // self.pane_ms
        self._pane_bucket: Dict[int, int] = {}
        self._dirty: set = set()
        self._floor_bucket: Optional[int] = None  # event time: emitted floor
        # cursors restored ahead of member re-attach (restore_state)
        self._restored_cursors: Dict[str, int] = {}
        # shared-source fan-out key encode (mirrors nodes_fused.py
        # _shared_encode): None = undecided, False = self-encode forever.
        # A live tier (ops/tierstore.py) recycles slots, which breaks the
        # neutral table's dense insertion-order contract — self-encode.
        self._shared_slots_ok: Optional[bool] = (
            None if self.store.tier is None else False)
        self._shared_nkt = None
        self.prep_ctx = None  # set by SrcSubTopo.attach
        self.prep_specs: List[tuple] = [self._prep_spec()]
        # fold-dedup telemetry: would = folds N private rules would have
        # run for the folded batches, did = folds this store actually ran
        self.folds_did = 0
        self.folds_would = 0
        self.windows_emitted = 0

    # ------------------------------------------------------------- accessors
    def member_count(self) -> int:
        return len(self._members)

    def member_topos(self) -> List[Any]:
        return [m.topo for m in self._members.values()]

    def pipeline_nodes(self) -> List[Node]:
        nodes: List[Node] = []
        if self._subtopo is not None:
            nodes.extend(self._subtopo.nodes)
        if self._wm_node is not None:
            nodes.append(self._wm_node)
        nodes.append(self)
        return nodes

    @property
    def source(self) -> Optional[Node]:
        return self._subtopo.source if self._subtopo is not None else None

    def fold_dedup_ratio(self) -> float:
        """1 - actual folds / folds N private rules would have run."""
        if self.folds_would <= 0:
            return 0.0
        return 1.0 - self.folds_did / self.folds_would

    def pane_occupancy(self) -> float:
        """Fraction of the pane ring held by unexpired (dirty) buckets —
        occupancy approaching 1.0 under event time means the watermark
        lags far enough that panes risk recycling before emission (the
        counted `pane_recycle` loss mode). Health-evaluator probe."""
        return len(self._dirty) / max(self.n_panes, 1)

    def member_cursor_ms(self, rule_id: str) -> Optional[int]:
        """One member rule's event-time emit cursor (last emitted window
        end). Watermark lag is a PER-RULE fact even though the pane store
        is shared — each member advances its own cursor."""
        m = self._members.get(rule_id)
        return m.last_end_ms if m is not None else None

    def _prep_spec(self):
        """(key_name, kernel columns, micro_batch, derived, sharding,
        mesh_tag) for the shared ingest prep's upload stage — the union
        plan's one declaration of what precompute() should pre-upload
        for this store (incl. the members' predicate-lift derived
        columns, keyed by the union's expression-IR hash; sharded stores
        add their row sharding + mesh tag, nodes_fused.py prep_spec)."""
        from ..sql.expr_ir import is_derived_expr_col

        key_name = self.dims[0] if len(self.dims) == 1 else None
        # same gate as nodes_fused.prep_spec: never register a mesh
        # placement the kernel won't consume (multi-process meshes)
        shard_ok = (getattr(self.store.gb, "mesh_tag", "")
                    and getattr(self.store.gb, "accepts_device_inputs",
                                False))
        return (key_name,
                [n for n in self.plan.columns
                 if not n.startswith(HLL_COL_PREFIX)
                 and not n.startswith(HH_COL_PREFIX)
                 and not is_derived_expr_col(n)],
                self.store.gb.micro_batch,
                ((self.plan.expr_tag, self.plan.derived)
                 if getattr(self.plan, "derived", ()) else None),
                self.store.gb.batch_sharding if shard_ok else None,
                self.store.gb.mesh_tag if shard_ok else "")

    # --------------------------------------------------------- attach/detach
    def attach_rule(self, spec: MemberSpec, entry: Node, topo: Any) -> bool:
        """Join a rule to the shared fold. Returns False when this store
        already closed (caller resolves a fresh one from the pool); raises
        on geometry/spec mismatch — the planner declines such rules, so a
        mismatch here is a plan/open race and must fail loudly."""
        with self._mlock:
            if self._closed:
                return False
            if spec.rule_id in self._members:
                raise ValueError(
                    f"rule {spec.rule_id} already attached to {self.name}")
            if spec.length_ms % self.pane_ms or \
                    spec.interval_ms % self.pane_ms:
                raise RuntimeError(
                    f"{self.name}: rule {spec.rule_id} window "
                    f"({spec.length_ms}/{spec.interval_ms}ms) is not a "
                    f"multiple of the live {self.pane_ms}ms pane — replan")
            span = spec.length_ms // self.pane_ms
            if span > self.n_panes - 1:
                raise RuntimeError(
                    f"{self.name}: rule {spec.rule_id} spans {span} panes, "
                    f"store holds {self.n_panes} — replan")
            spec_map = spec_map_into(self.plan, spec.plan)
            if not self._members:
                self.dims = list(spec.dims)
                self.prep_specs = [self._prep_spec()]
            elif list(spec.dims) != self.dims:
                raise RuntimeError(
                    f"{self.name}: rule {spec.rule_id} GROUP BY "
                    f"{spec.dims} != store key set {self.dims} — replan")
            m = _Member(spec, entry, topo, span, spec_map,
                        self._restored_cursors.get(spec.rule_id),
                        self._cur_bucket)
            members = dict(self._members)
            members[spec.rule_id] = m
            self._members = members  # copy-on-write (concurrent boundary)
            # control events (EOF, watermarks) reach the rule's chain
            self.outputs = self.outputs + [entry]
            if not self._opened:
                self._open_pipeline()
                self._opened = True
            logger.debug("%s: rule %s attached (%d member(s), warm from "
                         "live panes)", self.name, spec.rule_id,
                         len(members))
            from .events import recorder

            recorder().record("shared_fold_attach", rule=spec.rule_id,
                              store=self.name, members=len(members))
            return True

    def detach_rule(self, rule_id: str) -> None:
        close_now = False
        with self._mlock:
            m = self._members.get(rule_id)
            if m is None:
                return
            members = dict(self._members)
            del members[rule_id]
            self._members = members
            from .events import recorder

            recorder().record("shared_fold_detach", rule=rule_id,
                              store=self.name, members=len(members))
            self.outputs = [o for o in self.outputs if o is not m.entry]
            if not members and self._opened:
                self._closed = True
                close_now = True
                _pool_remove(self.key, self)
        if close_now:
            if self._tick_timer is not None:
                self._tick_timer.stop()
            if self._subtopo is not None:
                self._subtopo.detach(self.rider_id)
            for n in ([self._wm_node] if self._wm_node is not None else []):
                n.close()
            self.close()
            for n in ([self._wm_node] if self._wm_node else []) + [self]:
                n.join(timeout=2.0)
            logger.debug("shared fold %s closed (last rule detached)",
                         self.name)

    def _open_pipeline(self) -> None:
        """Start this node (+ watermark hop) and ride the shared subtopo
        as one rider. Standalone mode (no subtopo_ref — benches/tests
        driving process()/on_trigger directly) skips both."""
        if self._subtopo_ref is None:
            return
        head = self._wm_node if self._wm_node is not None else self
        # prep specs stashed on whichever node attaches reach the shared
        # ingest ctx through SrcSubTopo.attach's forwarding
        head.prep_specs = self.prep_specs
        self.open()
        if self._wm_node is not None:
            self._wm_node.open()
        self._subtopo = self._subtopo_ref.resolve_and_attach(
            self.rider_id, head, self._topo)
        if self.prep_ctx is None:
            self.prep_ctx = getattr(head, "prep_ctx", None)

    def status(self) -> Dict[str, Any]:
        out = ({} if self._subtopo is None
               else dict(self._subtopo.status()))
        if self._wm_node is not None:
            out[self._wm_node.name] = self._wm_node.stats
        out[self.name] = self.stats
        return out

    # -------------------------------------------------------------- lifecycle
    def on_open(self) -> None:
        self._cur_bucket = timex.now_ms() // self.pane_ms
        if not self.is_event_time:
            self._schedule_tick()

    def on_worker_start(self) -> None:
        self.store.warmup()

    def on_close(self) -> None:
        if self._tick_timer is not None:
            self._tick_timer.stop()

    def _schedule_tick(self) -> None:
        """Arm the next pane-boundary trigger. Re-arms from the timer
        callback itself (not the worker) so a burst of elapsed panes
        enqueues one trigger per boundary in order — the worker then
        advances bucket state strictly by queue order, exactly like the
        private fused node's cur_pane."""
        now = timex.now_ms()
        end = timex.align_to_window(now + 1, self.pane_ms)

        def fire(ts: int, end=end) -> None:
            if self._closed or self._stop.is_set():
                return
            # carry the SCHEDULED boundary, not the fire time: the real
            # clock invokes callbacks with the actual (sleep-overshot)
            # time, and an off-grid ts would fail every member's
            # `end % interval == 0` emission gate forever
            self.put_control(Trigger(ts=end))
            self._schedule_tick()

        self._tick_timer = timex.after(end - now, fire)

    # ------------------------------------------------------------------- data
    def process(self, item: Any) -> None:
        if not isinstance(item, ColumnBatch):
            if isinstance(item, Row):
                from ..data.batch import from_tuples

                item = from_tuples([item], emitter=item.emitter)
            else:
                self.broadcast(item)
                return
        if item.n == 0:
            return
        if item.shared_ctx is None and self.prep_ctx is not None:
            item.ensure_share_state()
            item.shared_ctx = self.prep_ctx
        self._fold(item)

    def _fold(self, sub: ColumnBatch) -> None:
        import time as _time

        t0 = _time.perf_counter()
        slots = self._encode(sub)
        cols, valid = build_value_columns(self.plan, sub)
        if self.is_event_time:
            sub, cols, valid, slots, pane_arg = self._event_panes(
                sub, cols, valid, slots)
            if sub is None:
                return  # every row was late (pane recycled)
        else:
            b = self._cur_bucket
            pane = b % self.n_panes
            held = self._pane_bucket.get(pane)
            if held is not None and held != b:
                # safety net — rotation resets ahead of reuse normally
                self.store.reset_pane(pane)
                self._dirty.discard(held)
            self._pane_bucket[pane] = b
            self._dirty.add(b)
            pane_arg = pane
        dev = self._device_inputs(sub, cols, valid, slots)
        t1 = _time.perf_counter()
        self.stats.observe_stage("upload", (t1 - t0) * 1e6, sub.n)
        if dev is not None:
            dcols, dvalid, dslots = dev
            self.store.fold({**cols, **dcols},
                            {**valid, **dvalid},
                            dslots if dslots is not None else slots,
                            pane_arg, n_rows=sub.n)
        else:
            self.store.fold(cols, valid, slots, pane_arg)
        self.stats.observe_stage(
            "fold", (_time.perf_counter() - t1) * 1e6, sub.n)
        if hasattr(self.store.gb, "note_rows"):
            # per-shard accounting (kuiper_shard_*): the kernel counts
            # host slot vectors itself; the prep path hands it DEVICE
            # slots, so count off the host copy here (nodes_fused twin)
            if dev is not None and dev[2] is not None:
                self.store.gb.note_rows(slots, sub.n,
                                        n_keys=self.store.kt.n_keys)
            else:
                self.store.gb.n_keys_hint = self.store.kt.n_keys
        self.folds_did += 1
        self.folds_would += max(len(self._members), 1)

    def _event_panes(self, sub, cols, valid, slots):
        """Event-time pane routing: bucket = ts // pane_ms. Rows whose
        pane was recycled past their bucket drop (counted); panes are
        claimed/reset per new bucket."""
        ts = sub.timestamps
        if ts is None:
            ts = np.zeros(sub.n, dtype=np.int64)
        buckets = ts // self.pane_ms
        if self._floor_bucket is None:
            self._floor_bucket = int(buckets.min())
        # drop (a) rows below the emitted floor — including rows a single
        # wide batch would alias onto a newer bucket's pane (in-batch
        # spread >= n_panes) — and (b) rows whose pane a NEWER bucket
        # already claimed: folding either would add old rows into the
        # newer window's aggregates. Bounded panes trade the host path's
        # unbounded buffering for device residence; every drop is counted
        # (same contract as the fused event path).
        lo = max(self._floor_bucket,
                 int(buckets.max()) - self.n_panes + 1)
        drop = buckets < lo
        for b in np.unique(buckets).tolist():
            held = self._pane_bucket.get(int(b) % self.n_panes)
            if held is not None and held > int(b):
                drop |= buckets == b
        if drop.any():
            self.stats.inc_dropped(
                "pane_recycle", n=int(drop.sum()),
                detail="late event (pane emitted/recycled)")
            keep = np.nonzero(~drop)[0]
            if len(keep) == 0:
                return None, None, None, None, None
            sub = sub.take(keep)
            cols = {k: v[keep] for k, v in cols.items()}
            valid = {k: v[keep] for k, v in valid.items()}
            slots = slots[keep]
            buckets = buckets[keep]
        for b in np.unique(buckets).tolist():
            b = int(b)
            pane = b % self.n_panes
            held = self._pane_bucket.get(pane)
            if held is not None and held != b:
                # held < b here (newer buckets were dropped above): the
                # older bucket's partials are discarded. If its windows had
                # not emitted yet (watermark lagging past the pane budget)
                # that is COUNTED data loss, never corruption.
                if held in self._dirty:
                    self.stats.inc_dropped(
                        "pane_recycle",
                        detail="recycled before emission (watermark lag)")
                self.store.reset_pane(pane)
                self._dirty.discard(held)
            self._pane_bucket[pane] = b
            self._dirty.add(b)
        ub = np.unique(buckets)
        pane_arg = (int(ub[0]) % self.n_panes if len(ub) == 1
                    else (buckets % self.n_panes).astype(np.uint8))
        self._cur_bucket = max(self._cur_bucket, int(buckets.max()))
        return sub, cols, valid, slots, pane_arg

    # ------------------------------------------------------------- key encode
    def _encode(self, sub: ColumnBatch) -> np.ndarray:
        kt = self.store.kt
        if not self.dims:
            if kt.n_keys == 0:
                kt.encode_column(np.array(["__all__"], dtype=np.object_))
            return np.zeros(sub.n, dtype=np.int32)
        if len(self.dims) == 1:
            slots = self._shared_encode(sub)
            if slots is not None:
                return slots
        key_cols = []
        for name in self.dims:
            col = sub.columns.get(name)
            if col is None:
                col = np.full(sub.n, None, dtype=np.object_)
            key_cols.append(col)
        slots, _ = kt.encode_multi(key_cols)
        return slots

    def _shared_encode(self, sub: ColumnBatch) -> Optional[np.ndarray]:
        """Ride the subtopo's one-per-batch key encode (same contract as
        nodes_fused.py _shared_encode: the neutral table's dense
        insertion-ordered ids match what feeding our own table the same
        sequence yields, so our table stays self-contained for emit
        decode and snapshots)."""
        ctx = getattr(sub, "shared_ctx", None)
        if ctx is None or self._shared_slots_ok is False:
            return None
        kt = self.store.kt
        try:
            slots, n_keys, nkt = ctx.encode(sub, self.dims[0])
        except Exception as exc:
            logger.debug("%s: shared key encode failed (%s) — self-encoding",
                         self.name, exc)
            self._shared_slots_ok = False
            return None
        if self._shared_slots_ok is None:
            self._shared_slots_ok = kt.n_keys == 0 or (
                kt.decode_all() == nkt.keys_slice(0, kt.n_keys))
            if not self._shared_slots_ok:
                return None
        self._shared_nkt = nkt
        if kt.n_keys < n_keys:
            new = np.array(nkt.keys_slice(kt.n_keys, n_keys),
                           dtype=np.object_)
            kt.encode_column(new)
        if kt.n_keys < n_keys:
            self._shared_slots_ok = False  # diverged: self-encode from now
            return None
        return slots

    def _device_inputs(self, sub, cols, valid, slots):
        """One device upload per column/slot vector for every consumer of
        this batch — same share keys + canonical builders as
        nodes_fused.py _shared_device_inputs, so a batch pre-uploaded by
        the ingest prep stage is a cache hit here."""
        ctx = getattr(sub, "shared_ctx", None)
        mb = self.store.gb.micro_batch
        if ctx is None or sub.n > mb or \
                not getattr(self.store.gb, "accepts_device_inputs", False):
            return None
        from ..sql.expr_ir import is_derived_expr_col
        from .ingest import (pad_col_for_device, pad_slots_for_device,
                             share_key, slot_wire_u16)

        dcols: Dict[str, Any] = {}
        dvalid: Dict[str, Any] = {}
        expr_tag = getattr(self.plan, "expr_tag", "")
        # mesh-aware uploads: tag-suffixed keys + row-sharded placement
        # for sharded stores (mirror of nodes_fused._shared_device_inputs)
        mesh_tag = getattr(self.store.gb, "mesh_tag", "")
        shd = (getattr(self.store.gb, "batch_sharding", None)
               if mesh_tag else None)

        def _key(*parts):
            return share_key(*parts, mesh_tag=mesh_tag)

        for name in self.plan.columns:
            if name.startswith(HLL_COL_PREFIX) or \
                    name.startswith(HH_COL_PREFIX):
                continue
            if is_derived_expr_col(name):
                host = cols[name]
                dt = str(host.dtype)
                dv, _ = sub.share(_key("dexpr", expr_tag, name, mb),
                                  lambda h=host, d=dt:
                                  pad_col_for_device(h, None, mb,
                                                     dtype=d,
                                                     sharding=shd))
                dcols[name] = dv
                continue
            src_col = sub.columns.get(name)
            if src_col is None or src_col.dtype == np.object_:
                continue
            host, vm = cols[name], valid.get(name)
            dv, dm = sub.share(_key("dcol", name, mb),
                               lambda h=host, v=vm:
                               pad_col_for_device(h, v, mb,
                                                  sharding=shd))
            dcols[name] = dv
            if dm is not None:
                dvalid[name] = dm
        dslots = None
        if self._shared_slots_ok and len(self.dims) == 1:
            from ..ops.groupby import slot_dtype

            cap = (self._shared_nkt.capacity
                   if self._shared_nkt is not None else self.store.kt.capacity)
            u16 = slot_wire_u16(slot_dtype(cap) is np.uint16, mesh_tag)
            dslots = sub.share(
                _key("dslots", self.dims[0], mb, u16),
                lambda s=slots, u=u16: pad_slots_for_device(
                    s, mb, u, sharding=shd))
        if not dcols and dslots is None:
            return None
        return dcols, dvalid, dslots

    # ---------------------------------------------------------------- trigger
    def on_trigger(self, trig: Trigger) -> None:
        """Processing-time pane boundary: emit every member whose window
        ends here, then rotate the ring (reset the pane the NEXT bucket
        will claim — it held bucket now-P, no longer spanned by any
        member window since P > max span)."""
        if self.is_event_time:
            return
        end_ms = trig.ts
        cache: Dict[Any, Any] = {}  # members sharing a pane set combine once
        for m in list(self._members.values()):
            if end_ms % m.spec.interval_ms == 0:
                self._emit_member(m, end_ms, cache=cache)
                m.last_end_ms = end_ms
        nb = end_ms // self.pane_ms
        pane = nb % self.n_panes
        held = self._pane_bucket.get(pane)
        if held is not None and held != nb:
            self.store.reset_pane(pane)
            self._dirty.discard(held)
            self._pane_bucket.pop(pane)
        self._cur_bucket = nb

    def on_watermark(self, wm: Watermark) -> None:
        """Event-time emission: each member's cursor advances through every
        window end at or below the watermark; panes wholly below every
        member's next window are released."""
        if not self.is_event_time:
            self.broadcast(wm)
            return
        members = list(self._members.values())
        cache: Dict[Any, Any] = {}  # no folds land mid-dispatch: one
        for m in members:           # combine per distinct live pane set
            iv = m.spec.interval_ms
            if m.last_end_ms is None:
                if self._floor_bucket is None:
                    continue  # no data yet: nothing to anchor the grid
                first_ts = self._floor_bucket * self.pane_ms
                m.last_end_ms = (first_ts // iv) * iv
            while m.last_end_ms + iv <= wm.ts:
                end = m.last_end_ms + iv
                self._emit_member(m, end, cache=cache)
                m.last_end_ms = end
        # release panes no member's NEXT window can span
        starts = [m.last_end_ms + m.spec.interval_ms - m.spec.length_ms
                  for m in members if m.last_end_ms is not None]
        if starts and len(starts) == len(members):
            floor_b = min(starts) // self.pane_ms
            for b in [b for b in self._dirty if b < floor_b]:
                pane = b % self.n_panes
                if self._pane_bucket.get(pane) == b:
                    self.store.reset_pane(pane)
                    self._pane_bucket.pop(pane)
                self._dirty.discard(b)
            self._floor_bucket = max(self._floor_bucket or 0, floor_b)
        self.broadcast(wm)

    def on_eof(self, eof: EOF) -> None:
        """Flush: each member's current partial window (bounded runs).
        Tumbling members flush the buckets since their last boundary;
        hopping members their trailing span (finer panes may include a
        partial leading bucket — see docs/SHARING.md)."""
        now = timex.now_ms()
        for m in list(self._members.values()):
            if self.is_event_time:
                if not self._dirty:
                    continue
                iv = m.spec.interval_ms
                hi = (max(self._dirty) + 1) * self.pane_ms
                end = -(-hi // iv) * iv  # align up
                last = m.last_end_ms
                if last is None or end > last:
                    self._emit_member(m, end)
                    m.last_end_ms = end
                continue
            b_hi = max((now - 1) // self.pane_ms, self._cur_bucket)
            b_lo = b_hi - m.span + 1
            if m.spec.interval_ms == m.spec.length_ms:  # tumbling
                anchor = (m.last_end_ms // self.pane_ms
                          if m.last_end_ms is not None else m.attach_bucket)
                b_lo = max(b_lo, anchor)
            self._emit_member(m, now, b_lo=b_lo, b_hi=b_hi)
        self.broadcast(eof)

    # ------------------------------------------------------------------- emit
    def _emit_member(self, m: _Member, end_ms: int,
                     b_lo: Optional[int] = None,
                     b_hi: Optional[int] = None,
                     cache: Optional[Dict[Any, Any]] = None) -> None:
        """Combine the panes spanning one member's window ending at
        `end_ms` and run the member's vectorized tail into its emit hop —
        the emit-combine overhead the planner's cost model weighs against
        the saved per-rule folds. `cache` scopes ONE boundary dispatch (no
        folds land in between, state is unchanged): members sharing a live
        pane set reuse one finalize+transfer, and the key table decodes
        once per dispatch instead of once per member."""
        import time as _time

        n_keys = self.store.kt.n_keys
        if b_hi is None:
            b_hi = (end_ms - 1) // self.pane_ms
        if b_lo is None:
            b_lo = b_hi - m.span + 1
        # combine ONLY panes still owned by a dirty bucket of this window:
        # a pane recycled forward (event-time backlog) holds a NEWER
        # bucket's partials — merging it would fold future rows into this
        # window (the recycled bucket's loss was already counted at
        # recycle time)
        live = [b for b in range(b_lo, b_hi + 1)
                if b in self._dirty
                and self._pane_bucket.get(b % self.n_panes) == b]
        if n_keys == 0 or not live:
            return  # empty window: no device round trip, no emission
        t0 = _time.perf_counter()
        panes = sorted({b % self.n_panes for b in live})
        ckey = ("combine", tuple(panes), n_keys)
        if cache is not None and ckey in cache:
            outs, act = cache[ckey]
        else:
            outs, act = self.store.combine(panes, n_keys)
            if cache is not None:
                cache[ckey] = (outs, act)
        if m.spec.act_idx is not None:
            # predicate-lifted member: group existence is this member's
            # own `count(*) FILTER(WHERE <pred>)` column — a key whose
            # rows all failed the member's predicate must not emit a
            # group (byte parity with the private plan's post-WHERE act)
            # kuiperlint: ignore[host-sync]: `outs` are HOST numpy arrays (store.combine already fetched+sliced them) — no device value in reach
            act = np.asarray(outs[m.spec_map[int(m.spec.act_idx)]])
        active = np.nonzero(act > 0)[0]
        n_groups = len(active)
        if n_groups:
            wr = WindowRange(end_ms - m.spec.length_ms, end_ms)
            dim_cols: Dict[str, np.ndarray] = {}
            if self.dims:
                if cache is not None:
                    keys = cache.get("__keys__")
                    if keys is None:
                        keys = cache["__keys__"] = \
                            self.store.kt.decode_all()
                else:
                    keys = self.store.kt.decode_all()
                if len(self.dims) == 1:
                    col = np.empty(n_groups, dtype=np.object_)
                    col[:] = [keys[s] for s in active.tolist()]
                    dim_cols[self.dims[0]] = col
                else:
                    sel = [keys[s] for s in active.tolist()]
                    for i, dn in enumerate(self.dims):
                        col = np.empty(n_groups, dtype=np.object_)
                        col[:] = [k[i] for k in sel]
                        dim_cols[dn] = col
            agg_cols = [outs[u][active] for u in m.spec_map]
            if m.spec.emit_columnar:
                payload = m.spec.direct_emit.run_columnar(
                    dim_cols, agg_cols, wr.window_start, wr.window_end)
                count = payload.n if payload is not None else 0
            else:
                payload = m.spec.direct_emit.run(
                    dim_cols, agg_cols, wr.window_start, wr.window_end)
                count = len(payload) if payload else 0
            if count:
                # ingest→emit provenance (the PR 3 SLO layer): stamp the
                # freshest contributing batch's ingest time, exactly what
                # Node.emit() would do — send_to alone doesn't stamp, and
                # an unstamped window never records an e2e sample at the
                # member's sink
                from .node import _stamp_ingest_ms

                if self._cur_ingest_ms is not None:
                    _stamp_ingest_ms(payload, self._cur_ingest_ms)
                self.stats.inc_out(count)
                self.send_to(m.entry, payload)
            self.windows_emitted += 1
        # per-rule emit-combine latency, attributed under rule="__shared__"
        # (this node renders there) with the member in the stage label
        self.stats.observe_stage(
            f"emit[{m.spec.rule_id}]",
            (_time.perf_counter() - t0) * 1e6, n_groups)

    # ------------------------------------------------------------------ state
    def snapshot_state(self) -> Optional[dict]:
        snap = self.store.snapshot()
        snap.update({
            "cur_bucket": self._cur_bucket,
            "pane_bucket": {str(p): b for p, b in self._pane_bucket.items()},
            "dirty": sorted(self._dirty),
            "floor_bucket": self._floor_bucket,
            "cursors": {rid: m.last_end_ms
                        for rid, m in self._members.items()
                        if m.last_end_ms is not None},
        })
        return snap

    def restore_state(self, state: dict) -> None:
        self.store.restore(state)
        self._cur_bucket = int(state.get("cur_bucket", self._cur_bucket))
        self._pane_bucket = {int(p): int(b) for p, b in
                             state.get("pane_bucket", {}).items()}
        self._dirty = set(state.get("dirty", []))
        self._floor_bucket = state.get("floor_bucket")
        self._restored_cursors = {
            rid: int(v) for rid, v in state.get("cursors", {}).items()}
        # already-attached members pick their cursor up immediately
        for rid, m in self._members.items():
            if rid in self._restored_cursors:
                m.last_end_ms = self._restored_cursors[rid]


class SharedFoldRider:
    """What a member rule's Topo holds while riding a shared fold — the
    same surface Topo expects from a SrcSubTopo (nodes/status/detach), so
    topo.open/close/wait_idle/status and the Prometheus __shared__ dedup
    all work unchanged."""

    def __init__(self, node: SharedFoldNode) -> None:
        self._node = node

    @property
    def nodes(self) -> List[Node]:
        return self._node.pipeline_nodes()

    @property
    def source(self):
        return self._node.source

    def detach(self, rule_id: str) -> None:
        self._node.detach_rule(rule_id)

    def ref_count(self) -> int:
        return self._node.member_count()

    def status(self) -> Dict[str, Any]:
        return self._node.status()


class SharedFoldRef:
    """Plan-time handle: the live store resolves at Topo.open (a pooled
    instance may have closed between planning and opening), mirroring
    subtopo.SubTopoRef."""

    def __init__(self, key: str, member_spec: MemberSpec, builder) -> None:
        self.key = key
        self.member_spec = member_spec
        self.builder = builder

    def resolve_and_attach(self, rule_id: str, entry: Node,
                           topo: Any) -> SharedFoldRider:
        for _ in range(8):
            node = get_or_create(self.key, self.builder)
            try:
                ok = node.attach_rule(self.member_spec, entry, topo)
            except Exception:
                # geometry/spec mismatch (plan/open race): a never-opened
                # memberless store must not linger in the pool — the
                # rule's restart replans against reality (private fold)
                if node.member_count() == 0 and not node._opened:
                    _pool_remove(self.key, node)
                raise
            if ok:
                return SharedFoldRider(node)
        raise RuntimeError(f"cannot attach to shared fold {self.key}")


# ------------------------------------------------------------------- pool
_stores: Dict[str, SharedFoldNode] = {}
_pool_lock = threading.Lock()


def get_or_create(key: str, builder) -> SharedFoldNode:
    with _pool_lock:
        node = _stores.get(key)
    if node is not None:
        return node
    candidate = builder()  # outside the lock: builds device state
    with _pool_lock:
        node = _stores.get(key)
        if node is None:
            _stores[key] = candidate
            return candidate
    return node  # lost the race; unopened candidate is garbage-collected


def get_store(key: str) -> Optional[SharedFoldNode]:
    with _pool_lock:
        return _stores.get(key)


def _pool_remove(key: str, node: SharedFoldNode) -> None:
    with _pool_lock:
        if _stores.get(key) is node:
            del _stores[key]


def live_stores() -> List[SharedFoldNode]:
    with _pool_lock:
        return list(_stores.values())


def pool_size() -> int:
    with _pool_lock:
        return len(_stores)


def reset() -> None:
    """Test hook: close and drop every pooled store."""
    with _pool_lock:
        stores = list(_stores.values())
        _stores.clear()
    for node in stores:
        node._closed = True
        if node._tick_timer is not None:
            node._tick_timer.stop()
        if node._wm_node is not None:
            node._wm_node.close()
        node.close()
