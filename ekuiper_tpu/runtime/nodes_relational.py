"""Device relational nodes: the runtime half of planner/relational.py.

DeviceJoinNode replaces the nested-loop probe of JoinNode with one
banded-gather mask per window (ops/joinring.py). The mask only decides
PAIRING — emitted tuples are the original host rows reassembled in the
reference emission order (per left row: matches in right order;
unmatched-left at the left row's position; unmatched rights appended in
right order) — so device emissions are byte-identical to the nested
loop. A window whose data steps outside the device contract
(JoinWindowFallback) runs the host loop for that window only; the plan
stays lifted and the window is counted, never silent.

DeviceAnalyticNode lifts lag() onto the segscan shift kernel: partition
keys dictionary-encode once (same KeyTable discipline as group-by) and
the per-partition carry lives in donated device arrays. Values are
device float32 — the same numeric contract as every fused kernel. A
non-numeric value migrates the node to the host path permanently,
transplanting the device carry into the evaluator's lag history first,
so no row ever sees a reset state.

VectorWindowFuncNode computes rank/dense_rank/lead collection-wide
(they are whole-collection functions — a per-row exec cannot know the
value order). Ranks come from the segscan sort kernel when the values
round-trip float32 exactly, else from the exact host path; lead's value
assignment is an exact index shift either way.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

from ..data import cast
from ..data.batch import ColumnBatch
from ..data.rows import GroupedTuplesSet, JoinTuple, Row, Tuple, WindowTuples
from ..ops.joinring import JoinWindowFallback, SideBatch
from ..ops.keytable import KeyTable
from ..sql import ast
from ..sql.compiler import record_host_fallback
from .nodes_join import JoinNode
from .nodes_ops import AnalyticNode, WindowFuncNode


def _is_null(v: Any) -> bool:
    return v is None or (isinstance(v, float) and v != v)


def _numeric(v: Any) -> bool:
    return v is None or (isinstance(v, (int, float))
                         and not isinstance(v, bool))


class DeviceJoinNode(JoinNode):
    """JoinNode with the device mask path for the (single) stream-stream
    join step the planner lowered."""

    def __init__(self, name: str, joins: List[ast.Join], left_name: str,
                 lowering, **kw) -> None:
        super().__init__(name, joins, left_name, **kw)
        self.lowering = lowering
        self.ring = lowering.build_ring()

    def _side_rows(self, rows: List[Row], side: str) -> SideBatch:
        lw = self.lowering
        stream = lw.left if side == "l" else lw.right
        names = lw.key_l if side == "l" else lw.key_r
        raws = lw.raw_l if side == "l" else lw.raw_r
        batch = SideBatch(n=len(rows))
        for col in names:
            batch.key_cols.append(
                [r.value(col, stream)[0] for r in rows])
        band_col = lw.band_l if side == "l" else lw.band_r
        if band_col is not None:
            batch.band = [r.value(band_col, stream)[0] for r in rows]
        for raw in raws:
            col = raw[len("__jl_"):]
            batch.cols[raw] = [r.value(col, stream)[0] for r in rows]
        return batch

    def _join_step(self, left: List[JoinTuple], right: List[Tuple],
                   join: ast.Join) -> List[JoinTuple]:
        try:
            mask = self.ring.match(self._side_rows(left, "l"),
                                   self._side_rows(right, "r"))
        except JoinWindowFallback as exc:
            self.ring.fallback_windows_total += 1
            record_host_fallback(exc.reason)
            return super()._join_step(left, right, join)
        jt = join.join_type

        def widen(rt) -> List[Tuple]:
            return list(rt.tuples) if isinstance(rt, JoinTuple) else [rt]

        out: List[JoinTuple] = []
        matched_right = mask.any(axis=0) if len(left) else \
            np.zeros(len(right), dtype=bool)
        for i, lt in enumerate(left):
            hits = np.nonzero(mask[i])[0]
            for j in hits:
                out.append(JoinTuple(
                    tuples=list(lt.tuples) + widen(right[j])))
            if not len(hits) and jt in (ast.JoinType.LEFT,
                                        ast.JoinType.FULL):
                out.append(JoinTuple(tuples=list(lt.tuples)))
        if jt in (ast.JoinType.RIGHT, ast.JoinType.FULL):
            for j, rt in enumerate(right):
                if not matched_right[j]:
                    out.append(JoinTuple(tuples=widen(rt)))
        return out


class DeviceAnalyticNode(AnalyticNode):
    """AnalyticNode with lag() on the segscan shift kernel."""

    def __init__(self, name: str, calls: List[ast.Call], lowering,
                 rule_id: str = "", **kw) -> None:
        super().__init__(name, calls, rule_id=rule_id, **kw)
        self.lowering = lowering
        self._migrated = False
        self._seg = None
        self._keys = KeyTable(initial_capacity=4096)

    def _ensure_seg(self):
        if self._seg is None:
            from ..ops.segscan import SegScan

            self._seg = SegScan(capacity=4096)
        return self._seg

    def process(self, item: Any) -> None:
        if self._migrated:
            super().process(item)
            return
        if isinstance(item, ColumnBatch):
            rows = item.to_tuples()
        elif isinstance(item, Row):
            rows = [item]
        else:
            self.emit(item)
            return
        staged = self._stage_calls(rows)
        if staged is None:
            # non-numeric value: move the carry to host state, then let
            # the host path compute this batch (no state was updated)
            self._migrate_lag_state()
            for r in rows:
                for call in self.calls:
                    r.set_cal_col(f"__analytic_{call.func_id}",
                                  self.ev.eval(call, r))
        else:
            self._apply_device(rows, staged)
        if isinstance(item, ColumnBatch):
            for r in rows:
                self.emit(r)
        else:
            self.emit(item)

    def _stage_calls(self, rows: List[Row]
                     ) -> Optional[List[Dict[str, Any]]]:
        """Validate + encode every call over the whole batch BEFORE any
        state update (migration must see a pristine carry). Returns None
        when any value falls outside the numeric device contract."""
        staged = []
        for plan in self.lowering.calls:
            vals = np.full(len(rows), np.nan, dtype=np.float32)
            pstrs: List[str] = []
            for i, r in enumerate(rows):
                v = r.value(plan.col)[0]
                if not _numeric(v):
                    return None
                if v is not None:
                    vals[i] = v
                pstrs.append("#".join(
                    cast.to_string(self.ev.eval(p, r))
                    for p in plan.partition))
            staged.append({"plan": plan, "vals": vals, "pstrs": pstrs})
        return staged

    def _apply_device(self, rows: List[Row], staged) -> None:
        seg = self._ensure_seg()
        for st in staged:
            plan, vals, pstrs = st["plan"], st["vals"], st["pstrs"]
            keys = np.empty(len(rows), dtype=object)
            for i, p in enumerate(pstrs):
                keys[i] = (plan.call.func_id, p)
            slots, _ = self._keys.encode_column(keys)
            out = seg.shift(slots.astype(np.int32), vals, len(rows))
            for i, r in enumerate(rows):
                if not out["lag_has"][i]:
                    v: Any = plan.default
                elif math.isnan(float(out["lag"][i])):
                    v = None
                else:
                    v = float(out["lag"][i])
                r.set_cal_col(f"__analytic_{plan.call.func_id}", v)

    def _migrate_lag_state(self) -> None:
        """One-way device -> host state transplant: per-partition last
        values leave the carry and become the evaluator's lag history,
        so the host path continues every partition where the device left
        it. Runs off the hot path, at most once per node."""
        self._migrated = True
        record_host_fallback("analytic_runtime_type")
        if self._seg is None:
            return
        carry = self._seg.peek_carry()
        for slot, key in enumerate(self._keys.decode_all()):
            if key is None or not bool(carry["has"][slot]):
                continue
            func_id, pstr = key
            last = float(carry["last"][slot])
            st = self.ev.func_states.setdefault(int(func_id), {})
            st["p:" + pstr] = {
                "hist": [None if math.isnan(last) else last]}
        self._seg = None

    # ------------------------------------------------------------- state
    def snapshot_state(self) -> Optional[dict]:
        base = super().snapshot_state()
        if base is None:
            return None
        base["migrated"] = self._migrated
        if self._seg is not None:
            base["segscan"] = self._seg.snapshot()
            base["part_keys"] = [list(k) if isinstance(k, tuple) else k
                                 for k in self._keys.decode_all()]
        return base

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        self._migrated = bool(state.get("migrated", False))
        snap = state.get("segscan")
        if snap is not None and not self._migrated:
            self._ensure_seg().restore(snap)
            self._keys.restore([tuple(k) if isinstance(k, list) else k
                                for k in state.get("part_keys", [])])
        else:
            self._seg = None


class VectorWindowFuncNode(WindowFuncNode):
    """WindowFuncNode computing rank/dense_rank/lead collection-wide;
    row_number (and any future per-row window func) keeps the exec
    path. `use_device` routes exact-float32 rank batches through the
    segscan sort kernel."""

    VECTOR = ("rank", "dense_rank", "lead")

    def __init__(self, name: str, calls: List[ast.Call],
                 use_device: bool = False, **kw) -> None:
        super().__init__(name, calls, **kw)
        self.use_device = use_device
        self._seg = None

    def process(self, item: Any) -> None:
        rows: List[Row]
        if isinstance(item, GroupedTuplesSet):
            rows = list(item.groups)
        elif isinstance(item, WindowTuples):
            rows = item.rows()
        elif isinstance(item, Row):
            rows = [item]
        elif isinstance(item, ColumnBatch):
            rows = item.to_tuples()
        else:
            self.emit(item)
            return
        self.ev.func_states = {}
        vector = [c for c in self.calls if c.name in self.VECTOR]
        per_row = [c for c in self.calls if c.name not in self.VECTOR]
        if vector and rows:
            self._apply_vector(vector, rows)
        for r in rows:
            for call in per_row:
                r.set_cal_col(f"__analytic_{call.func_id}",
                              self.ev.eval(call, r))
        if isinstance(item, ColumnBatch):
            for r in rows:
                self.emit(r)
        else:
            self.emit(item)

    # ---------------------------------------------------------- vector
    def _apply_vector(self, calls: List[ast.Call],
                      rows: List[Row]) -> None:
        n = len(rows)
        for call in calls:
            vals = [self.ev.eval(call.args[0], r) if call.args else None
                    for r in rows]
            if call.partition:
                pkeys = ["#".join(cast.to_string(self.ev.eval(p, r))
                                  for p in call.partition)
                         for r in rows]
            else:
                pkeys = [""] * n
            seg_of: Dict[str, int] = {}
            seg = np.zeros(n, dtype=np.int32)
            for i, p in enumerate(pkeys):
                seg[i] = seg_of.setdefault(p, len(seg_of))
            if call.name == "lead":
                out = self._lead(call, rows, vals, seg)
            else:
                out = self._ranks(call, vals, seg)
            for i, r in enumerate(rows):
                r.set_cal_col(f"__analytic_{call.func_id}", out[i])

    def _lead(self, call: ast.Call, rows: List[Row], vals: List[Any],
              seg: np.ndarray) -> List[Any]:
        offset = 1
        if len(call.args) > 1:
            offset = int(self.ev.eval(call.args[1], rows[0]))
        members: Dict[int, List[int]] = {}
        for i, s in enumerate(seg):
            members.setdefault(int(s), []).append(i)
        out: List[Any] = [None] * len(rows)
        for idxs in members.values():
            for pos, i in enumerate(idxs):
                if pos + offset < len(idxs) and offset >= 0:
                    out[i] = vals[idxs[pos + offset]]
                elif len(call.args) > 2:
                    out[i] = self.ev.eval(call.args[2], rows[i])
        return out

    def _ranks(self, call: ast.Call, vals: List[Any],
               seg: np.ndarray) -> List[Any]:
        numeric = all(_numeric(v) for v in vals)
        if numeric and self.use_device:
            fv = np.full(len(vals), np.nan, dtype=np.float32)
            exact = True
            for i, v in enumerate(vals):
                if v is None or (isinstance(v, float) and math.isnan(v)):
                    continue
                fv[i] = v
                if float(fv[i]) != float(v):
                    exact = False  # float32 would reorder ties
                    break
            if exact:
                if self._seg is None:
                    from ..ops.segscan import SegScan

                    self._seg = SegScan(capacity=256)
                out = self._seg.ranks(seg, fv, len(vals))
                key = "rank" if call.name == "rank" else "dense_rank"
                has = out["rank_has"]
                return [int(out[key][i]) if has[i] else None
                        for i in range(len(vals))]
        return self._ranks_py(call, vals, seg)

    @staticmethod
    def _ranks_py(call: ast.Call, vals: List[Any],
                  seg: np.ndarray) -> List[Any]:
        """Exact host ranks over arbitrary comparable values: rank =
        1 + count(valid smaller); dense_rank = 1 + count(distinct valid
        smaller). NULL (and float NaN) ranks as NULL."""
        import bisect

        members: Dict[int, List[int]] = {}
        for i, s in enumerate(seg):
            members.setdefault(int(s), []).append(i)
        out: List[Any] = [None] * len(vals)
        for idxs in members.values():
            valid = sorted(vals[i] for i in idxs
                           if not _is_null(vals[i]))
            distinct: List[Any] = []
            for v in valid:
                if not distinct or distinct[-1] != v:
                    distinct.append(v)
            pool = valid if call.name == "rank" else distinct
            for i in idxs:
                if _is_null(vals[i]):
                    continue
                out[i] = 1 + bisect.bisect_left(pool, vals[i])
        return out
