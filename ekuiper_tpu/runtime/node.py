"""Runtime node fabric — analogue of eKuiper's defaultNode goroutine/channel
fabric (internal/topo/node/node.go:113-196) and the UnaryOperator run loop
(internal/topo/node/operations.go:60-130).

Each node is one worker thread with a bounded input queue. Broadcast to
multiple downstream nodes enqueues to each; on a full buffer the oldest item
is dropped unless `disable_buffer_full_discard` — the reference's drop-oldest
backpressure semantics. All thread bodies run under safe_run so a failing
operator drains its error to the topo instead of killing the process.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Callable, List, Optional

from ..utils.infra import logger, safe_run
from ..utils.metrics import StatManager
from .events import EOF, Barrier, ErrorEvent, PreTrigger, Trigger, Watermark


class Node:
    def __init__(
        self,
        name: str,
        op_type: str = "op",
        buffer_length: int = 1024,
        disable_buffer_full_discard: bool = False,
    ) -> None:
        self.name = name
        self.op_type = op_type
        self.inq: "queue.Queue[Any]" = queue.Queue(maxsize=buffer_length)
        self.outputs: List["Node"] = []
        self.stats = StatManager(op_type, name)
        self.disable_buffer_full_discard = disable_buffer_full_discard
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._topo = None  # set by Topo.add

    # ------------------------------------------------------------------ wiring
    def connect(self, downstream: "Node") -> "Node":
        self.outputs.append(downstream)
        return downstream

    # ------------------------------------------------------------------- input
    def put(self, item: Any) -> None:
        """Enqueue with drop-oldest on overflow (node.go:140-196)."""
        if self.disable_buffer_full_discard:
            self.inq.put(item)
            return
        while True:
            try:
                self.inq.put_nowait(item)
                return
            except queue.Full:
                try:
                    dropped = self.inq.get_nowait()
                    self.inq.task_done()  # dropped items count as handled
                    self.stats.inc_exception("buffer full, dropped oldest")
                    logger.debug("%s: buffer full, dropped %r", self.name, type(dropped))
                except queue.Empty:
                    continue

    def broadcast(self, item: Any) -> None:
        for out in self.outputs:
            out.put(item)

    # --------------------------------------------------------------- lifecycle
    def open(self) -> None:
        """Synchronous setup (on_open) on the caller thread, then start the
        worker. Matches the reference where source.Open subscribes before
        Topo.Open returns — data published right after open() is never lost."""
        self._stop.clear()
        err = safe_run(self.on_open)
        if err is not None:
            if self._topo is not None:
                self._topo.drain_error(err, self.name)
            return
        self._thread = threading.Thread(
            target=self._run_safe, name=f"node-{self.name}", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        try:
            self.inq.put_nowait(None)  # wake the worker (it also polls at 0.2s)
        except queue.Full:
            pass

    def join(self, timeout: float = 5.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def _run_safe(self) -> None:
        err = safe_run(self._run)
        if err is not None and self._topo is not None:
            self._topo.drain_error(err, self.name)

    def _run(self) -> None:
        self.on_worker_start()
        try:
            while not self._stop.is_set():
                try:
                    item = self.inq.get(timeout=0.2)
                except queue.Empty:
                    continue
                try:
                    if item is None:
                        continue
                    self.stats.set_buffer_length(self.inq.qsize())
                    self._dispatch(item)
                finally:
                    # unfinished_tasks accounting backs Topo.wait_idle()
                    self.inq.task_done()
        finally:
            self.on_close()

    def _dispatch(self, item: Any) -> None:
        self.stats.inc_in()
        self.stats.process_begin()
        try:
            if isinstance(item, Barrier):
                self.on_barrier(item)
            elif isinstance(item, Watermark):
                self.on_watermark(item)
            elif isinstance(item, EOF):
                self.on_eof(item)
            elif isinstance(item, Trigger):
                self.on_trigger(item)
            elif isinstance(item, PreTrigger):
                self.on_pre_trigger(item)
            else:
                self.process(item)
        except Exception as exc:  # per-item containment: skip poisoned items
            self.stats.inc_exception(str(exc))
            logger.warning("%s error: %s", self.name, exc)
            self.on_error(exc, item)
        finally:
            self.stats.process_end()

    # ------------------------------------------------------------- overridables
    def on_open(self) -> None:
        """Synchronous setup on the opener's thread (subscriptions, timers).
        Must be fast — Topo.open() blocks on it. Slow work (jit warmup)
        belongs in on_worker_start."""

    def on_worker_start(self) -> None:
        """First action on the worker thread, before the dispatch loop —
        e.g. warmup compiles that must not block Topo.open()."""

    def on_close(self) -> None:
        pass

    def process(self, item: Any) -> None:
        """Data item (ColumnBatch / collection / row)."""
        self.emit(item)

    def on_barrier(self, barrier: Barrier) -> None:
        """Default: snapshot own state then forward (at-least-once tracker)."""
        if self._topo is not None:
            self._topo.checkpoint_ack(self.name, barrier, self.snapshot_state())
        self.broadcast(barrier)

    def on_watermark(self, wm: Watermark) -> None:
        self.broadcast(wm)

    def on_eof(self, eof: EOF) -> None:
        self.broadcast(eof)

    def on_trigger(self, trig: Trigger) -> None:
        pass

    def on_pre_trigger(self, pre: PreTrigger) -> None:
        pass

    def on_error(self, exc: Exception, item: Any) -> None:
        """Per-item error: forwarded downstream as data when send_error."""

    # ------------------------------------------------------------------ output
    def emit(self, item: Any, count: int = 1) -> None:
        self.stats.inc_out(count)
        self.broadcast(item)

    # ------------------------------------------------------------------- state
    def snapshot_state(self) -> Optional[dict]:
        return None

    def restore_state(self, state: dict) -> None:
        pass
