"""Runtime node fabric — analogue of eKuiper's defaultNode goroutine/channel
fabric (internal/topo/node/node.go:113-196) and the UnaryOperator run loop
(internal/topo/node/operations.go:60-130).

Each node is one worker thread with a bounded input queue. Broadcast to
multiple downstream nodes enqueues to each; on a full buffer the oldest item
is dropped unless `disable_buffer_full_discard` — the reference's drop-oldest
backpressure semantics. All thread bodies run under safe_run so a failing
operator drains its error to the topo instead of killing the process.
"""
from __future__ import annotations

import queue
import threading
import time as _time
from collections import deque
from typing import Any, Callable, List, Optional

from ..observability.tracer import Tracer, item_stats
from ..utils.infra import logger, safe_run
from ..utils.metrics import StatManager
from ..utils.timex import now_ms as timex_now_ms
from .events import EOF, Barrier, ErrorEvent, PreTrigger, Trigger, Watermark


#: per-thread ingest-provenance override for emissions delivered OFF the
#: dispatch thread (the fused node's async emit worker): the issuing
#: dispatch captures its provenance into the emit queue and the worker
#: installs it here for the delivery — reading the node's live
#: _cur_ingest_ms from the worker would stamp window results with batches
#: folded AFTER the boundary, under-reporting e2e exactly when emission
#: is slow
_emit_ctx = threading.local()

#: distinct "no override installed" marker: None is a VALID override value
#: (issue-time provenance was absent — the delivery must then stamp
#: nothing, not fall back to the live _cur_ingest_ms it was shielding
#: against)
_NO_OVERRIDE = object()


def _item_ingest_ms(item: Any) -> Optional[int]:
    """Ingest timestamp riding an item, if any. Bare lists (multi-row
    project output) can't carry attributes, so their first element speaks
    for the emission — rows of one emission share provenance."""
    ing = getattr(item, "ingest_ms", None)
    if ing is None and type(item) is list and item:
        ing = getattr(item[0], "ingest_ms", None)
    return ing


def _stamp_ingest_ms(item: Any, ing: int) -> None:
    """Attach the ingest timestamp to an outgoing item when it can hold
    one (dataclasses take ad-hoc attributes; list elements are stamped
    individually; bytes/str/dict silently can't — their e2e sample is
    recorded at the last attributable hop)."""
    try:
        if getattr(item, "ingest_ms", None) is None:
            item.ingest_ms = ing
        return
    except (AttributeError, TypeError):
        pass
    if type(item) is list:
        for x in item:
            try:
                if getattr(x, "ingest_ms", None) is None:
                    x.ingest_ms = ing
            except (AttributeError, TypeError):
                return  # homogeneous lists: first failure ends the walk


class _Tagged:
    """Envelope recording which upstream enqueued an item — barrier
    alignment (exactly-once) must distinguish input edges, and the fabric
    uses one queue per node, not one per edge."""

    __slots__ = ("item", "from_name")

    def __init__(self, item: Any, from_name: Optional[str]) -> None:
        self.item = item
        self.from_name = from_name


#: events the QoS shed gate must NEVER discard: dropping a barrier stalls
#: checkpoint alignment, dropping a watermark/trigger stalls windows —
#: shedding is a DATA-plane relief valve only
_CONTROL_EVENTS = (Barrier, Watermark, EOF, Trigger, PreTrigger, ErrorEvent)


def _item_rows(item: Any) -> int:
    """Row count an item represents, for drop accounting (a ColumnBatch
    speaks for all its rows; a bare emission list for its elements)."""
    n = getattr(item, "n", None)
    if isinstance(n, int) and n > 0:
        return n
    if type(item) is list:
        return max(len(item), 1)
    return 1


class Node:
    def __init__(
        self,
        name: str,
        op_type: str = "op",
        buffer_length: int = 1024,
        disable_buffer_full_discard: bool = False,
    ) -> None:
        self.name = name
        self.op_type = op_type
        self.inq: "queue.Queue[Any]" = queue.Queue(maxsize=buffer_length)
        self.outputs: List["Node"] = []
        self.stats = StatManager(op_type, name)
        self.disable_buffer_full_discard = disable_buffer_full_discard
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._topo = None  # set by Topo.add
        self._input_names: set = set()  # distinct upstream node names
        # barrier bookkeeping (reference barrier_handler.go):
        # tracker (qos<=1): checkpoint_id -> barriers seen, snapshot on FIRST
        # aligner (qos==2): checkpoint_id -> {blocked edges, held-back items}
        self._barrier_seen: dict = {}
        self._align: dict = {}
        self._align_done: dict = {}  # recently completed cids (bounded)
        # set by Topo.open for qos==2 rules: data items carry their sender so
        # the aligner can hold back per edge; below that, only barriers are
        # tagged (skips a per-item envelope allocation on the hot path)
        self._tag_data = False
        # queue-wait telemetry: enqueue perf timestamps, FIFO-paired with
        # the input queue (same order; deque ops are GIL-atomic). close()'s
        # wake sentinel bypasses put(), so pairing can skew by one at
        # shutdown — telemetry-grade, guarded by emptiness checks.
        self._enq_times: deque = deque()
        # ingest→emit provenance: the most recent ingest timestamp (ms,
        # engine clock) seen on a dispatched item. emit() stamps it onto
        # outgoing items so sinks can record true end-to-end latency even
        # for window emissions that happen on trigger/worker dispatches.
        self._cur_ingest_ms: Optional[int] = None
        # span attributes for the CURRENT dispatch (set by subclasses,
        # e.g. the sink's e2e latency), attached to the recorded span
        self._span_attrs: Optional[dict] = None
        # QoS shed gate (runtime/control.py): fraction of incoming DATA
        # items discarded before enqueue when this rule is breaching its
        # SLO. Deterministic accumulator pattern (not random) so tests
        # and replay see the same drop positions; every shed row counts
        # in the drop taxonomy under reason="shed_qos". Concurrent put()
        # races on the accumulator are telemetry-grade: the achieved
        # fraction can skew by one item, never lose the accounting.
        self._shed_frac = 0.0
        self._shed_acc = 0.0

    # ------------------------------------------------------------------ wiring
    def connect(self, downstream: "Node") -> "Node":
        self.outputs.append(downstream)
        downstream._input_names.add(self.name)
        return downstream

    # ------------------------------------------------------------------- input
    def set_shed_fraction(self, frac: float) -> None:
        """Install/clear the QoS shed gate (control plane only). 0 = off;
        clearing also resets the accumulator so a later re-shed starts
        from a clean phase."""
        self._shed_frac = max(0.0, min(float(frac), 1.0))
        if self._shed_frac == 0.0:
            self._shed_acc = 0.0

    def put(self, item: Any, from_name: Optional[str] = None) -> None:
        """Enqueue with drop-oldest on overflow (node.go:140-196)."""
        if self._shed_frac > 0.0 and not isinstance(item, _CONTROL_EVENTS):
            self._shed_acc += self._shed_frac
            if self._shed_acc >= 1.0:
                self._shed_acc -= 1.0
                # SLO-driven shedding (runtime/control.py): THIS rule's
                # input is relieved, by design, with a taxonomy reason —
                # never the global drop-oldest path below
                self.stats.inc_dropped("shed_qos", n=_item_rows(item))
                return
        entry = _Tagged(item, from_name) if from_name is not None else item
        # enqueue-clock appended BEFORE the queue insert: the worker may
        # dequeue the instant the item lands, and a missing time would
        # orphan the FIFO pairing for every later item
        self._enq_times.append(_time.perf_counter())
        if self.disable_buffer_full_discard:
            self.inq.put(entry)
            # enqueue-time high-water mark: a backpressure spike that
            # drains before the next Prometheus scrape / evaluator tick
            # must still be visible to the health plane's burn-rate math
            self.stats.note_queue_depth(self.inq.qsize())
            return
        while True:
            try:
                self.inq.put_nowait(entry)
                self.stats.note_queue_depth(self.inq.qsize())
                return
            except queue.Full:
                try:
                    dropped = self.inq.get_nowait()
                    self.inq.task_done()  # dropped items count as handled
                    if self._enq_times:
                        self._enq_times.popleft()  # its wait sample goes too
                    # a backpressure drop is the fabric WORKING AS DESIGNED,
                    # not an operator error: it counts in the drop taxonomy
                    # (kuiper_node_dropped_total{reason="buffer_full"}),
                    # never in exceptions_total
                    self.stats.inc_dropped("buffer_full")
                    logger.debug("%s: buffer full, dropped %r", self.name, type(dropped))
                except queue.Empty:
                    continue

    def put_control(self, item: Any) -> None:
        """Enqueue a control event (window trigger, session timer) —
        BLOCKING, never subject to drop-oldest — while keeping the
        queue-wait clock FIFO-paired with the queue (a bare inq.put would
        desync every later wait sample)."""
        self._enq_times.append(_time.perf_counter())
        self.inq.put(item)
        self.stats.note_queue_depth(self.inq.qsize())

    def send_to(self, out: "Node", item: Any) -> None:
        """Single place encoding the sender-tagging contract: barriers are
        always tagged (alignment identifies edges); data is tagged only when
        the receiver runs exactly-once (_tag_data)."""
        if getattr(out, "_tag_data", False) or isinstance(item, Barrier):
            out.put(item, self.name)
        else:
            out.put(item)

    def broadcast(self, item: Any) -> None:
        for out in self.outputs:
            self.send_to(out, item)

    # --------------------------------------------------------------- lifecycle
    def open(self) -> None:
        """Synchronous setup (on_open) on the caller thread, then start the
        worker. Matches the reference where source.Open subscribes before
        Topo.Open returns — data published right after open() is never lost."""
        self._stop.clear()
        err = safe_run(self.on_open)
        if err is not None:
            if self._topo is not None:
                self._topo.drain_error(err, self.name)
            return
        self._thread = threading.Thread(
            target=self._run_safe, name=f"node-{self.name}", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        try:
            self.inq.put_nowait(None)  # wake the worker (it also polls at 0.2s)
        except queue.Full:
            pass

    def join(self, timeout: float = 5.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def _run_safe(self) -> None:
        err = safe_run(self._run)
        if err is not None and self._topo is not None:
            self._topo.drain_error(err, self.name)

    def _run(self) -> None:
        from ..utils.rulelog import set_rule_context

        set_rule_context(getattr(self._topo, "rule_id", None))
        self.on_worker_start()
        try:
            while not self._stop.is_set():
                try:
                    entry = self.inq.get(timeout=0.2)
                except queue.Empty:
                    continue
                if self._enq_times:
                    try:
                        self.stats.observe_queue_wait(
                            (_time.perf_counter()
                             - self._enq_times.popleft()) * 1e6)
                    except IndexError:
                        pass  # raced another consumer draining at close
                try:
                    if entry is None:
                        continue
                    if isinstance(entry, _Tagged):
                        item, from_name = entry.item, entry.from_name
                    else:
                        item, from_name = entry, None
                    self.stats.set_buffer_length(self.inq.qsize())
                    self._dispatch(item, from_name)
                finally:
                    # unfinished_tasks accounting backs Topo.wait_idle()
                    self.inq.task_done()
        finally:
            self.on_close()

    def _dispatch(self, item: Any, from_name: Optional[str] = None) -> None:
        if self._align and from_name is not None:
            # exactly-once alignment in progress: items — INCLUDING later
            # checkpoints' barriers — from an edge whose barrier already
            # arrived are held back until all edges align
            # (barrier_handler.go BarrierAligner), preserving per-edge order
            for cid, st in list(self._align.items()):
                if from_name in st["blocked"]:
                    st["buffer"].append((item, from_name))
                    if len(st["buffer"]) > self.ALIGN_BUFFER_CAP:
                        # a peer edge's barrier was lost (drop-oldest
                        # backpressure or a dead upstream): force-complete —
                        # degrade this checkpoint to at-least-once instead of
                        # stalling the edge and growing the buffer forever
                        logger.warning(
                            "%s: alignment %s overflowed, degrading to "
                            "at-least-once", self.name, cid)
                        del self._align[cid]
                        self._mark_align_done(cid)
                        self.on_barrier(Barrier(checkpoint_id=cid, qos=1))
                        for it, fn in st["buffer"]:
                            self._dispatch(it, fn)
                    return
        if isinstance(item, Barrier):
            self._handle_barrier(item, from_name)
            return
        # tracing fast path: one attribute check when disabled
        tracer = Tracer._instance
        traced = (
            tracer is not None and tracer.any_enabled
            and self._topo is not None
            and tracer.is_enabled(getattr(self._topo, "rule_id", ""))
        )
        if traced:
            tid = tracer.lookup(item)
            if tid is not None:
                tracer.set_current(tid)
            elif self.op_type == "source" or tracer.current_trace() is None:
                tracer.new_trace()
            t0 = _time.perf_counter()
        self._tracing_now = traced
        ing = _item_ingest_ms(item)
        if ing is not None:
            # keep the LAST seen provenance (not reset on control events):
            # window emissions fire on trigger dispatches, where the freshest
            # contributing batch's ingest time is exactly the right stamp
            self._cur_ingest_ms = ing
        self.stats.inc_in()
        self.stats.process_begin()
        try:
            if isinstance(item, Watermark):
                self.on_watermark(item)
            elif isinstance(item, EOF):
                self.on_eof(item)
            elif isinstance(item, Trigger):
                self.on_trigger(item)
            elif isinstance(item, PreTrigger):
                self.on_pre_trigger(item)
            else:
                self.process(item)
        except Exception as exc:  # per-item containment: skip poisoned items
            self.stats.inc_exception(str(exc))
            logger.warning("%s error: %s", self.name, exc)
            self.on_error(exc, item)
        finally:
            self.stats.process_end()
            if traced:
                kind, rows = item_stats(item)
                attrs, self._span_attrs = self._span_attrs, None
                tracer.record(
                    self._topo.rule_id, self.name, timex_now_ms(),
                    int((_time.perf_counter() - t0) * 1e6), kind, rows,
                    attrs=attrs)
                self._tracing_now = False

    # ------------------------------------------------------------- overridables
    def on_open(self) -> None:
        """Synchronous setup on the opener's thread (subscriptions, timers).
        Must be fast — Topo.open() blocks on it. Slow work (jit warmup)
        belongs in on_worker_start."""

    def on_worker_start(self) -> None:
        """First action on the worker thread, before the dispatch loop —
        e.g. warmup compiles that must not block Topo.open()."""

    def on_close(self) -> None:
        pass

    def process(self, item: Any) -> None:
        """Data item (ColumnBatch / collection / row)."""
        self.emit(item)

    def _handle_barrier(self, barrier: Barrier, from_name: Optional[str]) -> None:
        """Fan-in-correct barrier handling (barrier_handler.go:23-88).

        qos<=1 (at-least-once) BarrierTracker: snapshot + forward on the
        FIRST arrival of a checkpoint id, swallow the rest — no duplicate
        barriers downstream, no multi-snapshot.

        qos==2 (exactly-once) BarrierAligner: after the first arrival, hold
        back items from edges whose barrier already came, snapshot only when
        every input edge's barrier arrived (a consistent cut), then replay
        the held-back items.
        """
        cid = barrier.checkpoint_id
        n = max(len(self._input_names), 1)
        if barrier.qos >= 2 and n > 1:
            if cid in self._align_done:
                # a peer's late barrier for a checkpoint that already
                # completed (alignment overflow degraded it) — swallow it,
                # re-opening alignment would stall that edge forever
                return
            st = self._align.get(cid)
            if st is None:
                st = {"blocked": set(), "buffer": []}
                self._align[cid] = st
            st["blocked"].add(from_name)
            if len(st["blocked"]) >= n:
                del self._align[cid]
                self._mark_align_done(cid)
                self.on_barrier(barrier)
                for item, fn in st["buffer"]:
                    self._dispatch(item, fn)
            return
        seen = self._barrier_seen.get(cid, 0)
        if seen == 0:
            self.on_barrier(barrier)
        if seen + 1 >= n:
            self._barrier_seen.pop(cid, None)
        else:
            self._barrier_seen[cid] = seen + 1
            if len(self._barrier_seen) > 64:
                # stale ids (a peer edge lost its barrier to backpressure):
                # drop the oldest bookkeeping, the checkpoint already fired
                oldest = min(self._barrier_seen)
                del self._barrier_seen[oldest]

    #: held-back items per in-flight alignment before it force-completes
    ALIGN_BUFFER_CAP = 10_000

    def _mark_align_done(self, cid: int) -> None:
        self._align_done[cid] = True
        while len(self._align_done) > 16:
            del self._align_done[next(iter(self._align_done))]

    def on_barrier(self, barrier: Barrier) -> None:
        """Snapshot own state, ack the coordinator, forward downstream.
        Called exactly once per checkpoint id (see _handle_barrier).

        A snapshot failure (e.g. the fused node's bounded async-emit drain
        timing out on a wedged device fetch) must fail THIS CHECKPOINT, not
        the rule: skip the ack — the checkpoint never completes and a later
        one retries — but still forward the barrier so downstream aligners
        never stall, and keep the worker thread alive."""
        if self._topo is not None:
            try:
                state = self.snapshot_state()
            except Exception as exc:
                logger.error(
                    "%s: snapshot for checkpoint %d failed (%s) — skipping "
                    "ack; this checkpoint will not commit, a later one "
                    "retries", self.name, barrier.checkpoint_id, exc)
                # surface in /rules metrics: a PERSISTENTLY failing snapshot
                # silently pins recovery to an old checkpoint otherwise
                self.stats.inc_exception(f"snapshot failed: {exc}")
            else:
                self._topo.checkpoint_ack(self.name, barrier, state)
        self.broadcast(barrier)

    def on_watermark(self, wm: Watermark) -> None:
        self.broadcast(wm)

    def on_eof(self, eof: EOF) -> None:
        self.broadcast(eof)

    def on_trigger(self, trig: Trigger) -> None:
        pass

    def on_pre_trigger(self, pre: PreTrigger) -> None:
        pass

    def on_error(self, exc: Exception, item: Any) -> None:
        """Per-item error: forwarded downstream as data when send_error."""

    def extra_pending(self) -> int:
        """Work in flight OUTSIDE the input queue (e.g. the source's decode
        ring) — Topo.wait_idle counts it so 'idle' still means no data
        anywhere in the DAG."""
        return 0

    # ------------------------------------------------------------------ output
    def emit(self, item: Any, count: int = 1) -> None:
        if getattr(self, "_tracing_now", False):
            Tracer.global_instance().tag(item)  # trace follows the item
        ing = getattr(_emit_ctx, "ingest_ms", _NO_OVERRIDE)
        if ing is _NO_OVERRIDE:
            ing = self._cur_ingest_ms
        if ing is not None:
            _stamp_ingest_ms(item, ing)  # provenance follows the item too
        self.stats.inc_out(count)
        self.broadcast(item)

    # ------------------------------------------------------------------- state
    def snapshot_state(self) -> Optional[dict]:
        return None

    def restore_state(self, state: dict) -> None:
        pass
