"""Sink node — analogue of eKuiper's sink chain (planner_sink.go:36-253:
transform → batch → encode → cache → sink node) with SinkNode retry
(sink_node.go:197-255) folded in.

Transforms supported: field picking, dataTemplate (a pragmatic subset of Go
templates: {{.field}} substitution), sendSingle splitting, omitIfEmpty.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from ..data.batch import ColumnBatch
from ..data.rows import GroupedTuplesSet, Row, Tuple, WindowTuples
from ..utils import timex
from ..utils.infra import logger
from .node import Node, _item_ingest_ms

_TMPL_RE = re.compile(r"\{\{\s*\.(\w+)\s*\}\}")


def apply_transform(msg: Dict[str, Any], fields=None, exclude_fields=None,
                    data_template: str = "") -> Any:
    """Field projection + dataTemplate rendering (transform_op.go)."""
    if fields:
        msg = {k: msg.get(k) for k in fields}
    if exclude_fields:
        msg = {k: v for k, v in msg.items() if k not in exclude_fields}
    if data_template:
        return _TMPL_RE.sub(lambda m: str(msg.get(m.group(1), "")), data_template)
    return msg


def to_messages(item: Any) -> List[Dict[str, Any]]:
    """Normalize any runtime data item to a list of plain message dicts
    (shared by SinkNode and the sink-chain EncodeNode)."""
    if isinstance(item, list):
        out: List[Dict[str, Any]] = []
        for x in item:
            out.extend(to_messages(x))
        return out
    if isinstance(item, Tuple):
        return [item.all_values()]
    if isinstance(item, GroupedTuplesSet):
        return [g.all_values() for g in item.groups]
    if isinstance(item, (WindowTuples,)):
        return [r.all_values() for r in item.rows()]
    if isinstance(item, ColumnBatch):
        return [t.message for t in item.to_tuples()]
    if isinstance(item, dict):
        return [item]
    if isinstance(item, Row):
        return [item.all_values()]
    return []


class SinkNode(Node):
    def __init__(
        self,
        name: str,
        sink,  # io.Sink
        send_single: bool = False,
        fields: Optional[List[str]] = None,
        exclude_fields: Optional[List[str]] = None,
        data_template: str = "",
        omit_if_empty: bool = False,
        retry_count: int = 0,
        retry_interval_ms: int = 1000,
        cache_node=None,  # upstream CacheNode for at-least-once nack feedback
        **kw,
    ) -> None:
        super().__init__(name, op_type="sink", **kw)
        self.cache_node = cache_node
        self.sink = sink
        self.send_single = send_single
        self.fields = fields
        self.exclude_fields = exclude_fields
        self.data_template = data_template
        self.omit_if_empty = omit_if_empty
        self.retry_count = retry_count
        self.retry_interval_ms = retry_interval_ms
        self._current: Any = None  # item being processed (cache ack/nack key)
        self.results: List[Any] = []  # test/trial access

    def on_open(self) -> None:
        self.sink.connect()

    def on_close(self) -> None:
        try:
            self.sink.close()
        except Exception as exc:
            logger.debug("sink %s close error: %s", self.name, exc)

    # ------------------------------------------------------------------ data
    def process(self, item: Any) -> None:
        self._observe_e2e(item)
        # ack/nack to the cache always reference the PRE-transform item the
        # cache emitted, so its in-flight tracking matches on resends
        self._current = item
        if (isinstance(item, ColumnBatch) and item.n
                and getattr(self.sink, "accepts_batches", False)
                and not (self.send_single or self.fields
                         or self.exclude_fields or self.data_template)):
            # columnar fast path: a batch-capable sink takes the window
            # emission as-is — no per-row dict materialization (at 250+
            # rules x thousands of keys per boundary that conversion is
            # seconds of host time)
            self._collect(item)
            return
        if isinstance(item, (bytes, bytearray, str)):
            # opaque payloads: post-encode/compress bytes, rendered template
            # strings — pass through untransformed
            # (reference: bytes-collector sink variant, sink_node.go:197)
            self._collect(bytes(item) if isinstance(item, (bytes, bytearray))
                          else item)
            return
        msgs = self._to_messages(item)
        if not msgs and self.omit_if_empty:
            return
        msgs = [self._transform(m) for m in msgs]
        if self.send_single:
            # the cache tracks the PRE-split item: ack only after every
            # message lands, and stop on the first nack so the whole item is
            # parked exactly once (resend replays it from the start)
            for m in msgs:
                if not self._collect(m, ack=False):
                    return
            if self.cache_node is not None:
                self.cache_node.ack(self._current)
        else:
            self._collect(msgs if len(msgs) != 1 else msgs[0])

    def _observe_e2e(self, item: Any) -> None:
        """Record the ingest→emit latency sample for items carrying their
        source ingest stamp (runtime/node.py provenance propagation) into
        the rule's end-to-end histogram — the paper's SLO (p99 emit < 50ms)
        measured where the result actually leaves the engine."""
        ing = _item_ingest_ms(item)
        if ing is None:
            return
        lat_ms = max(timex.now_ms() - ing, 0)
        topo = self._topo
        observe = getattr(topo, "observe_e2e", None)
        if observe is not None:
            observe(lat_ms)
        if getattr(self, "_tracing_now", False):
            self._span_attrs = {"e2e_ms": lat_ms}

    def _to_messages(self, item: Any) -> List[Dict[str, Any]]:
        return to_messages(item)

    def _transform(self, msg: Dict[str, Any]) -> Any:
        return apply_transform(msg, self.fields, self.exclude_fields,
                               self.data_template)

    def _collect(self, payload: Any, ack: bool = True) -> bool:
        attempts = 0
        delay = self.retry_interval_ms
        while True:
            try:
                self.sink.collect(payload)
                if ack and self.cache_node is not None:
                    self.cache_node.ack(self._current)  # drop spilled copy
                self.results.append(payload)
                if len(self.results) > 10000:
                    del self.results[:5000]
                return True
            except Exception as exc:
                attempts += 1
                self.stats.inc_exception(str(exc))
                if attempts > self.retry_count:
                    if self.cache_node is not None:
                        # at-least-once: park the item in the sink cache; its
                        # resend loop re-delivers when the sink recovers
                        self.cache_node.nack(self._current)
                        return False
                    raise
                timex.sleep(delay)
                delay = min(delay * 2, 30_000)
