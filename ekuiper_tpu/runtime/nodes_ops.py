"""Relational operator nodes — analogues of internal/topo/operator/*:
FilterOp, AnalyticFuncsOp, AggregateOp, HavingOp, OrderOp, ProjectOp,
ProjectSetOp, plus join. Host path: these run on row collections after
windowing; the fused device path (nodes_fused.py) replaces
window+aggregate+having-on-aggs with one kernel.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

import numpy as np

from ..data import cast
from ..data.batch import ColumnBatch, from_tuples
from ..data.rows import (
    GroupedTuples, GroupedTuplesSet, JoinTuple, Row, Tuple, WindowTuples,
)
from ..functions import registry
from ..sql import ast
from ..sql.compiler import CompiledExpr, try_compile
from ..sql.eval import EvalError, Evaluator
from .node import Node


class FilterNode(Node):
    """WHERE — vectorized over ColumnBatch when compilable, row fallback
    otherwise (reference: internal/topo/operator/filter_operator.go)."""

    def __init__(self, name: str, condition: ast.Expr, **kw) -> None:
        super().__init__(name, op_type="op", **kw)
        self.condition = condition
        self.compiled = try_compile(condition, mode="host")
        self.ev = Evaluator()

    def process(self, item: Any) -> None:
        # stage accounting: WHERE evaluation (vectorized or per-row) is
        # "host_expr" — the health plane's bottleneck attribution names
        # host expression eval instead of binning it as "other"
        import time as _time

        t0 = _time.perf_counter()
        if isinstance(item, ColumnBatch):
            out = self._filter_batch(item)
            self.stats.observe_stage(
                "host_expr", (_time.perf_counter() - t0) * 1e6, item.n)
            if out is not None and out.n > 0:
                self.emit(out, count=out.n)
            return
        if isinstance(item, WindowTuples):
            kept = [r for r in item.rows() if self.ev.eval_condition(self.condition, r)]
            self.stats.observe_stage(
                "host_expr", (_time.perf_counter() - t0) * 1e6,
                len(item.rows()))
            if kept:
                self.emit(WindowTuples(content=kept, window_range=item.window_range))
            return
        if isinstance(item, Row):
            keep = self.ev.eval_condition(self.condition, item)
            self.stats.observe_stage(
                "host_expr", (_time.perf_counter() - t0) * 1e6, 1)
            if keep:
                self.emit(item)
            return
        self.emit(item)

    def _filter_batch(self, batch: ColumnBatch) -> Optional[ColumnBatch]:
        if self.compiled is not None and all(
            c in batch.columns for c in self.compiled.columns
        ):
            try:
                mask = np.asarray(self.compiled(batch.columns), dtype=bool)
                for c in self.compiled.columns:
                    mask &= batch.is_valid(c)
                return batch.select(mask)
            except Exception:
                pass  # fall back to rows
        rows = batch.to_tuples()
        kept = [r for r in rows if self.ev.eval_condition(self.condition, r)]
        if not kept:
            return None
        return from_tuples(kept, emitter=batch.emitter)


class AnalyticNode(Node):
    """Pre-computes analytic function values per row before filtering
    (reference: analyticfuncs_operator.go). Results cache on the row as
    __analytic_{func_id} cal-cols which the evaluator reads back."""

    def __init__(self, name: str, calls: List[ast.Call], rule_id: str = "", **kw) -> None:
        super().__init__(name, op_type="op", **kw)
        self.calls = calls
        self.ev = Evaluator(rule_id=rule_id)

    def process(self, item: Any) -> None:
        if isinstance(item, ColumnBatch):
            rows = item.to_tuples()
        elif isinstance(item, Row):
            rows = [item]
        else:
            self.emit(item)
            return
        for r in rows:
            for call in self.calls:
                val = self.ev.eval(call, r)
                r.set_cal_col(f"__analytic_{call.func_id}", val)
        if isinstance(item, ColumnBatch):
            for r in rows:
                self.emit(r)
        else:
            self.emit(item)

    def snapshot_state(self) -> Optional[dict]:
        # analytic state is json-serializable (lists/scalars)
        try:
            import json

            # round-trip: the snapshot must be a frozen copy — handing out
            # the live dict lets post-barrier rows mutate the checkpoint
            return {"func_states": json.loads(json.dumps(self.ev.func_states))}
        except (TypeError, ValueError):
            return None

    def restore_state(self, state: dict) -> None:
        fs = state.get("func_states", {})
        self.ev.func_states = {int(k): v for k, v in fs.items()}


class AggregateNode(Node):
    """GROUP BY on window output: evaluates dimension exprs per row, builds
    GroupedTuplesSet (reference: aggregate_operator.go:34-74)."""

    def __init__(self, name: str, dimensions: List[ast.Expr], **kw) -> None:
        super().__init__(name, op_type="op", **kw)
        self.dimensions = dimensions
        self.ev = Evaluator()

    def process(self, item: Any) -> None:
        if isinstance(item, ColumnBatch):
            rows: List[Row] = item.to_tuples()
            wr = None
        elif isinstance(item, WindowTuples):
            rows = item.rows()
            wr = item.window_range
        elif isinstance(item, Row):
            rows = [item]
            wr = None
        else:
            self.emit(item)
            return
        groups: Dict[str, GroupedTuples] = {}
        order: List[str] = []
        for r in rows:
            key_parts = []
            for d in self.dimensions:
                v = self.ev.eval(d, r)
                key_parts.append(cast.to_string(v) if v is not None else "")
            key = "#".join(key_parts)
            g = groups.get(key)
            if g is None:
                g = GroupedTuples(content=[], group_key=key, window_range=wr)
                groups[key] = g
                order.append(key)
            g.content.append(r)
        self.emit(GroupedTuplesSet(groups=[groups[k] for k in order], window_range=wr))


class HavingNode(Node):
    """Post-agg filter (reference: having_operator.go)."""

    def __init__(self, name: str, condition: ast.Expr, rule_id: str = "", **kw) -> None:
        super().__init__(name, op_type="op", **kw)
        self.condition = condition
        self.ev = Evaluator(rule_id=rule_id)

    def process(self, item: Any) -> None:
        if isinstance(item, GroupedTuplesSet):
            self.ev.window_range = item.window_range
            kept = [
                g for g in item.groups
                if self.ev.eval_condition(self.condition, g)
            ]
            if kept:
                self.emit(GroupedTuplesSet(groups=kept, window_range=item.window_range))
            return
        if isinstance(item, WindowTuples):
            # non-grouped agg condition applies to the whole window
            self.ev.window_range = item.window_range
            if self.ev.eval_condition(self.condition, item):
                self.emit(item)
            return
        if isinstance(item, Row):
            if self.ev.eval_condition(self.condition, item):
                self.emit(item)
            return
        self.emit(item)


class OrderNode(Node):
    """ORDER BY (reference: order_operator.go + internal/xsql/sorter.go)."""

    def __init__(self, name: str, sorts: List[ast.SortField], **kw) -> None:
        super().__init__(name, op_type="op", **kw)
        self.sorts = sorts
        self.ev = Evaluator()

    def process(self, item: Any) -> None:
        if isinstance(item, GroupedTuplesSet):
            item.groups = self._sort(item.groups)
        elif isinstance(item, WindowTuples):
            item.content = self._sort(item.content)
        elif isinstance(item, ColumnBatch):
            rows = self._sort(item.to_tuples())
            item = from_tuples(rows, emitter=item.emitter)
        self.emit(item)

    def _sort(self, rows: List[Any]) -> List[Any]:
        def cmp(a, b) -> int:
            for sf in self.sorts:
                expr = sf.expr if sf.expr is not None else ast.FieldRef(sf.name, sf.stream)
                va = self.ev.eval(expr, a)
                vb = self.ev.eval(expr, b)
                c = cast.compare(va, vb)
                if c is None:
                    c = 0
                if c != 0:
                    return c if sf.ascending else -c
            return 0

        return sorted(rows, key=functools.cmp_to_key(cmp))


class ProjectNode(Node):
    """SELECT projection (reference: project_operator.go:54-136). Emits
    result Tuples with the output message per row/group."""

    def __init__(
        self, name: str, fields: List[ast.Field], rule_id: str = "",
        limit: Optional[int] = None, send_nil: bool = False,
        is_agg: bool = False, **kw,
    ) -> None:
        super().__init__(name, op_type="op", **kw)
        self.fields = fields
        self.limit = limit
        self.is_agg = is_agg
        self.ev = Evaluator(rule_id=rule_id)

    def process(self, item: Any) -> None:
        rows: List[Row]
        wr = None
        if isinstance(item, GroupedTuplesSet):
            rows = list(item.groups)
            wr = item.window_range
        elif isinstance(item, WindowTuples):
            # aggregate query without GROUP BY: whole window = one group
            rows = [item] if self.is_agg else item.rows()
            wr = item.window_range
        elif isinstance(item, ColumnBatch):
            rows = item.to_tuples()
        elif isinstance(item, Row):
            rows = [item]
        else:
            self.emit(item)
            return
        self.ev.window_range = wr
        if self.limit is not None:
            rows = rows[: self.limit]
        out: List[Tuple] = []
        for r in rows:
            msg: Dict[str, Any] = {}
            for idx, f in enumerate(self.fields):
                if f.invisible:
                    continue
                if isinstance(f.expr, ast.Wildcard):
                    val = self.ev.eval(f.expr, r)
                    if isinstance(val, dict):
                        msg.update(val)
                    continue
                val = self.ev.eval(f.expr, r)
                msg[f.output_name or f"kuiper_field_{idx}"] = val
            ts = getattr(r, "timestamp", 0)
            meta = getattr(r, "metadata", None)
            out.append(Tuple(emitter="", message=msg, timestamp=ts,
                             metadata=dict(meta) if meta else {}))
        if out:
            self.emit(out if len(out) > 1 else out[0], count=len(out))


class ProjectSetNode(Node):
    """SRF expansion post-projection (reference: projectset_operator.go).
    The projected message holds the SRF result list under `srf_name`; each
    element becomes one output row — dict elements merge into the row,
    scalar elements replace the column."""

    def __init__(self, name: str, srf_name: str, **kw) -> None:
        super().__init__(name, op_type="op", **kw)
        self.srf_name = srf_name

    def process(self, item: Any) -> None:
        rows: List[Tuple]
        if isinstance(item, list):
            rows = [r for r in item if isinstance(r, Tuple)]
        elif isinstance(item, Tuple):
            rows = [item]
        else:
            self.emit(item)
            return
        for r in rows:
            expanded = r.message.get(self.srf_name)
            if not isinstance(expanded, list):
                self.emit(r)
                continue
            for v in expanded:
                new_msg = dict(r.message)
                if isinstance(v, dict):
                    del new_msg[self.srf_name]
                    new_msg.update(v)
                else:
                    new_msg[self.srf_name] = v
                self.emit(Tuple(emitter=r.emitter, message=new_msg,
                                timestamp=r.timestamp))


class WindowFuncNode(Node):
    """SQL window functions (row_number) applied post-agg
    (reference: windowfunc_operator.go)."""

    def __init__(self, name: str, calls: List[ast.Call], **kw) -> None:
        super().__init__(name, op_type="op", **kw)
        self.calls = calls
        self.ev = Evaluator()

    def process(self, item: Any) -> None:
        rows: List[Row]
        if isinstance(item, GroupedTuplesSet):
            rows = list(item.groups)
        elif isinstance(item, WindowTuples):
            rows = item.rows()
        elif isinstance(item, Row):
            rows = [item]
        elif isinstance(item, ColumnBatch):
            rows = item.to_tuples()
        else:
            self.emit(item)
            return
        # row_number restarts per collection
        self.ev.func_states = {}
        for r in rows:
            for call in self.calls:
                val = self.ev.eval(call, r)
                r.set_cal_col(f"__analytic_{call.func_id}", val)
        if isinstance(item, ColumnBatch):
            # emit the mutated rows, not the unmodified batch
            for r in rows:
                self.emit(r)
        else:
            self.emit(item)
