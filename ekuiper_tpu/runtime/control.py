"""Adaptive QoS control plane — the first ACTUATOR on the engine's
telemetry.

The observability stack built across the last PRs measures everything:
per-rule SLO burn, bottleneck stage, watermark lag, HBM trend, XLA
compile storms, drop taxonomy. The health plane
(observability/health.py) turns those into per-rule VERDICTS — and until
now nothing acted on them: a rule-churn storm or a hot-key skew shift
degraded every rule equally through global drop-oldest backpressure.
This module closes the loop (ROADMAP item 5) with three bounded,
logged actuators:

- **Admission control** — `admit_rule()` prices a candidate rule BEFORE
  it starts: the sharing cost model's fold/emit coefficients
  (planner/sharing.py, the Factor-Windows currency) give its steady-state
  device cost, memwatch + the health plane's HBM trend bound its memory
  claim, and devwatch's compile-storm counters flag a bad moment to add
  compile load. The decision is structured — accept | reject(reason,
  price) | queue(reason, price) — never a bare exception: a rejected
  rule's caller gets the price that condemned it, a queued rule is
  retried every control tick and started when pressure clears.

- **SLO-driven load shedding** — when the health FSM holds a rule at
  `breaching`, the controller sheds THAT RULE's input at its topo entry
  nodes (runtime/topo.py entry_nodes — downstream of shared work,
  upstream of the rule's private pipeline) through the existing drop
  taxonomy (`StatManager.inc_dropped(reason="shed_qos")`). The shed
  fraction climbs a per-qos-class ladder with hysteresis mirroring the
  health FSM (`up_ticks` breaching ticks per escalation, `down_ticks`
  healthy ticks per step down); `qosClass: critical` rules are never
  shed. Every transition is a flight-recorder event.

- **Auto-sizing** — when the attributed bottleneck is `decode` or
  `upload` on a rule that is not healthy, the controller resizes the
  source's decode pool (more parse workers) or ingest ring (deeper
  decode→fold overlap), bounded by `KUIPER_AUTOSIZE_MAX_POOL/RING`,
  cooled down between actions, stepped back toward the configured size
  after sustained health, and logged + flight-recorded per action.
  Inline sources (decode_pool_size=0) are never converted — that path
  is bit-for-bit deterministic by contract.

Configuration (all read at decision time, so tests/bench set per-case):

  KUIPER_CONTROL_INTERVAL_MS            controller cadence (default 5s)
  KUIPER_ADMISSION=0                    disable admission (accept all)
  KUIPER_HBM_BUDGET_MB                  reject when current+projected HBM
                                        exceeds it (0 = off)
  KUIPER_ADMISSION_FOLD_BUDGET_US_PER_S reject when the committed fold
                                        ledger + price exceeds it (0=off)
  KUIPER_ADMISSION_SIG_BUDGET           reject when the candidate's
                                        jitcert-certified signature count
                                        exceeds it (0 = off)
  KUIPER_ADMISSION_DEFER_BREACHING      queue new rules while >= N rules
                                        are breaching (0 = off)
  KUIPER_ADMISSION_DEFER_STORMS=0       stop queueing on compile storms
  KUIPER_AUTOSIZE_MAX_POOL / _MAX_RING  autosize upper bounds (default 6)

Prometheus families (docs/OBSERVABILITY.md + docs/RESILIENCE.md):
kuiper_admission_total{decision}, kuiper_shed_total{rule,qos},
kuiper_autosize_events_total.
"""
from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..utils import timex
from ..utils.infra import EngineError, logger

# ------------------------------------------------------------- QoS classes
#: per-class shed ladders: level 1..n -> fraction of the rule's input
#: discarded at its entry nodes. `critical` is exempt — it rides global
#: backpressure only. The class is a RULE option (`qosClass`), distinct
#: from the checkpoint `qos` level.
SHED_LADDERS: Dict[str, tuple] = {
    "low": (0.25, 0.50, 0.75, 0.90),
    "standard": (0.10, 0.25, 0.50, 0.75),
    "high": (0.05, 0.10, 0.25, 0.50),
    "critical": (),
}

DEFAULT_QOS_CLASS = "standard"


def parse_qos_class(options: Optional[Dict[str, Any]]) -> str:
    """Rule QoS class off its options (`qosClass`/`qos_class`); unknown
    values fall back to `standard` (a typo must not exempt a rule from
    shedding — nor subject it to the `low` ladder)."""
    raw = (options or {}).get("qosClass",
                              (options or {}).get("qos_class"))
    cls = str(raw).strip().lower() if raw is not None else DEFAULT_QOS_CLASS
    return cls if cls in SHED_LADDERS else DEFAULT_QOS_CLASS


# --------------------------------------------------------------- admission
#: admission pricing coefficients beyond the sharing model's: rough
#: steady-state cost of a host-path rule per batch (row loop + project +
#: sink), and the HBM projection's pane multiplier (panes + emit staging)
HOST_BATCH_US = 50.0
HBM_PANE_FACTOR = 4

DEFAULT_INTERVAL_MS = int(os.environ.get("KUIPER_CONTROL_INTERVAL_MS",
                                         "5000") or 5000)
#: admission queue bound — past it, queueing degrades to reject (a queue
#: that grows without bound during a storm is its own meltdown)
ADMISSION_QUEUE_CAP = 64


class AdmissionRejected(EngineError):
    """A rule was refused admission. Carries the STRUCTURED decision
    (reason + price) — the REST layer serializes it instead of a bare
    error string, per the control plane's no-opaque-rejections
    contract."""

    def __init__(self, decision: Dict[str, Any]) -> None:
        super().__init__(
            f"rule admission rejected: {decision.get('reason', '?')}")
        self.decision = decision


def _env_float(name: str, default: float = 0.0) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def placement_shards() -> int:
    """Chips available for rule placement — the multi-chip serving mode's
    admission axis (docs/DISTRIBUTED.md). KUIPER_MESH geometry when set
    ("RxK"/"K", or "auto" = every local device); 1 otherwise, which keeps
    every single-chip deployment's admission semantics bit-identical."""
    from ..parallel.mesh import mesh_cfg_from_env

    cfg = mesh_cfg_from_env()
    if cfg is None:
        return 1
    if cfg.get("auto"):
        try:
            import jax

            return max(len(jax.devices()), 1)
        except Exception:
            return 1
    return max(int(cfg.get("rows", 1)) * int(cfg.get("keys", 1)), 1)


def _placement_for(price: Dict[str, Any], loads: List[float],
                   budget_bytes: float) -> Optional[Dict[str, Any]]:
    """Pick a placement for a candidate against the per-chip committed
    ledger: a mesh-eligible rule spreads its claim 1/K across every chip
    (its state is key-range sharded), anything else lands whole on the
    least-loaded chip. Returns the placement dict, or None when no chip
    (set) can hold the claim within the per-chip budget."""
    K = len(loads)
    cur_share = float(price.get("hbm_current_bytes", 0)) / max(K, 1)
    projected = float(price.get("hbm_projected_bytes", 0))
    if price.get("placement_eligible") and K > 1:
        share = projected / K
        if max(loads) + share + cur_share <= budget_bytes:
            return {"mode": "sharded", "shards": list(range(K)),
                    "bytes_per_shard": int(share)}
        return None
    chip = int(np_argmin(loads))
    if loads[chip] + projected + cur_share <= budget_bytes:
        return {"mode": "single", "shards": [chip],
                "bytes_per_shard": int(projected)}
    return None


def np_argmin(vals: List[float]) -> int:
    best, best_v = 0, None
    for i, v in enumerate(vals):
        if best_v is None or v < best_v:
            best, best_v = i, v
    return best


def bill_placement(price: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Placement for an already-admitted rule being (re)billed OUTSIDE
    the admission gate (boot recovery, operator start of a stopped
    rule): the same math as the gate, but it never rejects — a claim
    that no longer fits still bills where it would land, so the
    per-chip ledger reflects what actually runs after a restart
    instead of re-gating admissions against an empty ledger."""
    ctl = _controller
    K = placement_shards()
    budget = _env_float("KUIPER_HBM_BUDGET_MB") * 1024 * 1024
    if ctl is None or K <= 1 or budget <= 0:
        # with the budget unset the admission gate never places rules
        # either — billing only here would make the ledger (and the
        # kuiper_shard_rules gauge) appear out of nowhere after restarts
        return None
    loads = ctl.shard_loads(K)
    p = _placement_for(price, loads, budget)
    if p is not None:
        return p
    # nothing fits (the fleet outgrew the budget while it ran): bill
    # where the claim lands anyway, so the ledger reflects what runs
    projected = int(price.get("hbm_projected_bytes", 0))
    if price.get("placement_eligible"):
        return {"mode": "sharded", "shards": list(range(K)),
                "bytes_per_shard": projected // K}
    return {"mode": "single", "shards": [np_argmin(loads)],
            "bytes_per_shard": projected}


def _tier_price_slots(price: Dict[str, Any], plan, stmt, opts) -> int:
    """Hot-set slot claim for a tiered candidate (0 = untiered).
    Mirrors the planner's eligibility gates (planner/planner.py
    _build_device_chain + the fused node's window-type gate) so
    admission prices exactly what would be built; memoized in
    price["tier"] so the signature pricing and the HBM projection read
    one decision."""
    cached = price.get("tier")
    if cached is not None:
        return int(cached.get("hot_slots", 0))
    price["tier"] = {}
    try:
        from ..ops.tierstore import plan_tier_layout
        from ..planner.planner import resolve_tier_budget_mb
        from ..sql import ast as _ast

        budget = resolve_tier_budget_mb(opts)
        w = stmt.window
        if (not budget or stmt.sorts or stmt.limit is not None
                or (opts.plan_optimize_strategy or {}).get("mesh")
                or w is None
                or w.window_type not in (_ast.WindowType.TUMBLING_WINDOW,
                                         _ast.WindowType.HOPPING_WINDOW,
                                         _ast.WindowType.SLIDING_WINDOW)
                or any(s.kind == "heavy_hitters" for s in plan.specs)):
            return 0
        # the SAME pane count the node derives — a hopping rule's
        # per-key state is n_panes times wider, and pricing with 1 pane
        # would disagree with the node about whether the tier even
        # engages (unpriced tier jit sites / over-claimed HBM)
        if w.window_type == _ast.WindowType.HOPPING_WINDOW:
            iv = max(w.interval_ms() or 0, 1)
            n_panes = max((w.length_ms() + iv - 1) // iv, 1)
        elif w.window_type == _ast.WindowType.SLIDING_WINDOW:
            from ..ops.slidingring import ring_layout_for

            n_panes = ring_layout_for(w, plan).n_panes
        else:
            n_panes = 1
        layout = plan_tier_layout(
            plan, int(n_panes), opts.key_slots, budget,
            scan_interval_ms=opts.tier_scan_ms,
            window_ms=w.interval_ms() or w.length_ms())
        if layout is None:
            return 0
        # the node builds at the pow2-capped hot target (nodes_fused.py
        # uses the SAME TierLayout.hot_capacity) — price exactly that,
        # never more than the untiered request
        claim = min(int(opts.key_slots), layout.hot_capacity())
        price["tier"] = {"hot_slots": claim,
                        "demote_batch": int(layout.demote_batch)}
        return claim
    except Exception:
        return 0


def _relational_signatures(stmt, opts) -> int:
    """Certified signature count for the relational tier a host-chain
    rule would instantiate: the join-ring pad-pair ladder when the ON
    clause lowers, segscan shift/sort when lag or the rank family
    lowers. Non-lowering pieces cost nothing — they run as host python."""
    from ..observability import jitcert
    from ..planner import relational
    from ..planner.planner import _analytic_calls, _window_func_calls
    from ..sql.expr_ir import NotVectorizable

    kw: Dict[str, Any] = {}
    if stmt.joins and opts.join_impl == "device":
        try:
            low = relational.lower_join(stmt, stmt.joins)
            rl, rr = low.resid_signature()
            kw.update(join=True, join_resid_l=rl, join_resid_r=rr)
        except NotVectorizable:
            pass
    if opts.analytic_impl == "device":
        analytic = _analytic_calls(stmt)
        if analytic:
            try:
                relational.lower_analytics(analytic)
                kw["analytic_shift"] = True
            except NotVectorizable:
                pass
        wf = _window_func_calls(stmt)
        if wf:
            try:
                if relational.lower_window_funcs(wf).device_eligible():
                    kw["analytic_sort"] = True
            except NotVectorizable:
                pass
    if not kw:
        return 0
    return jitcert.estimate_relational_signatures(**kw)


def price_rule(rule, store) -> Dict[str, Any]:
    """Price a candidate rule off the live cost model + telemetry.
    Degrades per component — a rule the planner cannot price (graph
    rules, parse oddities) gets a zero-cost component, never an
    exception: admission must not be a new way for create to crash."""
    price: Dict[str, Any] = {
        "fold_us_per_s": 0.0,
        "path": "unknown",
        "hbm_projected_bytes": 0,
        "hbm_current_bytes": 0,
        "hbm_trend_bytes_per_min": 0.0,
        "compile_storms_total": 0,
        # compile load priced STATICALLY off the jitcert certificate:
        # the closed signature set this rule's kernel may trace at its
        # construction capacity (observability/jitcert.py) — admission
        # no longer waits for devwatch's live storm edge to learn a
        # candidate is compile-heavy. None = UNKNOWN (pricing failed /
        # unpriceable plan): gates treat unknown as compile load, so an
        # estimation failure can never open the storm-bypass
        "certified_new_signatures": None,
    }
    from ..observability import devwatch, memwatch
    from ..planner import sharing

    try:
        price["hbm_current_bytes"] = memwatch.registry().total_bytes()
    except Exception:
        pass
    try:
        from ..observability import health

        ev = health.evaluator()
        if ev is not None:
            price["hbm_trend_bytes_per_min"] = \
                ev.hbm_trend()["trend_bytes_per_min"]
    except Exception:
        pass
    try:
        price["compile_storms_total"] = \
            devwatch.registry().totals()["storms"]
    except Exception:
        pass
    try:
        from ..ops.aggspec import extract_kernel_plan
        from ..planner.planner import explain as plan_explain
        from ..planner.planner import merged_options
        from ..sql.parser import parse_select

        stmt = parse_select(rule.sql)
        opts = merged_options(rule)
        batches_per_s = 1000.0 / max(opts.micro_batch_linger_ms, 1)
        plan = None
        try:
            plan = extract_kernel_plan(stmt)
        except Exception:
            plan = None
        if plan is None:
            price["path"] = "host"
            price["fold_us_per_s"] = round(HOST_BATCH_US * batches_per_s, 1)
            price["certified_new_signatures"] = 0  # no fused kernel
            # relational kernels (join ring / segscan) still compile on
            # the host chain — price their certified signature sets so a
            # join-heavy candidate cannot slip past the compile budget
            try:
                price["certified_new_signatures"] = \
                    _relational_signatures(stmt, opts)
            except Exception as exc:
                logger.warning(
                    "relational pricing failed for rule %s: %s",
                    rule.id, exc)
        else:
            n_specs = len(plan.specs)
            explain = {}
            try:
                explain = plan_explain(rule, store)
            except Exception:
                pass
            share = explain.get("sharing") or {}
            if share.get("decision") == "shared":
                # marginal cost of joining the fleet: the emit-combine
                # overhead the sharing model already estimated — the
                # fold itself is already being paid for, and the store's
                # executables already exist (0 certified new signatures)
                price["path"] = "device-shared"
                price["fold_us_per_s"] = float(
                    (share.get("estimates") or {})
                    .get("emit_overhead_us_per_s", 0.0))
                price["certified_new_signatures"] = 0
            else:
                price["path"] = "device-private"
                price["fold_us_per_s"] = round(
                    (sharing.FOLD_DISPATCH_US
                     + sharing.FOLD_SPEC_US * n_specs) * batches_per_s, 1)
                try:
                    from ..observability import jitcert
                    from ..sql import ast as _ast

                    # pane count does not enter: it changes signature
                    # SHAPES, not the executable count the budget gates
                    # on (one executable per capacity step either way).
                    # DABA sliding rules price their ring sites too
                    # (advance/flip/query + components_dyn) — without
                    # this the budget under-prices sliding candidates
                    ring_slots = 0
                    if (stmt.window is not None
                            and stmt.window.window_type
                            == _ast.WindowType.SLIDING_WINDOW
                            and opts.sliding_impl == "daba"):
                        from ..ops.slidingring import ring_layout_for

                        ring_slots = ring_layout_for(
                            stmt.window, plan).n_ring_panes
                    sig_args = (plan, 1, opts.micro_batch_rows,
                                _tier_price_slots(price, plan, stmt, opts)
                                or opts.key_slots)
                    sig_kw = dict(
                        sliding_ring_slots=ring_slots,
                        tier_demote_batch=(price.get("tier", {})
                                           .get("demote_batch", 0)))
                    price["certified_new_signatures"] = \
                        jitcert.estimate_plan_signatures(
                            *sig_args, **sig_kw)
                    # AOT ledger: signatures a fleet bake already
                    # persisted are NOT compile debt — the signature
                    # budget gates on `uncached` when the disk cache is
                    # on (runtime/aotcache.py, docs/AOT_CACHE.md)
                    from . import aotcache

                    price["compile"] = aotcache.plan_compile_price(
                        jitcert.estimate_plan_certs(*sig_args, **sig_kw))
                except Exception as exc:
                    # leave the UNKNOWN sentinel: failing open here
                    # would both disarm the signature budget and route
                    # a compile-heavy candidate through the storm
                    # bypass — the exact class the gate exists to defer
                    logger.warning(
                        "jitcert pricing failed for rule %s: %s",
                        rule.id, exc)
                    price["certify_error"] = str(exc)[:200]
            # projected window-state claim: one f32 slot per key per agg
            # spec, times the pane/staging multiplier (documented in
            # docs/RESILIENCE.md — a bound, not an allocation). A TIERED
            # rule claims its HOT-SET footprint, not its full
            # cardinality: cold keys spill to host, so a high-cardinality
            # rule whose hot set fits is admitted where the untiered
            # projection would 429 it.
            slot_claim = (_tier_price_slots(price, plan, stmt, opts)
                          or opts.key_slots)
            price["hbm_projected_bytes"] = int(
                slot_claim * max(n_specs, 1) * 4 * HBM_PANE_FACTOR)
            # placement (multi-chip serving): a rule the planner would
            # shard spreads its state claim 1/K across the mesh — the
            # HBM gate then places it instead of rejecting at the
            # single-chip budget (docs/DISTRIBUTED.md)
            try:
                from ..planner.planner import mesh_request

                req = mesh_request(opts, plan)
                price["placement_eligible"] = req["mode"] == "sharded"
                if req["mode"] == "sharded":
                    price["mesh_source"] = req.get("source")
            except Exception:
                price["placement_eligible"] = False
            if share:
                price["sharing"] = {
                    "decision": share.get("decision"),
                    "reason": share.get("reason", "")[:160],
                }
    except Exception as exc:
        price["price_error"] = str(exc)[:200]
    return price


def _static_gates(price: Dict[str, Any],
                  committed_us_per_s: float,
                  ctl: "Optional[QoSController]" = None,
                  rule_id: Optional[str] = None
                  ) -> Optional[Dict[str, Any]]:
    """Budget gates that need no controller: return a reject decision or
    None. Budgets default OFF (env unset) — admission then accepts.
    With a controller AND a multi-chip mesh (KUIPER_MESH), the HBM
    budget becomes PER-CHIP and placement-aware: the candidate is
    assigned to the least-loaded shard (or spread 1/K when its plan
    shards) instead of rejecting at the single-chip budget."""
    hbm_budget_mb = _env_float("KUIPER_HBM_BUDGET_MB")
    if hbm_budget_mb > 0:
        budget = hbm_budget_mb * 1024 * 1024
        K = placement_shards()
        if ctl is not None and K > 1:
            loads = ctl.shard_loads(K, exclude=rule_id)
            placement = _placement_for(price, loads, budget)
            if placement is None:
                projected = price["hbm_projected_bytes"]
                return {
                    "decision": "reject",
                    "reason": (
                        f"projected HBM {projected / 1e6:.1f}MB does not "
                        f"fit any of {K} chips' {hbm_budget_mb:.0f}MB "
                        "per-chip budgets (KUIPER_HBM_BUDGET_MB; "
                        "least-loaded "
                        f"{min(loads) / 1e6:.1f}MB committed)"),
                    "price": price,
                }
            price["placement"] = placement
        else:
            projected = (price["hbm_current_bytes"]
                         + price["hbm_projected_bytes"])
            if projected > budget:
                return {
                    "decision": "reject",
                    "reason": (
                        f"projected HBM {projected / 1e6:.1f}MB exceeds "
                        f"the {hbm_budget_mb:.0f}MB budget "
                        "(KUIPER_HBM_BUDGET_MB)"),
                    "price": price,
                }
    fold_budget = _env_float("KUIPER_ADMISSION_FOLD_BUDGET_US_PER_S")
    if fold_budget > 0:
        if committed_us_per_s + price["fold_us_per_s"] > fold_budget:
            return {
                "decision": "reject",
                "reason": (
                    f"fold cost {price['fold_us_per_s']:.0f}us/s on top of "
                    f"{committed_us_per_s:.0f}us/s already committed "
                    f"exceeds the {fold_budget:.0f}us/s budget "
                    "(KUIPER_ADMISSION_FOLD_BUDGET_US_PER_S)"),
                "price": price,
            }
    sig_budget = int(_env_float("KUIPER_ADMISSION_SIG_BUDGET"))
    if sig_budget > 0:
        certified = price.get("certified_new_signatures")
        # unknown (None) passes THIS gate — rejecting on a pricing
        # failure would make every unpriceable host rule a 429; the
        # storm gate below stays conservative for unknowns instead
        priced = certified
        ledger = price.get("compile")
        if (priced is not None and ledger is not None
                and ledger.get("enabled") and not ledger.get("truncated")):
            # warm fleet image: only certified-but-UNCACHED signatures
            # are compile debt — executables the AOT bake persisted load
            # in tens of ms, they cannot stall the serve path
            priced = int(ledger.get("uncached", priced))
        if priced is not None and int(priced) > sig_budget:
            return {
                "decision": "reject",
                "reason": (
                    f"certified uncached compile surface of {priced} XLA "
                    f"signatures (certified {certified}) exceeds the "
                    f"{sig_budget}-signature budget "
                    "(KUIPER_ADMISSION_SIG_BUDGET; jitcert certificate "
                    "at construction capacity minus AOT-cached "
                    "executables)"),
                "price": price,
            }
    return None


# -------------------------------------------------------------- controller
class _RuleCtl:
    """Per-rule controller state across ticks."""

    __slots__ = ("shed_level", "breach_run", "clear_run", "qos_class",
                 "shed_rows_seen", "autosize_cool", "orig_sizes",
                 "missing_runs", "skew_run", "hint_active")

    def __init__(self) -> None:
        self.shed_level = 0
        self.breach_run = 0
        self.clear_run = 0
        self.qos_class = DEFAULT_QOS_CLASS
        self.shed_rows_seen = 0
        self.autosize_cool = 0
        self.orig_sizes: Dict[str, Dict[str, int]] = {}
        self.missing_runs = 0
        # mesh skew hysteresis (observability/meshwatch.py): consecutive
        # skewed ticks, and whether a rebalance_hint is currently open
        self.skew_run = 0
        self.hint_active = False


class QoSController:
    """Periodic actuator over the health plane's verdicts. `rules_fn()`
    yields the same (rule_id, topo, options) triples the HealthEvaluator
    consumes; `start_fn(rule_id)` starts a queued rule when admission
    pressure clears; `verdicts_fn()` defaults to the installed health
    evaluator's last verdicts (injectable for tests)."""

    def __init__(self, rules_fn: Callable[[], List[tuple]],
                 start_fn: Optional[Callable[[str], None]] = None,
                 verdicts_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 unqueue_fn: Optional[Callable[[str], None]] = None,
                 interval_ms: int = DEFAULT_INTERVAL_MS,
                 up_ticks: int = 2, down_ticks: int = 3) -> None:
        self._rules_fn = rules_fn
        self._start_fn = start_fn
        self._verdicts_fn = verdicts_fn
        # called when an entry leaves the queue WITHOUT being started
        # (dequeue-time reject) — the registry wires this to drop the
        # persisted admission_queue slot, or a restart would resurrect
        # a rule the controller already refused
        self._unqueue_fn = unqueue_fn
        self.interval_ms = int(interval_ms)
        self.up_ticks = max(int(up_ticks), 1)
        self.down_ticks = max(int(down_ticks), 1)
        self._lock = threading.RLock()
        self._timer = None
        self._running = False
        self.ticks = 0
        self._tracks: Dict[str, _RuleCtl] = {}
        # admission bookkeeping
        self._adm_counts = {"accept": 0, "reject": 0, "queue": 0}
        self._aqueue: Dict[str, Dict[str, Any]] = {}  # rid -> entry
        self._committed: Dict[str, float] = {}  # rid -> fold_us_per_s
        # per-chip HBM ledger (multi-chip serving): rid -> placement
        # {"mode": "sharded"|"single", "shards": [chip...],
        #  "bytes_per_shard": int} — billed at commit, released with the
        # rule; shard_loads() folds them into per-chip committed bytes
        self._placements: Dict[str, Dict[str, Any]] = {}
        self._prev_storms = 0
        self._storm_active = False
        # shed accounting: monotonic row totals per (rule, qos class) —
        # survives topo restarts (node counters reset with the topo)
        self._shed_totals: Dict[tuple, int] = {}
        # autosize accounting
        self.autosize_events = 0
        self._autosize_log: deque = deque(maxlen=64)
        # mesh skew accounting: rebalance_hint events raised (lifetime)
        self._rebalance_hints = 0

    # -------------------------------------------------------------- lifecycle
    def start(self) -> None:
        with self._lock:
            if self._running:
                return
            self._running = True
        self._arm()

    def stop(self) -> None:
        with self._lock:
            self._running = False
            if self._timer is not None:
                self._timer.stop()
                self._timer = None

    def _arm(self) -> None:
        self._timer = timex.after(self.interval_ms, self._fire)

    def _fire(self, ts: int) -> None:
        if not self._running:
            return
        try:
            self.tick()
        except Exception as exc:  # the controller must never kill a timer
            logger.warning("qos controller tick failed: %s", exc)
        if self._running:
            self._arm()

    # -------------------------------------------------------------- admission
    def storm_active(self) -> bool:
        """True when a compile storm fired since the last control tick —
        a bad moment to admit new compile load."""
        from ..observability import devwatch

        try:
            now_storms = devwatch.registry().totals()["storms"]
        except Exception:
            return False
        with self._lock:
            return self._storm_active or now_storms > self._prev_storms

    def breaching_count(self) -> int:
        verdicts = self._verdicts()
        return sum(1 for v in verdicts.values()
                   if v.get("state") == "breaching")

    def committed_us_per_s(self) -> float:
        with self._lock:
            return sum(self._committed.values())

    def note_admission(self, decision: str) -> None:
        with self._lock:
            self._adm_counts[decision] = \
                self._adm_counts.get(decision, 0) + 1

    def commit(self, rule_id: str, fold_us_per_s: float,
               placement: Optional[Dict[str, Any]] = None) -> None:
        with self._lock:
            self._committed[rule_id] = float(fold_us_per_s)
            if placement:
                self._placements[rule_id] = dict(placement)

    def release(self, rule_id: str) -> None:
        """Rule deleted: drop its admission ledger entry + queue slot +
        placement billing + controller track (shed TOTALS survive —
        monotonic counters)."""
        with self._lock:
            self._committed.pop(rule_id, None)
            self._placements.pop(rule_id, None)
            self._aqueue.pop(rule_id, None)
            self._tracks.pop(rule_id, None)

    def shard_loads(self, n_shards: Optional[int] = None,
                    exclude: Optional[str] = None) -> List[float]:
        """Committed HBM bytes per chip off the placement ledger — the
        per-chip half of the admission gate and the kuiper_shard_hbm_*
        families. Sized to max(n_shards, highest billed chip + 1).
        `exclude` drops one rule's own billing (an UPDATE replaces its
        claim — gating it against itself would double-bill the HBM
        axis, the same contract the fold-budget gate keeps)."""
        K = n_shards if n_shards is not None else placement_shards()
        with self._lock:
            placements = [p for rid, p in self._placements.items()
                          if rid != exclude]
        for p in placements:
            for c in p.get("shards", ()):
                K = max(K, int(c) + 1)
        loads = [0.0] * max(K, 1)
        for p in placements:
            share = float(p.get("bytes_per_shard", 0))
            for c in p.get("shards", ()):
                loads[int(c)] += share
        return loads

    def placement_state(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {rid: dict(p) for rid, p in self._placements.items()}

    def enqueue(self, rule_id: str, decision: Dict[str, Any]) -> bool:
        """Park a queue-decided rule for retry at control ticks. False
        when the queue is full (the caller downgrades to reject). The
        `queue` counter + flight event are recorded HERE, on success —
        counting at decision time would misreport a full-queue
        downgrade as both queued and rejected."""
        now = timex.now_ms()
        with self._lock:
            if len(self._aqueue) >= ADMISSION_QUEUE_CAP \
                    and rule_id not in self._aqueue:
                return False
            self._aqueue[rule_id] = {
                "rule": rule_id,
                "reason": decision.get("reason", ""),
                "price": decision.get("price", {}),
                "enqueued_ms": now,
                "attempts": 0,
            }
        self.note_admission("queue")
        from .events import recorder

        recorder().record(
            "admission", rule=rule_id, severity="info", ts_ms=now,
            decision="queue", reason=decision.get("reason", ""))
        return True

    def queued(self, rule_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            entry = self._aqueue.get(rule_id)
            return dict(entry) if entry is not None else None

    def claim(self, rule_id: str) -> Optional[Dict[str, Any]]:
        """Atomically pop a queued rule and commit its price — the ONE
        place the dequeue+commit invariant lives (the controller's own
        drain and the registry's operator-start override both use it).
        Returns the entry, or None when the rule wasn't queued."""
        with self._lock:
            entry = self._aqueue.pop(rule_id, None)
            if entry is None:
                return None
            price = entry.get("price") or {}
            self._committed[rule_id] = float(
                price.get("fold_us_per_s", 0.0))
            if price.get("placement"):
                self._placements[rule_id] = dict(price["placement"])
            return entry

    def _drain_admission_queue(self, now: int) -> None:
        """Retry queued rules; start the ones whose pressure cleared.
        Starts run OUTSIDE the controller lock — start_fn reaches the
        rule registry, whose locks must never nest under ours."""
        with self._lock:
            pending = list(self._aqueue.items())
        if not pending:
            return
        defer, reason = self._pressure()
        if defer:
            with self._lock:
                for _rid, entry in pending:
                    entry["attempts"] += 1
            return
        from .events import recorder

        for rid, entry in pending:
            # the budget gates re-run at dequeue time: N rules queued
            # during one storm each passed the gates against a ledger
            # that excluded the others — starting them all unchecked
            # could jointly blow the very budgets the gates enforce
            with self._lock:
                pending_entry = self._aqueue.get(rid)
                committed = sum(v for r, v in self._committed.items()
                                if r != rid)
            if pending_entry is None:
                continue
            price = dict(pending_entry.get("price") or {})
            price.setdefault("fold_us_per_s", 0.0)
            price.setdefault("hbm_projected_bytes", 0)
            # the HBM side must re-gate against LIVE telemetry — the
            # enqueue-time snapshot is exactly what the queue period
            # may have invalidated
            try:
                from ..observability import memwatch

                price["hbm_current_bytes"] = \
                    memwatch.registry().total_bytes()
            except Exception:
                price.setdefault("hbm_current_bytes", 0)
            rej = _static_gates(price, committed, ctl=self, rule_id=rid)
            if rej is not None:
                with self._lock:
                    self._aqueue.pop(rid, None)
                self.note_admission("reject")
                recorder().record(
                    "admission", rule=rid, severity="warn", ts_ms=now,
                    decision="reject", dequeued=True,
                    reason=rej["reason"])
                logger.warning("queued rule %s rejected at dequeue: %s",
                               rid, rej["reason"])
                if self._unqueue_fn is not None:
                    try:
                        self._unqueue_fn(rid)
                    except Exception:
                        pass
                continue
            with self._lock:
                # the gate re-run may have picked a placement against
                # the LIVE ledger — claim() must commit that, not the
                # enqueue-time snapshot
                if rid in self._aqueue:
                    self._aqueue[rid]["price"] = price
            entry = self.claim(rid)
            if entry is None:
                continue
            self.note_admission("accept")
            recorder().record(
                "admission", rule=rid, severity="info", ts_ms=now,
                decision="accept", dequeued=True,
                queued_ms=max(now - entry.get("enqueued_ms", now), 0),
                reason="admission pressure cleared")
            if self._start_fn is not None:
                try:
                    self._start_fn(rid)
                except Exception as exc:
                    logger.warning(
                        "queued rule %s failed to start: %s", rid, exc)

    def _pressure(self, price: Optional[Dict[str, Any]] = None) -> tuple:
        """(defer?, reason) — the transient conditions that QUEUE a new
        rule instead of accepting or rejecting it outright. A candidate
        whose jitcert certificate prices ZERO new signatures (shared /
        host path) adds no compile load and is never storm-deferred;
        an UNKNOWN count (None — pricing failed) defers like compile
        load, never bypasses."""
        certified = (price or {}).get("certified_new_signatures")
        adds_compile_load = (price is None or certified is None
                             or int(certified) > 0)
        if os.environ.get("KUIPER_ADMISSION_DEFER_STORMS", "1") != "0" \
                and adds_compile_load and self.storm_active():
            return True, ("an XLA compile storm is active; new compile "
                          "load is deferred until it clears")
        breach_gate = int(_env_float("KUIPER_ADMISSION_DEFER_BREACHING"))
        if breach_gate > 0:
            n = self.breaching_count()
            if n >= breach_gate:
                return True, (f"{n} rule(s) are breaching their SLO; "
                              "admission deferred until the engine "
                              "recovers")
        return False, ""

    # ----------------------------------------------------------------- tick
    def _verdicts(self) -> Dict[str, Any]:
        if self._verdicts_fn is not None:
            try:
                return self._verdicts_fn() or {}
            except Exception:
                return {}
        from ..observability import health

        ev = health.evaluator()
        if ev is None:
            return {}
        try:
            return ev.verdicts()
        except Exception:
            return {}

    def tick(self) -> Dict[str, Any]:
        """One control pass: update the storm edge, walk every rule's
        verdict through the shed ladder + autosizer, then retry the
        admission queue. Returns a {rule: action} summary (tests)."""
        # clock BEFORE the lock: mock-clock advances fire _fire -> tick
        # while holding the clock lock (same ABBA class health.tick
        # documents; clock orders first)
        now = timex.now_ms()
        verdicts = self._verdicts()
        from ..observability import devwatch

        actions: Dict[str, Any] = {}
        with self._lock:
            try:
                storms = devwatch.registry().totals()["storms"]
                self._storm_active = storms > self._prev_storms
                self._prev_storms = storms
            except Exception:
                self._storm_active = False
            try:
                rules = list(self._rules_fn() or [])
            except Exception as exc:
                logger.warning("qos controller rules_fn failed: %s", exc)
                rules = []
            seen = set()
            for entry in rules:
                try:
                    rid, topo, options = entry
                except (TypeError, ValueError):
                    continue
                if topo is None:
                    continue
                seen.add(rid)
                try:
                    act = self._control_rule(rid, topo, options or {},
                                             verdicts.get(rid), now)
                    if act:
                        actions[rid] = act
                except Exception as exc:
                    logger.warning("qos control of rule %s failed: %s",
                                   rid, exc)
            # tracks are swept with a GRACE period, not on first miss: a
            # rule mid-restart (kill/restore, update) briefly has no live
            # topo, and dropping its track then would reset the shed
            # ladder + re-baseline its shed accounting mid-storm
            for rid in [r for r in self._tracks if r not in seen]:
                tr = self._tracks[rid]
                tr.missing_runs += 1
                if tr.missing_runs > 10:
                    del self._tracks[rid]
            for rid in seen:
                if rid in self._tracks:
                    self._tracks[rid].missing_runs = 0
            self.ticks += 1
        self._drain_admission_queue(now)
        return actions

    # ------------------------------------------------------------- per rule
    def _control_rule(self, rid: str, topo: Any, options: Dict[str, Any],
                      verdict: Optional[Dict[str, Any]],
                      now: int) -> Dict[str, Any]:
        tr = self._tracks.get(rid)
        if tr is None:
            tr = self._tracks[rid] = _RuleCtl()
        tr.qos_class = parse_qos_class(options)
        ladder = SHED_LADDERS[tr.qos_class]
        # a rule UPDATE can change the class under a live shed level —
        # clamp to the new ladder (critical's empty ladder clamps to 0,
        # i.e. the re-assert below clears the gate) or the indexing
        # throws and this rule drops out of control forever
        if tr.shed_level > len(ladder):
            tr.shed_level = len(ladder)
        state = (verdict or {}).get("state", "healthy")
        act: Dict[str, Any] = {}

        # ---- shed accounting: fold the entry nodes' shed_qos counters
        # into the monotonic per-(rule, qos) totals. A restarted topo
        # resets its node counters — cur < seen re-baselines, no
        # negative deltas, no double counting.
        try:
            cur_rows = topo.shed_rows()
        except Exception:
            cur_rows = tr.shed_rows_seen
        delta = cur_rows - tr.shed_rows_seen
        if delta < 0:
            delta = cur_rows
        if delta > 0:
            key = (rid, tr.qos_class)
            self._shed_totals[key] = self._shed_totals.get(key, 0) + delta
        tr.shed_rows_seen = cur_rows

        # ---- re-assert the gate after a topo restart: the shed LEVEL
        # lives here, the fraction lives on the (rebuildable) entry
        # nodes — a restarted rule must not silently resume unshed while
        # the controller believes it is relieved
        expected = ladder[tr.shed_level - 1] if tr.shed_level > 0 else 0.0
        try:
            if abs(topo.shed_fraction() - expected) > 1e-9:
                topo.set_shed(expected)
        except Exception:
            pass

        # ---- shed ladder with health-FSM-mirrored hysteresis
        if state == "breaching":
            tr.breach_run += 1
            tr.clear_run = 0
        elif state == "healthy":
            tr.clear_run += 1
            tr.breach_run = 0
        else:  # degraded holds the current level
            tr.breach_run = 0
            tr.clear_run = 0
        target = tr.shed_level
        if ladder and tr.breach_run >= self.up_ticks \
                and tr.shed_level < len(ladder):
            target = tr.shed_level + 1
            tr.breach_run = 0
        elif tr.clear_run >= self.down_ticks and tr.shed_level > 0:
            target = tr.shed_level - 1
            tr.clear_run = 0
        if target != tr.shed_level:
            prev_level = tr.shed_level
            tr.shed_level = target
            frac = ladder[target - 1] if target > 0 else 0.0
            topo.set_shed(frac)
            from .events import recorder

            severity = "warn" if target > prev_level else "info"
            recorder().record(
                "shed", rule=rid, severity=severity, ts_ms=now,
                level=target, previous=prev_level,
                fraction=frac, qos=tr.qos_class,
                state=state)
            logger.log(
                30 if target > prev_level else 20,
                "rule %s: shed level %d -> %d (%.0f%% of input, qos "
                "class %s, health %s)", rid, prev_level, target,
                frac * 100, tr.qos_class, state)
            act["shed"] = {"level": target, "fraction": frac}
        if state == "breaching" and not ladder and verdict is not None:
            act.setdefault("shed", {"level": 0, "fraction": 0.0,
                                    "exempt": "critical"})

        # ---- autosize off the attributed bottleneck
        if tr.autosize_cool > 0:
            tr.autosize_cool -= 1
        else:
            auto = self._autosize_rule(rid, topo, tr, verdict, state, now)
            if auto:
                tr.autosize_cool = 3  # cooldown: one action per ~3 ticks
                act["autosize"] = auto

        # ---- mesh skew -> rebalance_hint (signal only: actually moving
        # key ranges is ROADMAP item 2's rebalancer; this gives it — and
        # the operator — the structured trigger). Shed-ladder-style
        # hysteresis: a hint opens after up_ticks consecutive skewed
        # observations, closes once the run drains back to zero (one
        # step per clear tick), and never re-fires while open.
        mesh = ((verdict or {}).get("bottleneck") or {}).get("mesh")
        if mesh is not None and mesh.get("skewed"):
            tr.skew_run += 1
            if tr.skew_run >= self.up_ticks and not tr.hint_active:
                tr.hint_active = True
                self._rebalance_hints += 1
                from .events import recorder

                recorder().record(
                    "rebalance_hint", rule=rid, severity="warn", ts_ms=now,
                    skew_ratio=mesh.get("skew_ratio"),
                    hot_shard=mesh.get("hot_shard"),
                    mesh=mesh.get("mesh"),
                    shard_loads=self.shard_loads())
                logger.warning(
                    "rule %s: mesh skew %.2fx on shard %s (mesh %s) — "
                    "rebalance hint raised", rid,
                    mesh.get("skew_ratio") or 0.0,
                    mesh.get("hot_shard"), mesh.get("mesh"))
                act["rebalance_hint"] = {
                    "skew_ratio": mesh.get("skew_ratio"),
                    "hot_shard": mesh.get("hot_shard"),
                }
        else:
            if tr.skew_run >= 1:
                tr.skew_run -= 1
            if tr.skew_run == 0 and tr.hint_active:
                tr.hint_active = False
                from .events import recorder

                recorder().record(
                    "rebalance_hint", rule=rid, severity="info", ts_ms=now,
                    cleared=True)
        return act

    def _autosize_rule(self, rid: str, topo: Any, tr: _RuleCtl,
                       verdict: Optional[Dict[str, Any]], state: str,
                       now: int) -> Optional[Dict[str, Any]]:
        max_pool = int(_env_float("KUIPER_AUTOSIZE_MAX_POOL", 6))
        max_ring = int(_env_float("KUIPER_AUTOSIZE_MAX_RING", 6))
        srcs = [n for n in list(getattr(topo, "sources", []))
                + [n for st, _ in getattr(topo, "live_shared",
                                          lambda: [])()
                   for n in getattr(st, "nodes", [])]
                if hasattr(n, "resize_ingest")
                and getattr(n, "decode_pool_size", 0) > 0]
        if not srcs:
            return None
        bn = (verdict or {}).get("bottleneck") or {}
        stage = bn.get("stage")
        src = srcs[0]
        orig = tr.orig_sizes.setdefault(src.name, {
            "pool_size": src.decode_pool_size,
            "ring_depth": src.ring_depth,
        })
        action = None
        if state != "healthy" and stage == "decode" \
                and src.decode_pool_size < max_pool:
            applied = src.resize_ingest(
                pool_size=src.decode_pool_size + 1)
            action = {"node": src.name, "action": "grow_pool",
                      "stage": stage, "applied": applied}
        elif state != "healthy" and stage == "upload" \
                and src.ring_depth < max_ring:
            applied = src.resize_ingest(ring_depth=src.ring_depth + 1)
            action = {"node": src.name, "action": "grow_ring",
                      "stage": stage, "applied": applied}
        elif state == "healthy" and tr.clear_run >= 2 * self.down_ticks:
            # sustained health: step back toward the configured sizes
            if src.decode_pool_size > orig["pool_size"]:
                applied = src.resize_ingest(
                    pool_size=src.decode_pool_size - 1)
                action = {"node": src.name, "action": "shrink_pool",
                          "stage": stage, "applied": applied}
            elif src.ring_depth > orig["ring_depth"]:
                applied = src.resize_ingest(
                    ring_depth=src.ring_depth - 1)
                action = {"node": src.name, "action": "shrink_ring",
                          "stage": stage, "applied": applied}
        if action is None:
            return None
        self.autosize_events += 1
        self._autosize_log.append({"ts_ms": now, "rule": rid, **action})
        from .events import recorder

        recorder().record(
            "autosize", rule=rid, severity="info", ts_ms=now, **{
                k: v for k, v in action.items() if k != "applied"},
            **(action.get("applied") or {}))
        logger.info("rule %s: autosize %s on %s (bottleneck %s) -> %s",
                    rid, action["action"], action["node"], stage,
                    action.get("applied"))
        return action

    # ---------------------------------------------------------------- queries
    def shed_state(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            out = {}
            for rid, tr in self._tracks.items():
                ladder = SHED_LADDERS[tr.qos_class]
                lvl = min(tr.shed_level, len(ladder))  # mid-update clamp
                out[rid] = {
                    "level": lvl,
                    "fraction": ladder[lvl - 1] if lvl > 0 else 0.0,
                    "qos": tr.qos_class,
                    "rows": tr.shed_rows_seen,
                }
            return out

    def shed_totals(self) -> Dict[tuple, int]:
        with self._lock:
            return dict(self._shed_totals)

    def admission_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._adm_counts)

    def diagnostics(self) -> Dict[str, Any]:
        """The GET /diagnostics/control payload."""
        with self._lock:
            queued = [dict(e) for e in self._aqueue.values()]
            committed = sum(self._committed.values())
            autosize_recent = list(self._autosize_log)
        return {
            "controller": {
                "interval_ms": self.interval_ms,
                "ticks": self.ticks,
                "up_ticks": self.up_ticks,
                "down_ticks": self.down_ticks,
            },
            "admission": {
                "decisions": self.admission_counts(),
                "queued": queued,
                "committed_fold_us_per_s": round(committed, 1),
                "budgets": {
                    "hbm_budget_mb": _env_float("KUIPER_HBM_BUDGET_MB"),
                    "fold_budget_us_per_s": _env_float(
                        "KUIPER_ADMISSION_FOLD_BUDGET_US_PER_S"),
                    "defer_breaching": int(_env_float(
                        "KUIPER_ADMISSION_DEFER_BREACHING")),
                },
                "storm_active": self.storm_active(),
            },
            "placement": {
                "shards": placement_shards(),
                "committed_bytes_per_shard": [
                    int(v) for v in self.shard_loads()],
                "rules": self.placement_state(),
            },
            "shedding": self.shed_state(),
            "shed_totals": {
                f"{rid}|{qos}": n
                for (rid, qos), n in sorted(self.shed_totals().items())},
            "autosize": {
                "events": self.autosize_events,
                "recent": autosize_recent,
            },
            "mesh": self._mesh_diagnostics(),
        }

    def _mesh_diagnostics(self) -> Dict[str, Any]:
        """Controller-side mesh view: skew/hint hysteresis per rule plus
        the meshwatch skew report — the "mesh" section of
        /diagnostics/control and the explain "mesh" detail's hint state."""
        from ..observability import meshwatch

        with self._lock:
            rules = {
                rid: {"skew_run": tr.skew_run,
                      "hint_active": tr.hint_active}
                for rid, tr in self._tracks.items()
                if tr.skew_run or tr.hint_active
            }
            hints = self._rebalance_hints
        try:
            skew = meshwatch.skew_report()
        except Exception:
            skew = {}
        return {
            "rebalance_hints_total": hints,
            "rules": rules,
            "skew": skew,
            "threshold": meshwatch.skew_threshold(),
        }


# -------------------------------------------------------------- singleton
_controller: Optional[QoSController] = None
_install_lock = threading.Lock()


def install(rules_fn: Callable[[], List[tuple]],
            start_fn: Optional[Callable[[str], None]] = None,
            interval_ms: int = DEFAULT_INTERVAL_MS,
            start: bool = True, **kw) -> QoSController:
    """Install (replacing any prior) the engine-wide controller. The
    REST server installs one over its rule registry at boot."""
    global _controller
    with _install_lock:
        if _controller is not None:
            _controller.stop()
        _controller = QoSController(rules_fn, start_fn=start_fn,
                                    interval_ms=interval_ms, **kw)
        ctl = _controller
    if start:
        ctl.start()
    return ctl


def controller() -> Optional[QoSController]:
    return _controller


def reset() -> None:
    """Test hook: stop and drop the installed controller."""
    global _controller
    with _install_lock:
        if _controller is not None:
            _controller.stop()
        _controller = None


# ------------------------------------------------------- admission helpers
def admit_rule(rule, store, allow_queue: bool = True) -> Dict[str, Any]:
    """The admission decision for one candidate rule: {"decision":
    accept|reject|queue, "reason", "price"}. Pure read — callers act on
    it (RuleRegistry.create/update). Works without an installed
    controller (static budget gates only; pressure deferral and
    counters need the controller). `allow_queue=False` (updates — the
    old definition keeps running, there is nothing to defer) skips the
    pressure gate entirely so no phantom queue decision is counted or
    flight-recorded."""
    if os.environ.get("KUIPER_ADMISSION", "1") == "0":
        return {"decision": "accept", "reason": "admission disabled",
                "price": {}}
    ctl = _controller
    price = price_rule(rule, store)
    committed = ctl.committed_us_per_s() if ctl is not None else 0.0
    # a rule replacing itself (update) must not be double-billed
    if ctl is not None:
        with ctl._lock:
            committed -= ctl._committed.get(rule.id, 0.0)
    decision = _static_gates(price, max(committed, 0.0), ctl=ctl,
                             rule_id=rule.id)
    if decision is None and ctl is not None and allow_queue:
        defer, reason = ctl._pressure(price)
        if defer:
            decision = {"decision": "queue", "reason": reason,
                        "price": price}
    if decision is None:
        decision = {"decision": "accept", "reason": "within budgets",
                    "price": price}
    if ctl is not None:
        # queue decisions are counted/flight-recorded by enqueue() on
        # SUCCESS — counting here would misreport a full-queue
        # downgrade (429) as queued
        if decision["decision"] != "queue":
            ctl.note_admission(decision["decision"])
        if decision["decision"] == "reject":
            from .events import recorder

            recorder().record(
                "admission", rule=rule.id, severity="warn",
                decision="reject", reason=decision["reason"],
                fold_us_per_s=price.get("fold_us_per_s"),
                path=price.get("path"))
    return decision


# -------------------------------------------------------- Prometheus view
def render_prometheus(out: List[str], esc) -> None:
    """Append the control-plane families to a /metrics scrape."""
    ctl = _controller
    if ctl is None:
        return
    out.append("# TYPE kuiper_admission_total counter")
    out.append("# HELP kuiper_admission_total rule admission decisions "
               "by outcome (accept/reject/queue)")
    counts = ctl.admission_counts()
    for decision in ("accept", "reject", "queue"):
        out.append(
            f'kuiper_admission_total{{decision="{decision}"}} '
            f"{counts.get(decision, 0)}")
    out.append("# TYPE kuiper_shed_total counter")
    out.append("# HELP kuiper_shed_total rows shed per rule by the SLO "
               "control plane, labeled by qos class "
               "(reason=shed_qos in the drop taxonomy)")
    for (rid, qos), n in sorted(ctl.shed_totals().items()):
        out.append(
            f'kuiper_shed_total{{rule="{esc(rid)}",qos="{esc(qos)}"}} '
            f"{n}")
    out.append("# TYPE kuiper_autosize_events_total counter")
    out.append("# HELP kuiper_autosize_events_total decode pool / ingest "
               "ring autosize actions taken by the control plane")
    out.append(f"kuiper_autosize_events_total {ctl.autosize_events}")
    # placement-aware admission (multi-chip serving): the per-chip HBM
    # ledger the gate places rules against, plus rules placed per chip
    loads = ctl.shard_loads()
    placements = ctl.placement_state()
    rules_per = [0] * len(loads)
    for p in placements.values():
        for c in p.get("shards", ()):
            if 0 <= int(c) < len(rules_per):
                rules_per[int(c)] += 1
    out.append("# TYPE kuiper_shard_hbm_committed_bytes gauge")
    out.append("# HELP kuiper_shard_hbm_committed_bytes admission-"
               "committed HBM bytes per placement shard (per-chip "
               "ledger, runtime/control.py)")
    for i, v in enumerate(loads):
        out.append(
            f'kuiper_shard_hbm_committed_bytes{{shard="{i}"}} {int(v)}')
    out.append("# TYPE kuiper_shard_rules gauge")
    out.append("# HELP kuiper_shard_rules rules placed on each shard by "
               "placement-aware admission")
    for i, v in enumerate(rules_per):
        out.append(f'kuiper_shard_rules{{shard="{i}"}} {v}')
