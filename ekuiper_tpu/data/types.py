"""Stream data types & schema — analogue of eKuiper's column types in stream
DDL (reference: pkg/ast/sourceStmt.go) and the planner's field index assignment
for SliceTuple (reference: internal/topo/planner/planner.go:88,94-165).

In the TPU build the schema is load-bearing: it decides which columns are
device-eligible (numeric → jnp arrays on HBM) and which stay host-side
(strings/arrays/structs → dictionary-encoded or object columns).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional

import numpy as np


class DataType(str, Enum):
    BIGINT = "bigint"
    FLOAT = "float"
    STRING = "string"
    BOOLEAN = "boolean"
    DATETIME = "datetime"
    BYTEA = "bytea"
    ARRAY = "array"
    STRUCT = "struct"
    UNKNOWN = "unknown"  # schemaless column


NUMERIC_TYPES = {DataType.BIGINT, DataType.FLOAT, DataType.BOOLEAN, DataType.DATETIME}

_NP_DTYPES = {
    DataType.BIGINT: np.int64,
    DataType.FLOAT: np.float32,
    DataType.BOOLEAN: np.bool_,
    DataType.DATETIME: np.int64,  # epoch ms
}


def np_dtype(dt: DataType):
    """numpy dtype for device-eligible columns; object for host columns."""
    return _NP_DTYPES.get(dt, np.object_)


@dataclass
class Field:
    name: str
    type: DataType = DataType.UNKNOWN
    # nested element/field types for ARRAY/STRUCT columns
    elem_type: Optional["DataType"] = None
    fields: Optional[List["Field"]] = None

    @property
    def device_eligible(self) -> bool:
        return self.type in NUMERIC_TYPES

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "type": self.type.value}
        if self.elem_type is not None:
            d["elem_type"] = self.elem_type.value
        if self.fields is not None:
            d["fields"] = [f.to_dict() for f in self.fields]
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Field":
        return Field(
            name=d["name"],
            type=DataType(d.get("type", "unknown")),
            elem_type=DataType(d["elem_type"]) if d.get("elem_type") else None,
            fields=[Field.from_dict(f) for f in d["fields"]] if d.get("fields") else None,
        )


@dataclass
class Schema:
    """Ordered field list. Empty fields = schemaless stream."""

    fields: List[Field] = field(default_factory=list)

    @property
    def schemaless(self) -> bool:
        return len(self.fields) == 0

    def field_names(self) -> List[str]:
        return [f.name for f in self.fields]

    def get(self, name: str) -> Optional[Field]:
        for f in self.fields:
            if f.name == name:
                return f
        return None

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        return -1

    def to_dict(self) -> Dict[str, Any]:
        return {"fields": [f.to_dict() for f in self.fields]}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Schema":
        return Schema(fields=[Field.from_dict(f) for f in d.get("fields", [])])
