"""Row & collection data model — analogue of eKuiper's internal/xsql row model:
Tuple (map row + metadata + alias overlay, internal/xsql/row.go:319), JoinTuple
(row.go:355), WindowTuples / GroupedTuples collections
(internal/xsql/collection.go:40-109).

These are the *control-path* representations: per-row objects used by the
interpreter fallback, joins, and sinks. The hot path converts runs of Tuples
into a columnar ColumnBatch (see batch.py) before touching the device.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple as PyTuple


class Row:
    """Interface: anything the expression evaluator can read values from."""

    def value(self, key: str, table: str = "") -> PyTuple[Any, bool]:
        raise NotImplementedError

    def all_values(self) -> Dict[str, Any]:
        raise NotImplementedError

    def set_cal_col(self, key: str, value: Any) -> None:
        raise NotImplementedError


@dataclass
class Tuple(Row):
    """One event. `message` is the decoded payload; `cal_cols` is the
    alias/computed-column overlay (analogue of AffiliateRow, row.go:105)."""

    emitter: str = ""
    message: Dict[str, Any] = field(default_factory=dict)
    timestamp: int = 0  # ms; ingest time, replaced by event time when configured
    metadata: Dict[str, Any] = field(default_factory=dict)
    cal_cols: Dict[str, Any] = field(default_factory=dict)

    def value(self, key: str, table: str = "") -> PyTuple[Any, bool]:
        if table and table != self.emitter:
            return None, False
        if key in self.cal_cols:
            return self.cal_cols[key], True
        if key in self.message:
            return self.message[key], True
        return None, False

    def all_values(self) -> Dict[str, Any]:
        out = dict(self.message)
        out.update(self.cal_cols)
        return out

    def meta(self, key: str) -> PyTuple[Any, bool]:
        if key in self.metadata:
            return self.metadata[key], True
        return None, False

    def set_cal_col(self, key: str, value: Any) -> None:
        self.cal_cols[key] = value

    def clone(self) -> "Tuple":
        return Tuple(
            emitter=self.emitter,
            message=copy.copy(self.message),
            timestamp=self.timestamp,
            metadata=copy.copy(self.metadata),
            cal_cols=copy.copy(self.cal_cols),
        )


@dataclass
class JoinTuple(Row):
    """Merged row from a join: ordered (emitter, Tuple) pairs
    (analogue of internal/xsql/row.go:355)."""

    tuples: List[Tuple] = field(default_factory=list)
    cal_cols: Dict[str, Any] = field(default_factory=dict)

    @property
    def timestamp(self) -> int:
        return max((t.timestamp for t in self.tuples), default=0)

    def add(self, t: Tuple) -> None:
        self.tuples.append(t)

    def value(self, key: str, table: str = "") -> PyTuple[Any, bool]:
        if key in self.cal_cols:
            return self.cal_cols[key], True
        if table:
            for t in self.tuples:
                if t.emitter == table:
                    return t.value(key)
            return None, False
        for t in self.tuples:
            v, ok = t.value(key)
            if ok:
                return v, True
        return None, False

    def all_values(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for t in reversed(self.tuples):
            out.update(t.all_values())
        out.update(self.cal_cols)
        return out

    def set_cal_col(self, key: str, value: Any) -> None:
        self.cal_cols[key] = value

    def clone(self) -> "JoinTuple":
        return JoinTuple(
            tuples=[t.clone() for t in self.tuples], cal_cols=copy.copy(self.cal_cols)
        )


@dataclass
class WindowRange:
    """Window bounds attached to emitted collections; feeds window_start()/
    window_end() SQL functions (reference: internal/xsql window range)."""

    window_start: int = 0
    window_end: int = 0


class Collection:
    """Interface for multi-row results flowing between operators."""

    def rows(self) -> List[Row]:
        raise NotImplementedError

    def __len__(self) -> int:
        return len(self.rows())

    def __iter__(self) -> Iterator[Row]:
        return iter(self.rows())


@dataclass
class WindowTuples(Collection):
    """All rows of one triggered window (analogue collection.go:70)."""

    content: List[Row] = field(default_factory=list)
    window_range: Optional[WindowRange] = None

    def rows(self) -> List[Row]:
        return self.content

    # acts as the aggregate context for ungrouped agg queries: non-agg
    # columns read from the first row (reference semantics)
    def value(self, key: str, table: str = "") -> PyTuple[Any, bool]:
        if self.content:
            return self.content[0].value(key, table)
        return None, False

    def all_values(self) -> Dict[str, Any]:
        return self.content[0].all_values() if self.content else {}


@dataclass
class GroupedTuples(Collection):
    """One GROUP BY group: rows + shared group key
    (analogue internal/xsql/row.go:374)."""

    content: List[Row] = field(default_factory=list)
    group_key: str = ""
    window_range: Optional[WindowRange] = None
    cal_cols: Dict[str, Any] = field(default_factory=dict)
    # precomputed aggregate results by call key — filled by the device kernel
    # path so the evaluator skips per-group recomputation
    agg_values: Dict[str, Any] = field(default_factory=dict)

    def rows(self) -> List[Row]:
        return self.content

    # GroupedTuples acts as a Row for post-agg operators (HAVING/project read
    # both agg results and the first row's columns).
    def value(self, key: str, table: str = "") -> PyTuple[Any, bool]:
        if key in self.cal_cols:
            return self.cal_cols[key], True
        if self.content:
            return self.content[0].value(key, table)
        return None, False

    def all_values(self) -> Dict[str, Any]:
        out = self.content[0].all_values() if self.content else {}
        out.update(self.cal_cols)
        return out

    def set_cal_col(self, key: str, value: Any) -> None:
        self.cal_cols[key] = value


@dataclass
class GroupedTuplesSet(Collection):
    """All groups of one window/batch (analogue collection.go:109)."""

    groups: List[GroupedTuples] = field(default_factory=list)
    window_range: Optional[WindowRange] = None

    def rows(self) -> List[Row]:
        return list(self.groups)
