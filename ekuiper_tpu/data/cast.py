"""Type coercion — analogue of eKuiper's pkg/cast/cast.go (1234 LoC).

The reference coerces arbitrary decoded JSON values to schema types with two
strictness levels (STRICT vs CONVERT_ALL); the preprocessor op applies it per
field (reference: internal/topo/operator/preprocessor.go). We mirror the
semantics that matter for SQL behavior: numeric cross-casts, string parsing,
bool ints, datetime from ISO strings / epoch numbers.
"""
from __future__ import annotations

import datetime as _dt
from typing import Any, List, Optional

from .types import DataType, Field

STRICT = "strict"
CONVERT_ALL = "convert_all"


class CastError(ValueError):
    pass


def to_int(v: Any, strict: str = CONVERT_ALL) -> int:
    if isinstance(v, bool):
        if strict == STRICT:
            raise CastError(f"cannot cast bool {v} to bigint strictly")
        return 1 if v else 0
    if isinstance(v, int):
        return v
    if isinstance(v, float):
        if strict == STRICT and not float(v).is_integer():
            raise CastError(f"cannot cast float {v} to bigint strictly")
        return int(v)
    if isinstance(v, str) and strict != STRICT:
        try:
            return int(float(v)) if ("." in v or "e" in v.lower()) else int(v)
        except ValueError as e:
            raise CastError(f"cannot cast string {v!r} to bigint") from e
    raise CastError(f"cannot cast {type(v).__name__} {v!r} to bigint")


def to_float(v: Any, strict: str = CONVERT_ALL) -> float:
    if isinstance(v, bool):
        if strict == STRICT:
            raise CastError(f"cannot cast bool {v} to float strictly")
        return 1.0 if v else 0.0
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str) and strict != STRICT:
        try:
            return float(v)
        except ValueError as e:
            raise CastError(f"cannot cast string {v!r} to float") from e
    raise CastError(f"cannot cast {type(v).__name__} {v!r} to float")


def to_bool(v: Any, strict: str = CONVERT_ALL) -> bool:
    if isinstance(v, bool):
        return v
    if strict != STRICT:
        if isinstance(v, (int, float)) and v in (0, 1):
            return bool(v)
        if isinstance(v, str):
            low = v.lower()
            if low in ("true", "1"):
                return True
            if low in ("false", "0"):
                return False
    raise CastError(f"cannot cast {type(v).__name__} {v!r} to boolean")


def to_string(v: Any, strict: str = CONVERT_ALL) -> str:
    if isinstance(v, str):
        return v
    if strict == STRICT:
        raise CastError(f"cannot cast {type(v).__name__} {v!r} to string strictly")
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, bytes):
        return v.decode("utf-8", errors="replace")
    if isinstance(v, float) and float(v).is_integer():
        return str(int(v))
    return str(v)


def to_bytes(v: Any, strict: str = CONVERT_ALL) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, str) and strict != STRICT:
        return v.encode("utf-8")
    raise CastError(f"cannot cast {type(v).__name__} {v!r} to bytea")


_ISO_FORMATS = (
    "%Y-%m-%dT%H:%M:%S.%f%z",
    "%Y-%m-%dT%H:%M:%S%z",
    "%Y-%m-%dT%H:%M:%S.%fZ",
    "%Y-%m-%dT%H:%M:%SZ",
    "%Y-%m-%dT%H:%M:%S.%f",
    "%Y-%m-%dT%H:%M:%S",
    "%Y-%m-%d %H:%M:%S.%f",
    "%Y-%m-%d %H:%M:%S",
    "%Y-%m-%d",
)


def to_datetime_ms(v: Any, strict: str = CONVERT_ALL) -> int:
    """Coerce to epoch milliseconds (the engine-wide time representation)."""
    if isinstance(v, bool):
        raise CastError("cannot cast bool to datetime")
    if isinstance(v, (int, float)):
        return int(v)
    if isinstance(v, _dt.datetime):
        if v.tzinfo is None:
            v = v.replace(tzinfo=_dt.timezone.utc)
        return int(v.timestamp() * 1000)
    if isinstance(v, str):
        for fmt in _ISO_FORMATS:
            try:
                parsed = _dt.datetime.strptime(v, fmt)
                if parsed.tzinfo is None:
                    parsed = parsed.replace(tzinfo=_dt.timezone.utc)
                return int(parsed.timestamp() * 1000)
            except ValueError:
                continue
        try:
            return int(float(v))
        except ValueError:
            pass
    raise CastError(f"cannot cast {type(v).__name__} {v!r} to datetime")


def to_typed(v: Any, f: Field, strict: str = CONVERT_ALL) -> Any:
    """Coerce a decoded value to a schema field's type."""
    if v is None:
        return None
    t = f.type
    if t in (DataType.UNKNOWN,):
        return v
    if t == DataType.BIGINT:
        return to_int(v, strict)
    if t == DataType.FLOAT:
        return to_float(v, strict)
    if t == DataType.STRING:
        return to_string(v, strict)
    if t == DataType.BOOLEAN:
        return to_bool(v, strict)
    if t == DataType.DATETIME:
        return to_datetime_ms(v, strict)
    if t == DataType.BYTEA:
        return to_bytes(v, strict)
    if t == DataType.ARRAY:
        if not isinstance(v, (list, tuple)):
            raise CastError(f"cannot cast {type(v).__name__} to array")
        if f.elem_type is not None and f.elem_type != DataType.UNKNOWN:
            elem_field = Field(name=f.name, type=f.elem_type)
            return [to_typed(x, elem_field, strict) for x in v]
        return list(v)
    if t == DataType.STRUCT:
        if not isinstance(v, dict):
            raise CastError(f"cannot cast {type(v).__name__} to struct")
        if f.fields:
            out = {}
            for sub in f.fields:
                if sub.name in v:
                    out[sub.name] = to_typed(v[sub.name], sub, strict)
            return out
        return dict(v)
    raise CastError(f"unknown target type {t}")


def compare(a: Any, b: Any) -> Optional[int]:
    """Three-way compare with eKuiper-style cross-type numeric comparison.
    Returns None for incomparable (NULL-ish) pairs."""
    if a is None or b is None:
        return None
    if isinstance(a, bool) or isinstance(b, bool):
        if isinstance(a, bool) and isinstance(b, bool):
            return (a > b) - (a < b)
        return None
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return (a > b) - (a < b)
    if isinstance(a, str) and isinstance(b, str):
        return (a > b) - (a < b)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        for x, y in zip(a, b):
            c = compare(x, y)
            if c is None or c != 0:
                return c
        return (len(a) > len(b)) - (len(a) < len(b))
    return None
