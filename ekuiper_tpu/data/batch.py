"""Columnar micro-batch — the TPU-native data representation.

The reference's experimental SliceTuple (internal/xsql/slice_tuple.go:25,
planner index assignment planner.go:88-165) replaces map rows with
index-addressed slices; this module completes that direction: runs of events
become a struct-of-arrays ColumnBatch whose numeric columns upload to device
HBM as jnp arrays, so window/aggregate kernels run vectorized on the VPU/MXU
instead of per-row interpreter walks (the hot loop at internal/xsql/valuer.go:289).

String columns stay host-side; GROUP BY keys are dictionary-encoded to int32
slot ids by the key table (ops/keytable.py) before device upload.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import threading as _threading

from .rows import Tuple
from .types import DataType, Schema, np_dtype


@dataclass
class ColumnBatch:
    """Struct-of-arrays batch. All columns have equal length `n`.

    - numeric columns: np.float32 / np.int64 / np.bool_
    - host columns (strings, arrays, structs, schemaless): dtype=object
    - `valid[name]`: optional bool mask (absent = all valid)
    - `timestamps`: int64 ms (event time when configured, else ingest time)
    """

    n: int
    columns: Dict[str, np.ndarray] = field(default_factory=dict)
    valid: Dict[str, np.ndarray] = field(default_factory=dict)
    timestamps: Optional[np.ndarray] = None
    emitter: str = ""
    # shared-source fan-out: N consumers of the SAME batch share one key
    # encode and one device upload per column (see runtime/subtopo.py
    # SharedPrepCtx). `share()` memoizes per-batch; pruned copies made by
    # SharedEntryNode carry these references so all riders hit one cache.
    shared_ctx: Any = None
    share_state: Optional[Dict[Any, Any]] = None
    # ingest wall time (engine clock, ms) of the batch's oldest row —
    # stamped at the source, carried through every hop so emit/sink nodes
    # can record true ingest→emit latency (observability/histogram.py)
    ingest_ms: Optional[int] = None

    # unannotated -> a plain class attribute, not a dataclass field
    _SHARE_INIT_LOCK = _threading.Lock()

    def ensure_share_state(self) -> Dict[Any, Any]:
        state = self.share_state
        if state is None:
            with ColumnBatch._SHARE_INIT_LOCK:
                state = self.share_state
                if state is None:
                    state = self.share_state = {
                        "__lock__": _threading.RLock()}
        return state

    def __getstate__(self) -> dict:
        # the share cache (lock + device arrays) and subtopo ctx are
        # per-process ephemera — drop them so batches stay picklable
        # (sink-cache disk spill pickles parked items)
        state = self.__dict__.copy()
        state["shared_ctx"] = None
        state["share_state"] = None
        return state

    def share(self, key: Any, factory) -> Any:
        """Memoize `factory()` under `key` for every consumer of this batch
        (and its pruned copies). First caller computes; the per-batch lock
        makes concurrent consumers wait instead of duplicating the work."""
        state = self.ensure_share_state()
        with state["__lock__"]:
            if key not in state:
                state[key] = factory()
            return state[key]

    def __len__(self) -> int:
        return self.n

    def names(self) -> List[str]:
        return list(self.columns.keys())

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]

    def is_valid(self, name: str) -> np.ndarray:
        v = self.valid.get(name)
        if v is None:
            return np.ones(self.n, dtype=np.bool_)
        return v

    def numeric_names(self) -> List[str]:
        return [k for k, v in self.columns.items() if v.dtype != np.object_]

    def select(self, mask: np.ndarray) -> "ColumnBatch":
        idx = np.nonzero(mask)[0]
        return self.take(idx)

    def take(self, idx: np.ndarray) -> "ColumnBatch":
        return ColumnBatch(
            n=len(idx),
            columns={k: v[idx] for k, v in self.columns.items()},
            valid={k: v[idx] for k, v in self.valid.items()},
            timestamps=None if self.timestamps is None else self.timestamps[idx],
            emitter=self.emitter,
            ingest_ms=self.ingest_ms,
        )

    def to_tuples(self) -> List[Tuple]:
        """Back to row objects (sink/interpreter path)."""
        out: List[Tuple] = []
        names = self.names()
        cols = [self.columns[k] for k in names]
        valids = [self.valid.get(k) for k in names]
        ts = self.timestamps
        for i in range(self.n):
            msg: Dict[str, Any] = {}
            for name, col, v in zip(names, cols, valids):
                if v is not None and not v[i]:
                    continue
                val = col[i]
                if isinstance(val, np.generic):
                    val = val.item()
                msg[name] = val
            out.append(
                Tuple(
                    emitter=self.emitter,
                    message=msg,
                    timestamp=int(ts[i]) if ts is not None else 0,
                )
            )
        return out

    @staticmethod
    def concat(batches: Sequence["ColumnBatch"]) -> "ColumnBatch":
        batches = [b for b in batches if b.n > 0]
        if not batches:
            return ColumnBatch(n=0)
        if len(batches) == 1:
            return batches[0]
        names: List[str] = []
        for b in batches:
            for k in b.columns:
                if k not in names:
                    names.append(k)
        n_total = sum(b.n for b in batches)
        columns: Dict[str, np.ndarray] = {}
        valid: Dict[str, np.ndarray] = {}
        for name in names:
            parts, vparts, need_valid = [], [], False
            for b in batches:
                col = b.columns.get(name)
                if col is None:
                    dtype = np.object_
                    for ob in batches:
                        if name in ob.columns:
                            dtype = ob.columns[name].dtype
                            break
                    col = np.zeros(b.n, dtype=dtype)
                    vp = np.zeros(b.n, dtype=np.bool_)
                    need_valid = True
                else:
                    vp = b.valid.get(name)
                    if vp is None:
                        vp = np.ones(b.n, dtype=np.bool_)
                    else:
                        need_valid = need_valid or not vp.all()
                parts.append(col)
                vparts.append(vp)
            columns[name] = np.concatenate(parts)
            if need_valid:
                valid[name] = np.concatenate(vparts)
        ts = None
        if all(b.timestamps is not None for b in batches):
            ts = np.concatenate([b.timestamps for b in batches])
        ings = [b.ingest_ms for b in batches if b.ingest_ms is not None]
        return ColumnBatch(
            n=n_total, columns=columns, valid=valid, timestamps=ts,
            emitter=batches[0].emitter,
            ingest_ms=min(ings) if ings else None,
        )


def from_tuples(
    tuples: Sequence[Tuple], schema: Optional[Schema] = None, emitter: str = ""
) -> ColumnBatch:
    """Columnarize a run of rows. With a schema, columns get typed numpy
    dtypes; schemaless columns are inferred from observed python types
    (promoted to object on conflict)."""
    n = len(tuples)
    if n == 0:
        return ColumnBatch(n=0, emitter=emitter)

    names: List[str] = []
    declared: Dict[str, Any] = {}
    if schema is not None and not schema.schemaless:
        for f in schema.fields:
            names.append(f.name)
            declared[f.name] = np_dtype(f.type)
    else:
        seen = set()
        for t in tuples:
            for k in t.message:
                if k not in seen:
                    seen.add(k)
                    names.append(k)

    columns: Dict[str, np.ndarray] = {}
    valid: Dict[str, np.ndarray] = {}
    for name in names:
        raw = [t.message.get(name) for t in tuples]
        mask = np.array([r is not None for r in raw], dtype=np.bool_)
        dtype = declared.get(name)
        if dtype is None:
            dtype = _infer_dtype(raw, mask)
        if dtype == np.object_:
            col = np.empty(n, dtype=np.object_)
            col[:] = raw
        else:
            col = np.zeros(n, dtype=dtype)
            if mask.all():
                try:
                    col[:] = raw
                except (ValueError, TypeError, OverflowError):
                    col = np.empty(n, dtype=np.object_)
                    col[:] = raw
                    dtype = np.object_
            else:
                for i, r in enumerate(raw):
                    if mask[i]:
                        try:
                            col[i] = r
                        except (ValueError, TypeError, OverflowError):
                            mask[i] = False
                if dtype == np.float32:
                    col[~mask] = np.nan
        columns[name] = col
        if not mask.all():
            valid[name] = mask

    ts = np.fromiter((t.timestamp for t in tuples), dtype=np.int64, count=n)
    return ColumnBatch(n=n, columns=columns, valid=valid, timestamps=ts, emitter=emitter)


def from_messages(
    msgs: List[Dict[str, Any]],
    tss: List[int],
    schema: Optional[Schema] = None,
    emitter: str = "",
    strict: str = "convert_all",
    timestamp_field: str = "",
    on_error=None,
    project: Optional[set] = None,
):
    """Columnarize decoded messages DIRECTLY — no per-row Tuple objects, no
    per-row preprocessor. This is the vectorized twin of SourceNode's
    ingest→preprocess→from_tuples chain (reference: per-tuple decode_op +
    preprocessor.Apply, internal/topo/operator/preprocessor.go): schema
    coercion runs per COLUMN (bulk numpy assignment when a C-speed type scan
    proves the payload conforms; per-value cast.to_typed fallback otherwise)
    and event-time extraction is one vectorized pass.

    Returns (ColumnBatch, n_dropped). Rows whose cast or timestamp fails
    drop, mirroring the row-path contract; on_error(msg, n) reports them.
    """
    from . import cast as _cast
    from .types import DataType

    n = len(msgs)
    if n == 0:
        return ColumnBatch(n=0, emitter=emitter), 0
    bad = np.zeros(n, dtype=np.bool_)
    columns: Dict[str, np.ndarray] = {}
    valid: Dict[str, np.ndarray] = {}
    if schema is not None and not schema.schemaless:
        for f in schema.fields:
            raw = [m.get(f.name) for m in msgs]
            mask = np.fromiter(
                (r is not None for r in raw), dtype=np.bool_, count=n)
            col = None
            if f.type == DataType.BIGINT:
                if all(r is None or type(r) is int for r in raw):
                    col = np.zeros(n, dtype=np.int64)
            elif f.type == DataType.FLOAT:
                if all(r is None or type(r) in (int, float) for r in raw):
                    col = np.zeros(n, dtype=np.float32)
            elif f.type == DataType.BOOLEAN:
                if all(r is None or type(r) is bool for r in raw):
                    col = np.zeros(n, dtype=np.bool_)
            elif f.type == DataType.STRING:
                if all(r is None or type(r) is str for r in raw):
                    col = np.empty(n, dtype=np.object_)
                    col[:] = raw
            if col is not None and col.dtype != np.object_:
                try:
                    if mask.all():
                        col[:] = raw
                    else:
                        idx = np.nonzero(mask)[0]
                        col[idx] = [raw[i] for i in idx.tolist()]
                        if col.dtype == np.float32:
                            col[~mask] = np.nan
                except (ValueError, TypeError, OverflowError):
                    col = None  # e.g. ints beyond int64 — cast fallback
            if col is None:
                # non-conforming payload (strings-as-numbers, datetimes,
                # arrays/structs): per-value cast, same rules as the row path
                col = np.empty(n, dtype=np.object_)
                for i, r in enumerate(raw):
                    if r is None:
                        continue
                    try:
                        col[i] = _cast.to_typed(r, f, strict)
                    except _cast.CastError as exc:
                        bad[i] = True
                        if on_error is not None:
                            on_error(str(exc), 1)
                tgt = np_dtype(f.type)
                if tgt != np.object_:
                    # retighten to the declared dtype when every good row
                    # coerced cleanly (device-eligible upload path)
                    good = mask & ~bad
                    tight = np.zeros(n, dtype=tgt)
                    try:
                        idx = np.nonzero(good)[0]
                        tight[idx] = [col[i] for i in idx.tolist()]
                        if tgt == np.float32:
                            tight[~good] = np.nan
                        col = tight
                    except (ValueError, TypeError, OverflowError):
                        pass
            columns[f.name] = col
            if not mask.all():
                valid[f.name] = mask & ~bad
    else:
        names: List[str] = []
        seen = set()
        for m in msgs:
            for k in m:
                if k not in seen:
                    seen.add(k)
                    if project is None or k in project:
                        names.append(k)
        for name in names:
            raw = [m.get(name) for m in msgs]
            mask = np.fromiter(
                (r is not None for r in raw), dtype=np.bool_, count=n)
            dtype = _infer_dtype(raw, mask)
            if dtype == np.object_:
                col = np.empty(n, dtype=np.object_)
                col[:] = raw
            else:
                col = np.zeros(n, dtype=dtype)
                if mask.all():
                    col[:] = raw
                else:
                    idx = np.nonzero(mask)[0]
                    col[idx] = [raw[i] for i in idx.tolist()]
                    if dtype == np.float32:
                        col[~mask] = np.nan
            columns[name] = col
            if not mask.all():
                valid[name] = mask
    ts = np.asarray(tss, dtype=np.int64)
    if timestamp_field:
        raw = columns.get(timestamp_field)
        if raw is not None and raw.dtype == np.int64 \
                and timestamp_field not in valid and not bad.any():
            # int64 column (BIGINT/DATETIME): exact epoch-ms passthrough.
            # Other shapes take the per-value path over the RAW message
            # values (a float32 column can't hold epoch ms exactly).
            ts = raw
        else:
            vm = valid.get(timestamp_field)
            ts = ts.copy()
            for i, m in enumerate(msgs):
                if bad[i]:
                    continue
                r = m.get(timestamp_field)
                if r is None or (vm is not None and not vm[i]):
                    bad[i] = True
                    if on_error is not None:
                        on_error(
                            f"missing timestamp field {timestamp_field}", 1)
                    continue
                try:
                    ts[i] = _cast.to_datetime_ms(r)
                except (_cast.CastError, ValueError, TypeError) as exc:
                    bad[i] = True
                    if on_error is not None:
                        on_error(str(exc), 1)
    n_drop = int(bad.sum())
    cb = ColumnBatch(n=n, columns=columns, valid=valid, timestamps=ts,
                     emitter=emitter)
    if n_drop:
        cb = cb.select(~bad)
    return cb, n_drop


def _infer_dtype(raw: List[Any], mask: np.ndarray):
    saw_float = saw_int = saw_bool = saw_other = False
    for r, ok in zip(raw, mask):
        if not ok:
            continue
        if isinstance(r, bool):
            saw_bool = True
        elif isinstance(r, int):
            saw_int = True
        elif isinstance(r, float):
            saw_float = True
        else:
            saw_other = True
    if saw_other:
        return np.object_
    if saw_bool and (saw_int or saw_float):
        # don't silently coerce True/False into 1/1.0 — keep originals
        return np.object_
    if saw_float:
        return np.float32
    if saw_int:
        return np.int64
    if saw_bool:
        return np.bool_
    return np.object_
