"""Panic isolation & error draining — analogue of eKuiper's pkg/infra/saferun.go.

Every runtime-node thread body is wrapped in `safe_run` so a bug in one
operator never takes down the process; the error is recovered and forwarded to
the rule's drain channel, exactly like infra.SafeRun / infra.DrainError
(reference: pkg/infra/saferun.go:34,57).
"""
from __future__ import annotations

import logging
import traceback
from typing import Callable, Optional

logger = logging.getLogger("ekuiper_tpu")


class EngineError(Exception):
    """Base class for engine errors."""


class ParseError(EngineError):
    pass


class PlanError(EngineError):
    pass


class RuntimeError_(EngineError):
    pass


def safe_run(fn: Callable[[], Optional[BaseException]]) -> Optional[BaseException]:
    """Run fn, converting any raised exception into a returned error."""
    try:
        return fn()
    except BaseException as exc:  # noqa: BLE001 - this is the recover point
        logger.debug("safe_run recovered: %s\n%s", exc, traceback.format_exc())
        return exc


def drain_error(err: Optional[BaseException], errq) -> None:
    """Forward err to an error queue without blocking if it is full."""
    if err is None:
        return
    try:
        errq.put_nowait(err)
    except Exception:  # queue full — an error is already being handled
        pass
