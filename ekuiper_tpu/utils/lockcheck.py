"""Dynamic lock-order checker — the runtime twin of kuiperlint's static
`lock-order` pass.

When installed (tests do it via conftest; KUIPER_LOCKCHECK=0 opts out),
`threading.Lock`/`RLock`/`Condition` allocated from ekuiper_tpu code are
wrapped in a tracking proxy that records, per thread, the ACQUISITION
ORDER actually exercised: taking lock B while holding lock A adds the
edge A→B to a process-global graph keyed by each lock's allocation site
(file:line — every instance of a class shares its lock's site, which is
exactly the granularity ordering rules are written at). `check()` runs
cycle detection over the accumulated graph; the per-test teardown in
tests/conftest.py asserts it stays empty, so the test that closes an
ABBA cycle is the test that fails.

The static pass sees paths tests never schedule; this checker sees
orders the AST can't resolve (callbacks, dynamic dispatch). Together
they cover the PR 6 clock/stats inversion class from both sides.

Design notes:
 * Only locks created from ekuiper_tpu modules are tracked — stdlib
   internals (queue, threading.Condition's implicit RLock) keep vanilla
   locks, so overhead lands on engine locks only (~1µs/acquire).
 * Condition.wait() releases the underlying lock: the proxy implements
   `_release_save`/`_acquire_restore`/`_is_owned` so the held-set
   bookkeeping tracks the real ownership through waits.
 * Same-site edges are skipped: RLock reentry and sibling instances of
   one class are not ordering violations.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

_state_lock = _ORIG_LOCK()  # guards _edges; never held while blocking
_edges: Dict[Tuple[str, str], str] = {}  # (held_site, new_site) -> witness
_tls = threading.local()
_installed = False


def _held() -> List[list]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


class _TrackedLock:
    """Proxy over a real lock carrying its allocation site."""

    __slots__ = ("_inner", "site", "_reentrant")

    def __init__(self, inner, site: str, reentrant: bool) -> None:
        self._inner = inner
        self.site = site
        self._reentrant = reentrant

    # ------------------------------------------------------- acquire path
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._note_acquire()
        return ok

    def release(self) -> None:
        self._note_release()
        self._inner.release()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # --------------------------------------- Condition(lock) integration
    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # plain Lock: CPython's own Condition fallback probe
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        depth = self._forget()
        if hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), depth)
        self._inner.release()
        return (None, depth)

    def _acquire_restore(self, state) -> None:
        inner_state, depth = state
        if inner_state is not None:
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        self._note_acquire(depth=depth)

    # ------------------------------------------------------- bookkeeping
    def _note_acquire(self, depth: int = 1) -> None:
        held = _held()
        if self._reentrant:
            for entry in held:
                if entry[0] is self:
                    entry[1] += depth
                    return
        new_edges = [(e[0].site, self.site) for e in held
                     if e[0].site != self.site]
        held.append([self, depth])
        if new_edges:
            tname = threading.current_thread().name
            witness = f"thread {tname}"
            if os.environ.get("KUIPER_LOCKCHECK_TRACE"):
                # debugging aid: record WHERE the edge was exercised so a
                # cycle report points at code, not just allocation sites
                import traceback

                frames = [f"{f.filename.rsplit('/', 1)[-1]}:{f.lineno}"
                          for f in traceback.extract_stack()[-8:-2]]
                witness += " via " + " > ".join(frames)
            with _state_lock:
                for edge in new_edges:
                    _edges.setdefault(edge, witness)

    def _note_release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                held[i][1] -= 1
                if held[i][1] <= 0:
                    del held[i]
                return
        # released on a thread that never noted the acquire (e.g. lock
        # handed across threads): nothing to unwind

    def _forget(self) -> int:
        """Drop this lock from the held set entirely (Condition.wait);
        returns the reentry depth to restore afterwards."""
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                depth = held[i][1]
                del held[i]
                return depth
        return 1


def _site_of(frame) -> str:
    fn = frame.f_code.co_filename
    parts = fn.replace(os.sep, "/").rsplit("/", 2)
    return f"{'/'.join(parts[-2:])}:{frame.f_lineno}"


def _make_factory(orig, reentrant: bool):
    def factory():
        import sys

        inner = orig()
        frame = sys._getframe(1)
        if "ekuiper_tpu" not in frame.f_code.co_filename:
            return inner  # stdlib/third-party allocation: stay vanilla
        return _TrackedLock(inner, _site_of(frame), reentrant)

    return factory


def install() -> None:
    """Patch threading's lock factories; idempotent."""
    global _installed
    if _installed:
        return
    threading.Lock = _make_factory(_ORIG_LOCK, reentrant=False)
    threading.RLock = _make_factory(_ORIG_RLOCK, reentrant=True)
    _installed = True


def uninstall() -> None:
    global _installed
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    _installed = False


def installed() -> bool:
    return _installed


def reset() -> None:
    with _state_lock:
        _edges.clear()


def edges() -> Dict[Tuple[str, str], str]:
    with _state_lock:
        return dict(_edges)


def check() -> List[str]:
    """Cycle-check the accumulated acquisition graph. Returns one
    human-readable description per cycle (empty == ordering is sound)."""
    with _state_lock:
        snapshot = dict(_edges)
    graph: Dict[str, set] = {}
    for (a, b) in snapshot:
        graph.setdefault(a, set()).add(b)

    out: List[str] = []
    visiting: List[str] = []
    state: Dict[str, int] = {}  # 0 unseen / 1 on stack / 2 done
    reported = set()

    def dfs(v: str) -> None:
        state[v] = 1
        visiting.append(v)
        for w in sorted(graph.get(v, ())):
            if state.get(w, 0) == 1:
                cycle = tuple(visiting[visiting.index(w):] + [w])
                if cycle not in reported:
                    reported.add(cycle)
                    wit = "; ".join(
                        f"{x}->{y} ({snapshot.get((x, y), '?')})"
                        for x, y in zip(cycle, cycle[1:]))
                    out.append("lock-order cycle: " + " -> ".join(cycle)
                               + f" [{wit}]")
            elif state.get(w, 0) == 0:
                dfs(w)
        visiting.pop()
        state[v] = 2

    for v in sorted(graph):
        if state.get(v, 0) == 0:
            dfs(v)
    return out
