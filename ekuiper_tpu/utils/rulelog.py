"""Per-rule log files (analogue of the reference's rule-scoped loggers,
conf.Log + rule logToDisk): every engine log record produced while a
rule-owned thread is running is ALSO appended to data/logs/<rule>.log.

The engine's components log through one shared logger; rule attribution
rides a thread-local set by the threads a rule owns (node workers, the rule
FSM worker, supervisors). Opt-in via basic.rule_log_enabled."""
from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional, TextIO

_ctx = threading.local()


def set_rule_context(rule_id: Optional[str]) -> None:
    _ctx.rule_id = rule_id


def current_rule() -> Optional[str]:
    return getattr(_ctx, "rule_id", None)


class RuleLogRouter(logging.Handler):
    #: open handles kept; beyond this the least-recently-used file closes
    #: (rule churn must not leak fds)
    MAX_OPEN_FILES = 32

    def __init__(self, log_dir: str) -> None:
        super().__init__()
        self.log_dir = log_dir
        self._files: Dict[str, TextIO] = {}  # insertion order = LRU
        self._lock = threading.Lock()
        self.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(message)s"))

    @staticmethod
    def _filename(rule_id: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in rule_id)
        if safe != rule_id:
            # distinct ids must not collide after sanitization
            import hashlib

            safe += "-" + hashlib.sha1(rule_id.encode()).hexdigest()[:8]
        return f"{safe}.log"

    def emit(self, record: logging.LogRecord) -> None:
        rule_id = current_rule()
        if not rule_id:
            return
        try:
            line = self.format(record)
            with self._lock:
                f = self._files.pop(rule_id, None)
                if f is None:
                    os.makedirs(self.log_dir, exist_ok=True)
                    f = open(os.path.join(
                        self.log_dir, self._filename(rule_id)), "a")
                self._files[rule_id] = f  # re-insert = most recently used
                while len(self._files) > self.MAX_OPEN_FILES:
                    oldest = next(iter(self._files))
                    try:
                        self._files.pop(oldest).close()
                    except Exception:
                        pass
                f.write(line + "\n")
                f.flush()
        except Exception:
            self.handleError(record)

    def close(self) -> None:
        with self._lock:
            for f in self._files.values():
                try:
                    f.close()
                except Exception:
                    pass
            self._files.clear()
        super().close()


_router: Optional[RuleLogRouter] = None
_install_lock = threading.Lock()


def install(log_dir: str) -> RuleLogRouter:
    """Attach the router to the engine logger (idempotent; re-targets the
    directory on re-install)."""
    from .infra import logger

    global _router
    with _install_lock:
        if _router is not None:
            logger.removeHandler(_router)
            _router.close()
        _router = RuleLogRouter(log_dir)
        logger.addHandler(_router)
        return _router


def uninstall() -> None:
    from .infra import logger

    global _router
    with _install_lock:
        if _router is not None:
            logger.removeHandler(_router)
            _router.close()
            _router = None
