"""Cron expressions + Go-style durations for scheduled rules
(analogue of the reference's robfig/cron usage in internal/pkg/schedule).

Standard 5-field cron (minute hour day-of-month month day-of-week) with
lists, ranges, and steps. Matching follows vixie-cron semantics: when both
day-of-month and day-of-week are restricted, a date matches if EITHER does.
All computation is in local time via the engine clock (mock-testable).
"""
from __future__ import annotations

import re
import time
from typing import List, Optional, Set, Tuple

from .infra import EngineError

_FIELD_RANGES = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))
_MONTH_NAMES = {m: i + 1 for i, m in enumerate(
    "jan feb mar apr may jun jul aug sep oct nov dec".split())}
_DOW_NAMES = {d: i for i, d in enumerate(
    "sun mon tue wed thu fri sat".split())}


def _parse_field(spec: str, lo: int, hi: int, names=None) -> Set[int]:
    out: Set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            if step <= 0:
                raise EngineError(f"bad cron step in {spec!r}")
        if part in ("*", ""):
            lo2, hi2 = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            lo2, hi2 = _value(a, names), _value(b, names)
        else:
            v = _value(part, names)
            lo2 = hi2 = v
            if step > 1:
                hi2 = hi
        if not (lo <= lo2 <= hi and lo <= hi2 <= hi and lo2 <= hi2):
            raise EngineError(f"cron field {spec!r} out of range {lo}-{hi}")
        out.update(range(lo2, hi2 + 1, step))
    return out


def _value(tok: str, names) -> int:
    tok = tok.strip().lower()
    if names and tok in names:
        return names[tok]
    return int(tok)


class Cron:
    def __init__(self, expr: str) -> None:
        fields = expr.split()
        if len(fields) == 6:
            # robfig/cron's optional seconds field: accepted, seconds dropped
            fields = fields[1:]
        if len(fields) != 5:
            raise EngineError(
                f"cron {expr!r} must have 5 fields (min hour dom mon dow)")
        self.expr = expr
        (self.minutes, self.hours, self.dom, self.months, self.dow) = (
            _parse_field(f, lo, hi, names)
            for f, (lo, hi), names in zip(
                fields, _FIELD_RANGES,
                (None, None, None, _MONTH_NAMES, _DOW_NAMES))
        )
        self.dom_star = fields[2] == "*"
        self.dow_star = fields[4] == "*"

    def _day_matches(self, tm: time.struct_time) -> bool:
        dom_ok = tm.tm_mday in self.dom
        # struct_time: Monday=0 ... cron: Sunday=0
        dow_ok = ((tm.tm_wday + 1) % 7) in self.dow
        if self.dom_star and self.dow_star:
            return True
        if self.dom_star:
            return dow_ok
        if self.dow_star:
            return dom_ok
        return dom_ok or dow_ok  # vixie-cron OR semantics

    def next_fire_ms(self, after_ms: int) -> int:
        """Earliest fire time strictly after `after_ms` (epoch ms, local)."""
        t = (after_ms // 60_000 + 1) * 60  # next whole minute, seconds
        for _ in range(366 * 24 * 60):  # bounded search: one year of minutes
            tm = time.localtime(t)
            if (tm.tm_mon in self.months and self._day_matches(tm)
                    and tm.tm_hour in self.hours
                    and tm.tm_min in self.minutes):
                return t * 1000
            t += 60
        raise EngineError(f"cron {self.expr!r} never fires")


_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(ms|s|m|h|d)")
_DUR_MS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000, "d": 86_400_000}


def parse_duration_ms(spec) -> int:
    """Go-style duration ('1h30m', '10s', '500ms') or a bare number of
    milliseconds."""
    if isinstance(spec, (int, float)):
        return int(spec)
    s = str(spec).strip().lower()
    if not s:
        return 0
    if s.isdigit():
        return int(s)
    total = 0.0
    pos = 0
    for m in _DUR_RE.finditer(s):
        if m.start() != pos:
            raise EngineError(f"bad duration {spec!r}")
        total += float(m.group(1)) * _DUR_MS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise EngineError(f"bad duration {spec!r}")
    return int(total)


def parse_range_ms(r: dict) -> Tuple[int, int]:
    """A cronDatetimeRange entry: {beginTimestamp,endTimestamp} in ms or
    {begin,end} as 'YYYY-MM-DD HH:MM:SS' local."""
    if r.get("beginTimestamp") or r.get("endTimestamp"):
        return int(r.get("beginTimestamp", 0)), int(r.get("endTimestamp", 0))

    def parse(s: str) -> int:
        return int(time.mktime(time.strptime(s, "%Y-%m-%d %H:%M:%S")) * 1000)

    return parse(r["begin"]), parse(r["end"])


def in_ranges(now_ms: int, ranges: Optional[List[dict]]) -> bool:
    """IsInScheduleRanges (schedule.go:36-58): no ranges = always in."""
    if not ranges:
        return True
    for r in ranges:
        begin, end = parse_range_ms(r)
        if begin <= now_ms <= end:
            return True
    return False
