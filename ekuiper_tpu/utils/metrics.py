"""Per-node metrics — analogue of eKuiper's StatManager
(reference: internal/topo/node/metric/stats_manager.go:43-213).

Each runtime node owns a StatManager recording records in/out/error, process
latency, buffer length and last-invocation/exception info; a rule's status JSON
aggregates them per node, matching the reference's /rules/{name}/status shape.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Any, Dict, List, Optional

from ..observability.histogram import LatencyHistogram
from . import timex


class StatManager:
    METRIC_NAMES = (
        "records_in_total",
        "records_out_total",
        "messages_processed_total",
        "process_latency_us",
        "buffer_length",
        "last_invocation",
        "exceptions_total",
        "last_exception",
        "last_exception_time",
    )

    def __init__(self, op_type: str, op_id: str, instance: int = 0) -> None:
        self.op_type = op_type
        self.op_id = op_id
        self.instance = instance
        # owning rule, stamped by Topo.add_* — drop-burst flight events
        # need attribution even when the dropping thread (an upstream
        # connector) carries no rule context
        self.rule_id: str = ""
        self._lock = threading.Lock()
        self.records_in = 0
        self.records_out = 0
        self.messages_processed = 0
        self.exceptions = 0
        # drop taxonomy: data discarded BY DESIGN (backpressure, late
        # rows, undecodable payloads) counts here with a reason label —
        # never in `exceptions`, which means operator ERRORS. Reasons:
        # buffer_full / pane_recycle / decode_error / stale_watermark /
        # shed_qos (SLO-driven shedding, runtime/control.py).
        self.dropped: Dict[str, int] = {}
        self.last_exception: str = ""
        self.last_exception_time: int = 0
        self.last_invocation: int = 0
        self.process_latency_us: int = 0
        # cumulative busy time (wall-clock in-process), the engine's
        # per-rule CPU-usage proxy (reference: /rules/usage/cpu)
        self.process_time_us_total: int = 0
        self.buffer_length: int = 0
        self._started_at: Optional[int] = None
        self._started_perf: float = 0.0
        # named pipeline-stage accounting (decode/upload/fold, ...): lets
        # operators see where ingest wall time goes per node — the balance
        # of the sharded ingest pipeline is tuned from these
        self.stages: Dict[str, Dict[str, int]] = {}
        # latency DISTRIBUTIONS (observability/histogram.py): the last-value
        # process_latency_us gauge cannot express a tail — these make the
        # paper's p99 claims measurable per op. proc_hist records each
        # dispatch's busy time, queue_hist each item's wait in the input
        # queue before its dispatch began (both µs, real perf clock).
        self.proc_hist = LatencyHistogram()
        self.queue_hist = LatencyHistogram()
        # queue-depth high-water marks, noted at ENQUEUE time (node.py
        # put/put_control) so a spike that drains between observations is
        # still seen. Two marks with independent read-and-reset consumers:
        # the Prometheus scrape and the health evaluator's tick (their
        # cadences differ — one shared mark would blind whichever reads
        # second). Unlocked telemetry-grade updates: a lost increment
        # under a racing put costs one sample, never correctness.
        self._qd_peak_scrape = 0
        self._qd_peak_tick = 0

    def note_queue_depth(self, n: int) -> None:
        """Record an observed input-queue occupancy (enqueue-time)."""
        if n > self._qd_peak_scrape:
            self._qd_peak_scrape = n
        if n > self._qd_peak_tick:
            self._qd_peak_tick = n

    def take_queue_peak_scrape(self) -> int:
        """Max observed depth since the last scrape (read-and-reset)."""
        p = self._qd_peak_scrape
        self._qd_peak_scrape = 0
        return p

    def take_queue_peak_tick(self) -> int:
        """Max observed depth since the last evaluator tick
        (read-and-reset)."""
        p = self._qd_peak_tick
        self._qd_peak_tick = 0
        return p

    def inc_in(self, n: int = 1) -> None:
        # clock read OUTSIDE the stats lock: a mock advance() fires timer
        # callbacks under the CLOCK lock, and those can reach a stats
        # lock (drop-oldest -> inc_dropped) — holding stats while taking
        # clock here would complete the ABBA square (utils/lockcheck.py
        # flags it; the PR 6 health_sample fix covered only one side)
        now = timex.now_ms()
        with self._lock:
            self.records_in += n
            self.last_invocation = now

    def inc_out(self, n: int = 1) -> None:
        with self._lock:
            self.records_out += n

    def inc_processed(self, n: int = 1) -> None:
        with self._lock:
            self.messages_processed += n

    def inc_exception(self, err: str, n: int = 1) -> None:
        now = timex.now_ms()  # before the lock — see inc_in
        with self._lock:
            self.exceptions += n
            self.last_exception = err
            self.last_exception_time = now

    #: drop-burst flight-recorder thresholds: an event fires when a
    #: reason's cumulative count first reaches each decade — the FIRST
    #: drop is always an event (something new is being discarded), later
    #: ones only at 10x growth so a sustained storm can't flood the ring
    _BURST_DECADES = tuple(10 ** k for k in range(10))

    def inc_dropped(self, reason: str, n: int = 1, detail: str = "") -> None:
        """Count `n` items discarded for `reason` (taxonomy above) and
        record a flight-recorder drop-burst event at decade crossings."""
        with self._lock:
            old = self.dropped.get(reason, 0)
            new = old + n
            self.dropped[reason] = new
        crossed = 0
        for t in self._BURST_DECADES:
            if old < t <= new:
                crossed = t
        if crossed:
            from ..runtime.events import recorder

            recorder().record(
                "drop_burst", rule=self.rule_id, severity="warn",
                node=self.op_id, reason=reason, total=new,
                threshold=crossed,
                **({"detail": detail} if detail else {}))

    def process_begin(self) -> None:
        self._started_at = timex.now_ms()
        self._started_perf = _time.perf_counter()

    def process_end(self) -> None:
        if self._started_at is not None:
            busy_us = int((_time.perf_counter() - self._started_perf) * 1e6)
            now = timex.now_ms()  # before the lock — see inc_in
            with self._lock:
                # latency follows the engine clock (mock-deterministic in
                # tests); the cumulative busy total uses a real perf
                # counter — sub-ms work must still accrue
                self.process_latency_us = (now - self._started_at) * 1000
                self.process_time_us_total += busy_us
            self.proc_hist.record(busy_us)
            self._started_at = None

    def observe_queue_wait(self, us: float) -> None:
        """One item's input-queue dwell (enqueue→dispatch), µs."""
        self.queue_hist.record(us)

    def set_buffer_length(self, n: int) -> None:
        with self._lock:
            self.buffer_length = n

    def observe_stage(self, stage: str, us: int, rows: int = 0) -> None:
        """Accrue `us` microseconds (and optionally rows) to a named
        pipeline stage. Cheap enough for per-batch calls."""
        with self._lock:
            st = self.stages.get(stage)
            if st is None:
                st = self.stages[stage] = {
                    "calls": 0, "total_us": 0, "rows": 0}
            st["calls"] += 1
            st["total_us"] += int(us)
            st["rows"] += int(rows)

    def health_sample(self) -> Dict[str, Any]:
        """Cheap cumulative counters for the health evaluator's per-tick
        deltas — no histogram walks (snapshot() computes percentile
        summaries; a per-tick, per-node walk of every bucket array would
        make evaluator cost scale with histogram width).

        Deliberately LOCK-FREE: evaluator ticks can fire inside a mock
        clock's advance() (which holds the clock lock), while data-path
        threads hold this StatManager's lock and call timex.now_ms()
        (inc_in, process_end) — taking `self._lock` here would be a
        clock-lock/stats-lock ABBA deadlock. Monotonic int reads are
        atomic under the GIL; a dict resized mid-iteration just retries
        (telemetry-grade: a stale sample costs one tick's precision)."""
        for _ in range(4):
            try:
                return {
                    "busy_us": self.process_time_us_total,
                    "stages": {k: v["total_us"]
                               for k, v in self.stages.items()},
                    "dropped": sum(self.dropped.values()),
                    "in": self.records_in,
                }
            except RuntimeError:  # dict changed size during iteration
                continue
        # retries exhausted: flag the sample so the evaluator SKIPS this
        # node for the tick instead of baselining empty stages/drops —
        # the next delta would otherwise replay the node's entire
        # cumulative history as one tick's worth
        return {"busy_us": self.process_time_us_total, "stages": {},
                "dropped": 0, "in": self.records_in, "partial": True}

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "records_in_total": self.records_in,
                "records_out_total": self.records_out,
                "messages_processed_total": self.messages_processed,
                "process_latency_us": self.process_latency_us,
                "process_time_us_total": self.process_time_us_total,
                "buffer_length": self.buffer_length,
                "last_invocation": self.last_invocation,
                "exceptions_total": self.exceptions,
                "last_exception": self.last_exception,
                "last_exception_time": self.last_exception_time,
                "stage_timings": {k: dict(v) for k, v in self.stages.items()},
                "dropped_total": dict(self.dropped),
            }
        # percentile summaries computed OUTSIDE the stats lock (histograms
        # carry their own): p50/p90/p99/max for the status/REST layers
        out["process_latency_us_hist"] = self.proc_hist.snapshot()
        out["queue_wait_us_hist"] = self.queue_hist.snapshot()
        return out

    def metrics_list(self) -> List[Any]:
        snap = self.snapshot()
        return [snap[name] for name in self.METRIC_NAMES]


def flatten_status(stats: Dict[str, StatManager]) -> Dict[str, Any]:
    """Build the flat {op_id_metric: value} map used by rule status JSON
    (reference: internal/topo/rule/state.go:244-275)."""
    out: Dict[str, Any] = {}
    for op_id, sm in stats.items():
        snap = sm.snapshot()
        for metric, value in snap.items():
            out[f"{sm.op_type}_{op_id}_{sm.instance}_{metric}"] = value
    return out
