"""Compression + encryption codecs — analogue of the reference's
modules/compressor (gzip/zlib/flate/zstd) and modules/encryptor (aes)
registries (SURVEY §2.6).

All operate on bytes (they sit after the encode op in the sink chain,
planner_sink.go:36-253).
"""
from __future__ import annotations

import gzip
import os
import zlib
from typing import Callable, Dict, Tuple

# ----------------------------------------------------------------- compress
_compressors: Dict[str, Tuple[Callable[[bytes], bytes], Callable[[bytes], bytes]]] = {}


def register_compressor(name: str, compress, decompress) -> None:
    _compressors[name.lower()] = (compress, decompress)


register_compressor("gzip", gzip.compress, gzip.decompress)
register_compressor("zlib", zlib.compress, zlib.decompress)
# flate = raw DEFLATE (no zlib header), matching Go's compress/flate
register_compressor(
    "flate",
    lambda b: zlib.compress(b)[2:-4],
    lambda b: zlib.decompress(b, wbits=-zlib.MAX_WBITS),
)

try:
    import zstandard as _zstd

    register_compressor(
        "zstd",
        lambda b: _zstd.ZstdCompressor().compress(b),
        lambda b: _zstd.ZstdDecompressor().decompress(b),
    )
except ImportError:  # zstd optional, like the reference's build tag
    pass


def get_compressor(name: str):
    """-> (compress, decompress) or raises ValueError."""
    pair = _compressors.get(name.lower())
    if pair is None:
        raise ValueError(f"unknown compression algorithm {name!r} "
                         f"(have {sorted(_compressors)})")
    return pair


def compression_algorithms():
    return sorted(_compressors)


# ------------------------------------------------------------------ encrypt
class AesEncryptor:
    """AES encryptor/decryptor — analogue of modules/encryptor/aes.

    Modes: gcm (default, key any of 16/24/32 bytes; output nonce||ct||tag)
    and cfb (output iv||ct), mirroring the reference's aes modes.
    """

    def __init__(self, key: bytes, mode: str = "gcm") -> None:
        if len(key) not in (16, 24, 32):
            raise ValueError("aes key must be 16/24/32 bytes")
        self.key = key
        self.mode = mode.lower()
        if self.mode not in ("gcm", "cfb"):
            raise ValueError(f"unknown aes mode {mode!r}")

    def encrypt(self, data: bytes) -> bytes:
        from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

        if self.mode == "gcm":
            from cryptography.hazmat.primitives.ciphers.aead import AESGCM

            nonce = os.urandom(12)
            return nonce + AESGCM(self.key).encrypt(nonce, data, None)
        iv = os.urandom(16)
        enc = Cipher(algorithms.AES(self.key), modes.CFB(iv)).encryptor()
        return iv + enc.update(data) + enc.finalize()

    def decrypt(self, data: bytes) -> bytes:
        from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

        if self.mode == "gcm":
            from cryptography.hazmat.primitives.ciphers.aead import AESGCM

            return AESGCM(self.key).decrypt(data[:12], data[12:], None)
        dec = Cipher(algorithms.AES(self.key), modes.CFB(data[:16])).decryptor()
        return dec.update(data[16:]) + dec.finalize()


def get_encryptor(name: str, props: dict) -> AesEncryptor:
    if name.lower() != "aes":
        raise ValueError(f"unknown encryption algorithm {name!r}")
    key = props.get("key", "")
    if isinstance(key, str):
        key = key.encode()
    return AesEncryptor(key, props.get("aesMode", props.get("mode", "gcm")))
