"""Weakref object registry — THE ownership model shared by the
per-component metric render sources (ops/tierstore.py TierManagers,
parallel/sharded.py sharded kernels; memwatch pioneered it): strong
ownership stays with the registered object, the registry holds only a
weak reference plus a rule label, and a collected object's rows simply
stop rendering. One implementation so the pruning/dedup/locking
semantics cannot drift between consumers."""
from __future__ import annotations

import threading
import weakref
from typing import Any, List, Optional, Tuple


class WeakRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._refs: List[Tuple[Any, Optional[str]]] = []

    def register(self, obj, rule: Optional[str] = None) -> None:
        """Add (or re-label) an object; dead refs prune, and a
        re-registration of the same object replaces its entry."""
        with self._lock:
            kept = []
            for r, ru in self._refs:
                o = r()
                if o is None or o is obj:
                    continue
                kept.append((r, ru))
            kept.append((weakref.ref(obj), rule))
            self._refs = kept

    def items(self) -> List[Tuple[Any, Optional[str]]]:
        """Live (object, rule) pairs."""
        with self._lock:
            refs = list(self._refs)
        return [(o, rule) for (r, rule) in refs if (o := r()) is not None]

    # legacy alias (ops/tierstore.py grew up calling it managers())
    managers = items

    def clear(self) -> None:
        with self._lock:
            self._refs.clear()
