"""Mockable clock — the TPU-native analogue of eKuiper's pkg/timex.

The reference wraps benbjohnson/clock and auto-switches to a mock clock under
`go test` (reference: pkg/timex/timex.go), so window/ticker tests advance time
deterministically. We carry the same pattern: a process-global Clock that all
runtime components (window triggers, rate limiters, schedulers, metrics) must
use instead of time.time().

Real clock = wall clock. Mock clock = manually advanced; sleepers/timers are
woken when `advance()` crosses their deadline, so a test can feed tuples, call
`advance(10_000)`, and observe the tumbling window fire — no real waiting.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Optional

MS = 1
SECOND = 1000
MINUTE = 60 * SECOND
HOUR = 60 * MINUTE

DAY = 24 * HOUR

_UNIT_MS = {"ms": MS, "ss": SECOND, "mi": MINUTE, "hh": HOUR, "dd": DAY}


def unit_to_ms(unit: str) -> int:
    """Window-size unit (as in TUMBLINGWINDOW(ss, 10)) to milliseconds."""
    try:
        return _UNIT_MS[unit.lower()]
    except KeyError:
        raise ValueError(f"unknown time unit {unit!r} (want dd/hh/mi/ss/ms)")


class Timer:
    """One-shot timer handle. `wait()` blocks until it fires or is stopped."""

    def __init__(self) -> None:
        self._event = threading.Event()
        self.fired_at: Optional[int] = None
        self.stopped = False

    def _fire(self, now_ms: int) -> None:
        self.fired_at = now_ms
        self._event.set()

    def stop(self) -> None:
        self.stopped = True
        self._event.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    @property
    def fired(self) -> bool:
        return self.fired_at is not None


class Clock:
    """Interface. now_ms() is the engine-wide notion of processing time."""

    def now_ms(self) -> int:
        raise NotImplementedError

    def sleep(self, ms: int) -> None:
        raise NotImplementedError

    def after(self, ms: int, callback: Optional[Callable[[int], None]] = None) -> Timer:
        raise NotImplementedError

    def is_mock(self) -> bool:
        return False


class RealClock(Clock):
    def now_ms(self) -> int:
        return int(time.time() * 1000)

    def sleep(self, ms: int) -> None:
        time.sleep(ms / 1000.0)

    def after(self, ms: int, callback: Optional[Callable[[int], None]] = None) -> Timer:
        timer = Timer()

        def run() -> None:
            time.sleep(ms / 1000.0)
            if not timer.stopped:
                now = self.now_ms()
                timer._fire(now)
                if callback is not None:
                    callback(now)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        return timer


class MockClock(Clock):
    """Deterministic clock. Time only moves via set()/advance().

    Timers registered with `after()` fire synchronously inside the advancing
    thread, in deadline order, which makes window-trigger tests reproducible.
    """

    def __init__(self, start_ms: int = 0) -> None:
        self._now = start_ms
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._counter = itertools.count()
        # heap of (deadline, seq, timer, callback)
        self._timers: list = []

    def is_mock(self) -> bool:
        return True

    def now_ms(self) -> int:
        with self._lock:
            return self._now

    def set(self, ms: int) -> None:
        with self._cond:
            if ms < self._now:
                raise ValueError(f"mock clock cannot go backwards ({ms} < {self._now})")
            self._fire_until(ms)
            self._now = ms
            self._cond.notify_all()

    def advance(self, ms: int) -> None:
        with self._cond:
            target = self._now + ms
            self._fire_until(target)
            self._now = target
            self._cond.notify_all()

    def _fire_until(self, target_ms: int) -> None:
        # Fire due timers in deadline order, moving time to each deadline so a
        # callback that re-registers (a ticker) keeps firing within one advance.
        while self._timers and self._timers[0][0] <= target_ms:
            deadline, _, timer, callback = heapq.heappop(self._timers)
            if timer.stopped:
                continue
            self._now = max(self._now, deadline)
            timer._fire(deadline)
            if callback is not None:
                callback(deadline)

    def sleep(self, ms: int) -> None:
        """Block until mock time passes now+ms (some other thread must advance)."""
        with self._cond:
            deadline = self._now + ms
            while self._now < deadline:
                self._cond.wait(timeout=5.0)

    def after(self, ms: int, callback: Optional[Callable[[int], None]] = None) -> Timer:
        timer = Timer()
        with self._cond:
            heapq.heappush(
                self._timers, (self._now + ms, next(self._counter), timer, callback)
            )
        return timer


_clock: Clock = RealClock()
_lock = threading.Lock()


def get_clock() -> Clock:
    return _clock


def now_ms() -> int:
    return _clock.now_ms()


def sleep(ms: int) -> None:
    _clock.sleep(ms)


def after(ms: int, callback: Optional[Callable[[int], None]] = None) -> Timer:
    return _clock.after(ms, callback)


def set_mock_clock(start_ms: int = 0) -> MockClock:
    """Install (and return) a fresh mock clock — call from test setup."""
    global _clock
    with _lock:
        mock = MockClock(start_ms)
        _clock = mock
        return mock


def get_mock_clock() -> MockClock:
    if not isinstance(_clock, MockClock):
        raise RuntimeError("mock clock not installed; call set_mock_clock() first")
    return _clock


def use_real_clock() -> None:
    global _clock
    with _lock:
        _clock = RealClock()


def align_to_window(now: int, interval_ms: int) -> int:
    """Next boundary of a tumbling/hopping interval at or after `now`.

    eKuiper aligns window boundaries to the epoch (getAlignedWindowEndTime),
    so a 10s tumbling window always fires at :00, :10, :20 ...
    """
    if interval_ms <= 0:
        raise ValueError("interval must be positive")
    rem = now % interval_ms
    return now if rem == 0 else now + (interval_ms - rem)
