"""Engine configuration — analogue of eKuiper's etc/kuiper.yaml → model.KuiperConf
(reference: pkg/model/conf.go:28, internal/conf/env_manager.go).

Sections mirror the reference: basic / rule / sink / source / store / portable.
Values can be overridden by environment variables of the form
EKUIPER_TPU__<SECTION>__<KEY> (double underscore separators), mirroring the
reference's env overlay scheme.
"""
from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

ENV_PREFIX = "EKUIPER_TPU__"


@dataclass
class RuleOptionConfig:
    """Default per-rule options (reference: internal/pkg/def/rule.go:27-49)."""

    debug: bool = False
    log_filename: str = ""
    is_event_time: bool = False
    late_tolerance_ms: int = 1000
    concurrency: int = 1
    buffer_length: int = 1024
    send_error: bool = True
    qos: int = 0  # 0 AtMostOnce, 1 AtLeastOnce, 2 ExactlyOnce
    checkpoint_interval_ms: int = 300_000
    restart_attempts: int = 0  # 0 = no restart; -1 = infinite
    restart_delay_ms: int = 1000
    restart_multiplier: float = 2.0
    restart_max_delay_ms: int = 30_000
    restart_jitter_factor: float = 0.1
    disable_buffer_full_discard: bool = False
    # TPU execution options
    micro_batch_rows: int = 4096
    micro_batch_linger_ms: int = 10
    # sharded ingest pipeline (runtime/ingest.py): decode_pool_size worker
    # threads decode drained payload runs off the connector thread, handing
    # ColumnBatches to the fused node through a bounded ring so decode of
    # batch k+1 overlaps the upload+fold of batch k. Default 0 = decode
    # inline on the ingest thread: emission then happens synchronously
    # inside ingest/flush, which rules driven by the mockable clock
    # (timex) depend on. Byte-fed production pipelines should set 2-4
    # (the full-pipe bench runs with 3).
    decode_pool_size: int = 0
    # native parse shards per decode call (jsoncol.cpp GIL-free pass);
    # 0 = auto (decode_pool_size when the pool is on, else 1)
    decode_shards: int = 0
    # decoded-batch ring depth: in-flight decodes before submit blocks
    # (backpressure toward the connector)
    ingest_ring_depth: int = 2
    # pipelined upload stage (pool-on only): decode-pool workers key-slot-
    # encode each batch (native C table when built) and pre-pad +
    # device_put its kernel inputs, so H2D of batch k+1 overlaps the fold
    # of batch k and the fused worker's upload stage collapses to share-
    # cache hits. Off = pool decodes only, fused node preps inline.
    ingest_prep_upload: bool = True
    # HBM budget for sliding-window device state beyond the panes: the
    # DABA ring partials (ops/slidingring.py — allocation refused past the
    # cap, rule falls back to refold) and the refold impl's _dev_ring
    # fold-input cache (FIFO-evicted past the cap, refolds fall back to
    # exact host uploads)
    sliding_dev_ring_mb: int = 256
    # sliding trigger emission: "daba" = constant-time two-stack rings
    # (ops/slidingring.py, default); "refold" = legacy pane-merge +
    # edge-refold path (parity baseline / escape hatch)
    sliding_impl: str = "daba"
    # stream-stream joins: "device" = banded-gather ring kernel
    # (ops/joinring.py) when the ON clause lowers, with per-window host
    # fallback; "host" = always the nested-loop reference operator
    join_impl: str = "device"
    # analytic/window functions: "device" = lag on the segscan shift
    # kernel + rank/dense_rank through the segscan sort kernel
    # (ops/segscan.py); "host" = per-row evaluator state machines
    analytic_impl: str = "device"
    key_slots: int = 16384  # group-by hash-slot table size per rule
    # tiered key state (ops/tierstore.py, docs/TIERED_STATE.md): "auto"
    # enables the HBM-resident hot set + host cold tier when
    # KUIPER_HBM_BUDGET_MB is set and too tight for the rule's capacity
    # ladder; "on" forces it (budget or tierHotMb required), "off"
    # disables. Cold keys' per-pane partials spill to a pinned host
    # arena and their device slots recycle through the key table.
    tier_store: str = "auto"
    # explicit hot-tier HBM allowance (MB); 0 = derive from
    # KUIPER_HBM_BUDGET_MB
    tier_hot_mb: int = 0
    # placement-policy cadence; 0 = derive from the window geometry
    tier_scan_ms: int = 0
    use_device_kernel: bool = True  # fuse window+agg into a jitted kernel when possible
    # pre-issue the window finalize this long before the boundary so the
    # device round trip overlaps the stream (ops/prefinalize.py); 0 disables
    prefinalize_lead_ms: int = 250
    # window-tail rows after a pre-issue: "device" folds them to both the
    # device state and the merge shadow (state always complete); "host"
    # freezes the device and shadows only (for saturated host→device links)
    tail_mode: str = "device"
    # fused window results stay columnar (ColumnBatch) end-to-end; sinks
    # convert to per-message dicts at the edge
    emit_columnar: bool = True
    # one shared ingest+decode pipeline per stream config across qos=0 rules
    # (reference subtopo_pool); checkpointed rules always get a private source
    share_source: bool = True
    # cost-based cross-rule window-aggregate sharing (planner/sharing.py):
    # correlated rules over one stream fold once into a shared pane store
    # and combine panes per window. qos=0 + share_source only; the planner
    # falls back to a private fold (logged) when the rewrite doesn't apply
    # or its cost model says it won't pay.
    shared_fold: bool = True
    # planOptimizeStrategy analogue (reference: internal/pkg/def/rule.go:55-66);
    # {"mesh": {"rows": R, "keys": K}} runs the fused kernel sharded over an
    # R x K device mesh (parallel/sharded.py)
    plan_optimize_strategy: Dict[str, Any] = field(default_factory=dict)


@dataclass
class StoreConfig:
    type: str = "sqlite"  # sqlite | memory
    path: str = "data"


@dataclass
class BasicConfig:
    log_level: str = "info"
    rest_port: int = 9081
    rest_ip: str = "0.0.0.0"
    prometheus: bool = False
    prometheus_port: int = 20499
    ignore_case: bool = False
    time_zone: str = "UTC"
    # REST JWT auth (reference internal/pkg/jwt — uses registered RSA keys;
    # here an HS256 shared secret, documented divergence). Off by default.
    authentication: bool = False
    jwt_secret: str = ""
    # per-rule log files under <store.path>/logs (rule logToDisk analogue)
    rule_log_enabled: bool = False


@dataclass
class SinkConfig:
    mem_cache_threshold: int = 1024
    max_disk_cache: int = 1024000
    buffer_page_size: int = 256
    resend_interval_ms: int = 0
    clean_cache_at_stop: bool = False


@dataclass
class SourceConfig:
    http_server_ip: str = "0.0.0.0"
    http_server_port: int = 10081


@dataclass
class PortableConfig:
    python_bin: str = "python"
    init_timeout_ms: int = 5000


@dataclass
class ClusterConfig:
    """Multi-host mesh participation (jax.distributed). When enabled, every
    host runs the engine with the same config; meshes built from
    jax.devices() then span all hosts, kernel collectives ride ICI inside a
    pod slice and DCN across slices. See docs/DISTRIBUTED.md for the
    execution model and its constraints."""

    enabled: bool = False
    coordinator_address: str = ""  # host:port of process 0
    num_processes: int = 1
    process_id: int = 0


@dataclass
class OpenTelemetryConfig:
    """Remote OTLP span export (reference pkg/tracer/manager.go:28-45 —
    otlptracehttp with WithInsecure). Off by default: zero egress unless
    explicitly pointed at a collector."""

    enable_remote_collector: bool = False
    remote_endpoint: str = "localhost:4318"
    service_name: str = "ekuiper_tpu"  # resource attribute on exported spans
    batch_max_spans: int = 512
    batch_interval_ms: int = 2000


@dataclass
class Config:
    basic: BasicConfig = field(default_factory=BasicConfig)
    rule: RuleOptionConfig = field(default_factory=RuleOptionConfig)
    store: StoreConfig = field(default_factory=StoreConfig)
    sink: SinkConfig = field(default_factory=SinkConfig)
    source: SourceConfig = field(default_factory=SourceConfig)
    portable: PortableConfig = field(default_factory=PortableConfig)
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    open_telemetry: OpenTelemetryConfig = field(
        default_factory=OpenTelemetryConfig)
    data_dir: str = "data"

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _coerce(value: str, target_type: type) -> Any:
    if target_type is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if target_type is int:
        return int(value)
    if target_type is float:
        return float(value)
    return value


def _apply_env(cfg: Config) -> None:
    for key, value in os.environ.items():
        if not key.startswith(ENV_PREFIX):
            continue
        parts = key[len(ENV_PREFIX):].lower().split("__")
        if len(parts) != 2:
            continue
        section, name = parts
        sec = getattr(cfg, section, None)
        if sec is None or not hasattr(sec, name):
            continue
        current = getattr(sec, name)
        setattr(sec, name, _coerce(value, type(current)))


def load_config(path: Optional[str] = None) -> Config:
    """Load config from a JSON file (if given/exists) then apply env overrides."""
    cfg = Config()
    if path and os.path.exists(path):
        with open(path) as f:
            raw = json.load(f)
        for section, values in raw.items():
            sec = getattr(cfg, section, None)
            if sec is None or not dataclasses.is_dataclass(sec):
                continue
            for k, v in values.items():
                if hasattr(sec, k):
                    setattr(sec, k, v)
    _apply_env(cfg)
    return cfg


_global: Optional[Config] = None


def apply_config_overlay(store) -> None:
    """Re-apply runtime PATCH /configs overlays persisted in the KV store
    (server/rest.py patch_configs) so patches survive restarts."""
    cfg = get_config()
    overlay = store.kv("config_overlay")
    for key in overlay.keys():
        val, ok = overlay.get_ok(key)
        if ok and hasattr(cfg.basic, key):
            setattr(cfg.basic, key, val)


def get_config() -> Config:
    global _global
    if _global is None:
        _global = load_config(os.environ.get("EKUIPER_TPU_CONFIG"))
    return _global


def set_config(cfg: Config) -> None:
    global _global
    _global = cfg
