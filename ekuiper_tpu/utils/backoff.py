"""Jittered exponential backoff — the ONE retry-delay policy for
connector reconnect loops (io/mqtt_native.py, io/zmq_native.py,
io/kafka_io.py).

Fixed-sleep retries synchronize: a broker restart makes every client
redial on the same beat, and the reconnect stampede is itself the next
outage (the classic thundering herd). Every reconnect path therefore
computes its delay here — exponential growth with a hard cap, plus
"equal jitter" (half the computed delay fixed, half uniform random), so
a fleet's retries spread over the window instead of arriving together.

The delay sequence is a pure function of (attempt, rng): tests inject a
seeded `random.Random` and assert the schedule deterministically, no
sleeping involved — the caller owns the actual wait (connectors block on
their `threading.Event` stop flags so close() interrupts a backoff
immediately; that part is wall-clock by design and lives outside the
engine clock).
"""
from __future__ import annotations

import random
import threading
from typing import Optional


def backoff_delay_s(attempt: int, base_s: float = 0.1,
                    cap_s: float = 30.0, factor: float = 2.0,
                    rng: Optional[random.Random] = None) -> float:
    """Delay before retry `attempt` (1-based): equal-jitter exponential
    backoff. attempt<=1 starts at `base_s`; growth is `factor`-fold per
    attempt, capped at `cap_s`; the returned delay is uniform in
    [raw/2, raw] so concurrent retriers spread while every delay keeps a
    meaningful floor (full jitter can return ~0 and hot-spin a dead
    broker)."""
    raw = min(base_s * (factor ** max(int(attempt) - 1, 0)), cap_s)
    r = (rng or random).uniform(0.5, 1.0)
    return raw * r


class Backoff:
    """Stateful wrapper for reconnect loops: `next_s()` advances the
    schedule, `reset()` rewinds it after a successful (re)connect,
    `wait(stop)` sleeps the next delay interruptibly against a
    `threading.Event` (returns True when the stop flag fired — the
    caller's signal to bail out of its retry loop)."""

    def __init__(self, base_s: float = 0.1, cap_s: float = 30.0,
                 factor: float = 2.0,
                 rng: Optional[random.Random] = None) -> None:
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.factor = float(factor)
        self._rng = rng
        self.attempt = 0

    def next_s(self) -> float:
        self.attempt += 1
        return backoff_delay_s(self.attempt, self.base_s, self.cap_s,
                               self.factor, rng=self._rng)

    def reset(self) -> None:
        self.attempt = 0

    def wait(self, stop: Optional[threading.Event] = None) -> bool:
        """Block for the next delay; a set/firing `stop` event cuts the
        wait short. Returns True when stopped."""
        delay = self.next_s()
        if stop is not None:
            return stop.wait(delay)
        # kuiperlint exempt by scope (utils/ is not clock-disciplined);
        # connector retries are wall-clock by design
        import time as _time

        _time.sleep(delay)
        return False
