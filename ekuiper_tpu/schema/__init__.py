"""Schema registry package — analogue of internal/schema."""
from .registry import SchemaRegistry

__all__ = ["SchemaRegistry"]
