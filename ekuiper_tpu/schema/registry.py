"""Schema registry — analogue of internal/schema/registry.go:49-184.

Stores schema files (protobuf .proto sources; "custom" schemas are gated
out — they are Go .so plugins in the reference) under the data dir, with
metadata in the KV store. Protobuf schemas are compiled once at registration
via `protoc --descriptor_set_out` (protoc is part of the base toolchain) and
loaded through google.protobuf's descriptor pool, so decode/encode never
shells out on the data path.
"""
from __future__ import annotations

import json
import os
import subprocess
import threading
from typing import Any, Dict, List, Optional

from ..utils.infra import EngineError, logger


class SchemaRegistry:
    _instance: Optional["SchemaRegistry"] = None

    def __init__(self, store=None, etc_dir: str = "data/schemas") -> None:
        self._kv = store.kv("schema") if store is not None else None
        self.etc_dir = etc_dir
        self._pools: Dict[str, Any] = {}  # name -> (pool, factory_cache)
        self._mu = threading.Lock()
        if self._kv is not None:
            for name in self._kv.keys():
                try:
                    self._load(json.loads(self._kv.get(name)))
                except Exception as e:
                    logger.warning("schema %s restore failed: %s", name, e)

    @classmethod
    def global_instance(cls) -> "SchemaRegistry":
        if cls._instance is None:
            cls._instance = SchemaRegistry()
        return cls._instance

    @classmethod
    def set_global(cls, reg: "SchemaRegistry") -> None:
        cls._instance = reg

    # ------------------------------------------------------------------ CRUD
    def create(self, spec: Dict[str, Any], overwrite: bool = False) -> None:
        """spec: {"name": ..., "type": "protobuf", "content": proto source}
        or {"name", "type", "file": path} (reference: schema json shape)."""
        name = spec.get("name", "")
        stype = spec.get("type", "protobuf")
        if not name:
            raise EngineError("schema name is required")
        if stype != "protobuf":
            raise EngineError(f"schema type {stype!r} not supported "
                              "(protobuf only; 'custom' is a Go .so concept)")
        if not overwrite and self.get(name) is not None:
            raise EngineError(f"schema {name} already exists")
        content = spec.get("content", "")
        if not content and spec.get("file"):
            with open(spec["file"]) as f:
                content = f.read()
        if not content:
            raise EngineError("schema content (or file) is required")
        os.makedirs(self.etc_dir, exist_ok=True)
        proto_path = os.path.join(self.etc_dir, f"{name}.proto")
        with open(proto_path, "w") as f:
            f.write(content)
        record = {"name": name, "type": stype, "proto_path": proto_path}
        self._load(record)  # compiles; raises on bad proto before persisting
        if self._kv is not None:
            self._kv.set(name, json.dumps(record))

    def get(self, name: str) -> Optional[Dict[str, Any]]:
        if self._kv is None:
            return None
        raw, ok = self._kv.get_ok(name)
        if not ok:
            return None
        rec = json.loads(raw)
        try:
            with open(rec["proto_path"]) as f:
                rec["content"] = f.read()
        except OSError:
            rec["content"] = ""
        return rec

    def list(self) -> List[str]:
        return sorted(self._kv.keys()) if self._kv is not None else []

    def delete(self, name: str) -> None:
        if self._kv is not None:
            raw, ok = self._kv.get_ok(name)
            if ok:
                rec = json.loads(raw)
                try:
                    os.unlink(rec["proto_path"])
                except OSError:
                    pass
            self._kv.delete(name)
        with self._mu:
            self._pools.pop(name, None)

    # ----------------------------------------------------------- compilation
    def _load(self, record: Dict[str, Any]) -> None:
        from google.protobuf import descriptor_pb2, descriptor_pool

        proto_path = record["proto_path"]
        desc_path = proto_path + ".pb"
        proto_dir = os.path.dirname(os.path.abspath(proto_path)) or "."
        res = subprocess.run(
            ["protoc", f"--proto_path={proto_dir}",
             f"--descriptor_set_out={desc_path}",
             os.path.basename(proto_path)],
            capture_output=True, timeout=30,
        )
        if res.returncode != 0:
            raise EngineError(
                f"protoc failed for {record['name']}: "
                f"{res.stderr.decode(errors='replace').strip()}")
        with open(desc_path, "rb") as f:
            fds = descriptor_pb2.FileDescriptorSet.FromString(f.read())
        pool = descriptor_pool.DescriptorPool()
        for fdp in fds.file:
            pool.Add(fdp)
        with self._mu:
            self._pools[record["name"]] = pool

    def message_class(self, schema_name: str, message_name: str):
        """-> generated message class for schema.message (SCHEMAID form
        "schema.message", registry.go GetSchema semantics)."""
        from google.protobuf import message_factory

        with self._mu:
            pool = self._pools.get(schema_name)
        if pool is None:
            raise EngineError(f"schema {schema_name} not found")
        # message may be package-qualified inside the proto; try verbatim
        # first, then scan the pool's files for a suffix match
        try:
            desc = pool.FindMessageTypeByName(message_name)
        except KeyError:
            desc = None
            rec = self.get(schema_name) or {}
            pkg = self._package_of(rec.get("content", ""))
            if pkg:
                try:
                    desc = pool.FindMessageTypeByName(f"{pkg}.{message_name}")
                except KeyError:
                    desc = None
        if desc is None:
            raise EngineError(
                f"message {message_name} not found in schema {schema_name}")
        return message_factory.GetMessageClass(desc)

    @staticmethod
    def _package_of(content: str) -> str:
        for line in content.splitlines():
            line = line.strip()
            if line.startswith("package ") and line.endswith(";"):
                return line[len("package "):-1].strip()
        return ""
