"""CLI — analogue of eKuiper's `kuiper` client (cmd/kuiper/main.go:89-660).

Talks to a running server over the REST API (the reference uses JSON-RPC;
REST carries the same operations here). Commands mirror the reference:

  create stream "CREATE STREAM ..."     show streams     describe stream X
  drop stream X                         (same for table)
  create rule <id> '<json>' | -f file   show rules       describe rule X
  drop rule X    start rule X   stop rule X   restart rule X
  getstatus rule X    query  (interactive SQL REPL via trial runner)

Run: python -m ekuiper_tpu.server.cli [--host H --port P] <command...>
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Optional


class Client:
    def __init__(self, host: str = "127.0.0.1", port: int = 9081) -> None:
        self.base = f"http://{host}:{port}"

    def call(self, method: str, path: str, body: Any = None) -> Any:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                raw = resp.read().decode()
                ctype = resp.headers.get("Content-Type", "")
                if "json" not in ctype:
                    return raw  # text endpoints (/metrics)
                return json.loads(raw)
        except urllib.error.HTTPError as exc:
            payload = exc.read().decode()
            try:
                return {"error": json.loads(payload).get("error", payload)}
            except json.JSONDecodeError:
                return {"error": payload}
        except urllib.error.URLError as exc:
            print(f"cannot connect to server at {self.base}: {exc.reason}",
                  file=sys.stderr)
            sys.exit(1)


def _print(result: Any) -> None:
    if isinstance(result, str):
        print(result)
    else:
        print(json.dumps(result, indent=2, default=str))


def run_query_repl(client: Client) -> None:
    """Interactive SQL REPL over the trial runtime (reference `kuiper query`)."""
    print("Connecting to server... type SQL, or 'exit' to quit.")
    while True:
        try:
            sql = input("kuiper_tpu > ").strip()
        except (EOFError, KeyboardInterrupt):
            break
        if not sql or sql.lower() in ("exit", "quit"):
            break
        trial = client.call("POST", "/ruletest", {"sql": sql})
        if "error" in trial:
            print("error:", trial["error"])
            continue
        tid = trial["id"]
        client.call("POST", f"/ruletest/{tid}/start")
        try:
            print("(collecting for 5s, Ctrl-C to stop early)")
            time.sleep(5)
        except KeyboardInterrupt:
            pass
        results = client.call("GET", f"/ruletest/{tid}")
        client.call("DELETE", f"/ruletest/{tid}")
        for row in results if isinstance(results, list) else [results]:
            print(json.dumps(row, default=str))


def main(argv: Optional[list] = None) -> None:
    ap = argparse.ArgumentParser(prog="kuiper_tpu", description="ekuiper_tpu CLI")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9081)
    ap.add_argument("args", nargs="*", help="command, e.g. show streams")
    ns = ap.parse_args(argv)
    client = Client(ns.host, ns.port)
    args = ns.args
    if not args:
        ap.print_help()
        return
    cmd = args[0].lower()

    if cmd == "query":
        run_query_repl(client)
        return
    if cmd == "show" and len(args) >= 2:
        target = args[1].lower()
        _print(client.call("GET", f"/{target if target.endswith('s') else target + 's'}"))
        return
    if cmd in ("describe", "desc") and len(args) >= 3:
        _print(client.call("GET", f"/{args[1].lower()}s/{args[2]}"))
        return
    if cmd == "drop" and len(args) >= 3:
        _print(client.call("DELETE", f"/{args[1].lower()}s/{args[2]}"))
        return
    if cmd == "create" and len(args) >= 3:
        target = args[1].lower()
        if target in ("stream", "table"):
            sql = " ".join(args[2:])
            _print(client.call("POST", f"/{target}s", {"sql": sql}))
            return
        if target == "rule":
            rule_id = args[2]
            if len(args) >= 4 and args[3] == "-f":
                with open(args[4]) as f:
                    body = json.load(f)
            else:
                body = json.loads(" ".join(args[3:]))
            body.setdefault("id", rule_id)
            _print(client.call("POST", "/rules", body))
            return
    if cmd in ("start", "stop", "restart") and len(args) >= 3 and args[1] == "rule":
        _print(client.call("POST", f"/rules/{args[2]}/{cmd}"))
        return
    if cmd == "getstatus" and len(args) >= 3 and args[1] == "rule":
        _print(client.call("GET", f"/rules/{args[2]}/status"))
        return
    if cmd == "ping" and len(args) >= 3 and args[1] == "connection":
        _print(client.call("GET", f"/connections/{args[2]}/ping"))
        return
    if cmd == "trace" and len(args) >= 4 and args[1] in ("start", "stop"):
        # trace start|stop rule <id>
        _print(client.call("POST", f"/rules/{args[3]}/trace/{args[1]}"))
        return
    if cmd == "trace" and len(args) >= 3 and args[1] == "rule":
        _print(client.call("GET", f"/trace/rule/{args[2]}"))
        return
    if cmd == "metrics":
        print(client.call("GET", "/metrics"))
        return
    print(f"unknown command: {' '.join(args)}", file=sys.stderr)
    sys.exit(2)


if __name__ == "__main__":
    main()
