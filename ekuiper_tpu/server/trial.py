"""Trial (ruletest) runner — analogue of eKuiper's internal/trial
(manager.go:34-81, run.go): run a rule against mock source data and collect
the results for inspection without persisting anything.

Divergence from the reference: results are fetched by polling GET
/ruletest/{id} instead of streaming over a websocket endpoint — same
capability, pull instead of push.
"""
from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional

from ..planner.planner import RuleDef, plan_rule
from ..runtime.nodes_sink import SinkNode
from ..runtime.nodes_source import SourceNode
from ..sql.parser import parse_select
from ..utils.infra import PlanError
from ..utils import timex


class _CollectSink:
    def configure(self, props):
        pass

    def connect(self):
        pass

    def collect(self, item):
        pass

    def close(self):
        pass


class Trial:
    def __init__(self, trial_id: str, topo, sink: SinkNode) -> None:
        self.id = trial_id
        self.topo = topo
        self.sink = sink


class TrialManager:
    def __init__(self, store) -> None:
        self.store = store
        self._trials: Dict[str, Trial] = {}
        self._lock = threading.Lock()

    def create(self, body: Optional[dict]) -> Dict[str, Any]:
        """body: {id?, sql, mockSource: {stream: {data: [...], interval, loop}},
        sinkProps: {...}} (reference: genTrialRule)."""
        if not body or "sql" not in body:
            raise PlanError("ruletest body must contain sql")
        trial_id = str(body.get("id") or uuid.uuid4())
        stmt = parse_select(body["sql"])
        mock = body.get("mockSource", {})
        # override the stream's physical source with a simulator fed by the
        # mock data; keep decode/schema from the stream definition
        conf = self.store.kv("source_conf")
        overridden = []
        for tbl in stmt.sources:
            m = mock.get(tbl.name)
            if m is not None:
                key = f"simulator:__trial_{trial_id}_{tbl.name}"
                conf.set(key, {
                    "data": m.get("data", []),
                    "interval": int(m.get("interval", 0)),
                    "loop": bool(m.get("loop", False)),
                    "batch_size": int(m.get("batch_size", 1)),
                })
                overridden.append((tbl.name, key))
        rule = RuleDef(
            id=f"__trial_{trial_id}", sql=body["sql"],
            actions=[{"nop": {}}],
            options=body.get("options", {}),
        )
        store = self.store
        if overridden:
            store = _TrialStoreView(self.store, dict(overridden), trial_id)
        topo = plan_rule(rule, store)
        sink = topo.sinks[0]
        trial = Trial(trial_id, topo, sink)
        with self._lock:
            self._trials[trial_id] = trial
        return {"id": trial_id}

    def start(self, trial_id: str) -> str:
        trial = self._get(trial_id)
        trial.topo.open()
        return f"Trial {trial_id} started"

    def results(self, trial_id: str) -> List[Any]:
        trial = self._get(trial_id)
        return list(trial.sink.results)

    def stop(self, trial_id: str) -> str:
        with self._lock:
            trial = self._trials.pop(trial_id, None)
        if trial is not None:
            trial.topo.close()
        return f"Trial {trial_id} stopped"

    def _get(self, trial_id: str) -> Trial:
        with self._lock:
            trial = self._trials.get(trial_id)
        if trial is None:
            raise PlanError(f"trial {trial_id} not found")
        return trial


class _TrialStoreView:
    """Store proxy that rewrites stream defs to the trial's simulator source."""

    def __init__(self, store, overrides: Dict[str, str], trial_id: str) -> None:
        self._store = store
        self._overrides = overrides
        self._trial_id = trial_id

    def kv(self, namespace: str):
        inner = self._store.kv(namespace)
        if namespace not in ("stream", "table"):
            return inner
        return _StreamKvView(inner, self._overrides, self._trial_id)

    def drop(self, namespace: str) -> None:
        self._store.drop(namespace)


class _StreamKvView:
    def __init__(self, inner, overrides: Dict[str, str], trial_id: str) -> None:
        self._inner = inner
        self._overrides = overrides
        self._trial_id = trial_id

    def get_ok(self, key: str):
        raw, ok = self._inner.get_ok(key)
        if not ok or key not in self._overrides:
            return raw, ok
        sql = raw["sql"] if isinstance(raw, dict) else raw
        from ..sql.parser import parse

        stmt = parse(sql)
        stmt.options.type = "simulator"
        conf_key = self._overrides[key].split(":", 1)[1]
        # rebuild DDL with simulator type/conf_key
        fields = ", ".join(
            f"{f.name} {f.type.value.upper()}" for f in stmt.fields
        )
        new_sql = (
            f"CREATE {'TABLE' if stmt.is_table else 'STREAM'} {stmt.name} "
            f"({fields}) WITH (TYPE=\"simulator\", CONF_KEY=\"{conf_key}\", "
            f"DATASOURCE=\"trial\")"
        )
        return {"sql": new_sql}, True

    def __getattr__(self, name):
        return getattr(self._inner, name)
