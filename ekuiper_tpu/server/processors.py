"""Definition processors — analogue of eKuiper's internal/processor:
StreamProcessor.ExecStmt (stream.go:73,229) for DDL, RuleProcessor (rule.go)
for rule defs, RulesetProcessor for import/export.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from ..planner.planner import RuleDef
from ..sql import ast
from ..sql.parser import parse
from ..store import kv
from ..utils.infra import EngineError, ParseError, PlanError


class StreamProcessor:
    def __init__(self, store=None) -> None:
        self.store = store or kv.get_store()

    def _table_for(self, is_table: bool):
        return self.store.kv("table" if is_table else "stream")

    def exec_stmt(self, sql: str) -> Any:
        """Execute a DDL statement; returns a result payload like the
        reference's CLI/REST responses."""
        stmt = parse(sql)
        if isinstance(stmt, ast.StreamStmt):
            return self.create(stmt, sql)
        if isinstance(stmt, ast.ShowStmt):
            return self.show(stmt.target == "TABLES")
        if isinstance(stmt, ast.DescribeStmt):
            return self.describe(stmt.name, stmt.target == "TABLE")
        if isinstance(stmt, ast.DropStmt):
            return self.drop(stmt.name, stmt.target == "TABLE")
        raise ParseError("unsupported statement for stream processor")

    def create(self, stmt: ast.StreamStmt, sql: str) -> str:
        table = self._table_for(stmt.is_table)
        if not table.setnx(stmt.name, {"sql": sql}):
            kind = "table" if stmt.is_table else "stream"
            raise PlanError(f"{kind} {stmt.name} already exists")
        return f"{'Table' if stmt.is_table else 'Stream'} {stmt.name} is created."

    def show(self, tables: bool = False) -> List[str]:
        return sorted(self._table_for(tables).keys())

    def describe(self, name: str, is_table: bool = False) -> Dict[str, Any]:
        raw, ok = self._table_for(is_table).get_ok(name)
        if not ok:
            raise PlanError(f"{'table' if is_table else 'stream'} {name} not found")
        stmt = parse(raw["sql"])
        return {
            "name": stmt.name,
            "fields": [
                {"name": f.name, "type": f.type.value} for f in stmt.fields
            ],
            "options": stmt.options.to_dict(),
            "sql": raw["sql"],
        }

    def drop(self, name: str, is_table: bool = False) -> str:
        if not self._table_for(is_table).delete(name):
            raise PlanError(f"{'table' if is_table else 'stream'} {name} not found")
        return f"{'Table' if is_table else 'Stream'} {name} is dropped."


class RuleProcessor:
    def __init__(self, store=None) -> None:
        self.store = store or kv.get_store()

    def _table(self):
        return self.store.kv("rule")

    def create(self, rule_json: Dict[str, Any]) -> RuleDef:
        rule = RuleDef.from_dict(rule_json)
        if not rule.id:
            raise PlanError("rule id is required")
        if not rule.sql and rule.graph is None:
            raise PlanError("rule sql or graph is required")
        if not self._table().setnx(rule.id, rule.to_dict()):
            raise PlanError(f"rule {rule.id} already exists")
        return rule

    def update(self, rule_json: Dict[str, Any]) -> RuleDef:
        rule = RuleDef.from_dict(rule_json)
        _, ok = self._table().get_ok(rule.id)
        if not ok:
            raise PlanError(f"rule {rule.id} not found")
        self._table().set(rule.id, rule.to_dict())
        return rule

    def get(self, rule_id: str) -> RuleDef:
        raw, ok = self._table().get_ok(rule_id)
        if not ok:
            raise PlanError(f"rule {rule_id} not found")
        return RuleDef.from_dict(raw)

    def list(self) -> List[str]:
        return sorted(self._table().keys())

    def drop(self, rule_id: str) -> None:
        if not self._table().delete(rule_id):
            raise PlanError(f"rule {rule_id} not found")
        # drop checkpoint state too
        self.store.drop(f"checkpoint:{rule_id}")


class RulesetProcessor:
    """Import/export of streams+tables+rules as one JSON document
    (reference: internal/processor/ruleset.go)."""

    def __init__(self, store=None) -> None:
        self.store = store or kv.get_store()

    def export(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"streams": {}, "tables": {}, "rules": {},
                               "scripts": {}}
        for name, v in self.store.kv("stream").items():
            out["streams"][name] = v["sql"]
        for name, v in self.store.kv("table").items():
            out["tables"][name] = v["sql"]
        for rid, v in self.store.kv("rule").items():
            out["rules"][rid] = v
        mgr = self._script_mgr()
        for name in mgr.list():
            out["scripts"][name] = mgr.get(name)
        return out

    def _script_mgr(self):
        """Scripts must come from/go to THIS processor's store (the global
        manager may be backed by a different one, e.g. importing into a
        fresh store); binding side effects are idempotent."""
        from ..plugin.script import ScriptManager

        return ScriptManager(self.store)

    def import_ruleset(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        counts: Dict[str, Any] = {"streams": 0, "tables": 0, "rules": 0,
                                  "scripts": 0}
        for name, sql in doc.get("streams", {}).items():
            self.store.kv("stream").set(name, {"sql": sql})
            counts["streams"] += 1
        for name, sql in doc.get("tables", {}).items():
            self.store.kv("table").set(name, {"sql": sql})
            counts["tables"] += 1
        for rid, rule in doc.get("rules", {}).items():
            if isinstance(rule, str):
                rule = json.loads(rule)
            rule.setdefault("id", rid)
            self.store.kv("rule").set(rid, rule)
            counts["rules"] += 1
        # scripts (reference rulesets carry JS bodies — they must be
        # translated to Python first; per-script errors are reported, the
        # rest of the import proceeds. docs/JS_MIGRATION.md)
        script_errors: Dict[str, str] = {}
        scripts = doc.get("scripts", {}) or {}
        if scripts:
            mgr = self._script_mgr()
            for name, spec in scripts.items():
                try:
                    if isinstance(spec, str):
                        spec = {"id": name, "script": spec}
                    if not isinstance(spec, dict):
                        raise EngineError(
                            f"script spec must be an object or source "
                            f"string, got {type(spec).__name__}")
                    spec.setdefault("id", name)
                    mgr.create(spec, overwrite=True)
                    counts["scripts"] += 1
                except Exception as e:
                    script_errors[name] = str(e)
        if script_errors:
            counts["script_errors"] = script_errors
        return counts
