"""REST API — analogue of eKuiper's REST server (internal/server/rest.go:177-232).

Routes (matching the reference surface):
  GET  /                               server info
  GET  /ping
  POST /streams            {"sql": "CREATE STREAM ..."}
  GET  /streams | /tables
  GET|DELETE /streams/{name}, /tables/{name}
  GET  /streams/{name}/schema
  POST /rules              rule def json
  GET  /rules
  GET|PUT|DELETE /rules/{id}
  POST /rules/{id}/start|stop|restart|reset_state
  GET  /rules/{id}/status|topo|explain
  POST /rules/validate
  GET  /ruleset/export    POST /ruleset/import
  POST /ruletest  GET /ruletest/{id}  DELETE /ruletest/{id}   (trial runs)

Implementation: stdlib ThreadingHTTPServer — no external web framework, same
zero-dependency stance as the reference's single static binary.
"""
from __future__ import annotations

import json
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import __version__ as _version
from ..runtime.control import AdmissionRejected
from ..sql import ast
from ..sql.parser import parse
from ..utils.infra import EngineError, ParseError, PlanError, logger
from .processors import RulesetProcessor, StreamProcessor
from .rule_manager import RuleRegistry
from .trial import TrialManager

Route = Tuple[str, re.Pattern, Callable]


class RestApi:
    """Route table + handlers, independent of the HTTP layer (testable)."""

    def __init__(self, store) -> None:
        self.store = store
        self.streams = StreamProcessor(store)
        self.rules = RuleRegistry(store)
        self.ruleset = RulesetProcessor(store)
        self.trials = TrialManager(store)
        self._import_status: Dict[str, Any] = {"status": "none"}
        self.routes: List[Route] = []
        r = self._route
        r("GET", r"^/$", self.info)
        r("GET", r"^/ping$", lambda m: {"ok": True})
        r("POST", r"^/streams$", self.create_def)
        r("POST", r"^/tables$", self.create_def)
        r("GET", r"^/streams$", lambda m: self.streams.show(False))
        r("GET", r"^/tables$", lambda m: self.streams.show(True))
        r("GET", r"^/streams/(?P<name>[^/]+)$",
          lambda m: self.streams.describe(m["name"], False))
        r("GET", r"^/tables/(?P<name>[^/]+)$",
          lambda m: self.streams.describe(m["name"], True))
        r("GET", r"^/streams/(?P<name>[^/]+)/schema$",
          lambda m: self.streams.describe(m["name"], False)["fields"])
        r("DELETE", r"^/streams/(?P<name>[^/]+)$",
          lambda m: self.streams.drop(m["name"], False))
        r("DELETE", r"^/tables/(?P<name>[^/]+)$",
          lambda m: self.streams.drop(m["name"], True))
        r("POST", r"^/rules$", self.create_rule)
        r("GET", r"^/rules$",
          lambda m, query=None: self.rules.list(
              tags=[t for t in (query or {}).get("tags", "").split(",") if t]
              or None))
        r("PUT", r"^/rules/(?P<id>[^/]+)/tags$",
          lambda m, body=None: self.rules.set_tags(
              m["id"], (body or {}).get("tags") or [], add=True)
          or f"Rule {m['id']} tags updated.")
        r("DELETE", r"^/rules/(?P<id>[^/]+)/tags$",
          lambda m, body=None: self.rules.set_tags(
              m["id"], (body or {}).get("tags") or [], add=False)
          or f"Rule {m['id']} tags removed.")
        r("POST", r"^/rules/validate$",
          lambda m, body=None: self.rules.validate(body))
        r("GET", r"^/rules/(?P<id>[^/]+)$",
          lambda m: self.rules.processor.get(m["id"]).to_dict())
        r("PUT", r"^/rules/(?P<id>[^/]+)$", self.update_rule)
        r("DELETE", r"^/rules/(?P<id>[^/]+)$",
          lambda m: self.rules.delete(m["id"]) or "Rule %s is dropped." % m["id"])
        r("POST", r"^/rules/(?P<id>[^/]+)/start$",
          lambda m: self.rules.start(m["id"]) or "Rule %s was started" % m["id"])
        r("POST", r"^/rules/(?P<id>[^/]+)/stop$",
          lambda m: self.rules.stop(m["id"]) or "Rule %s was stopped." % m["id"])
        r("POST", r"^/rules/(?P<id>[^/]+)/restart$",
          lambda m: self.rules.restart(m["id"]) or "Rule %s was restarted" % m["id"])
        r("POST", r"^/rules/(?P<id>[^/]+)/reset_state$",
          lambda m: self.rules.reset_state(m["id"]) or "Rule %s state was reset" % m["id"])
        r("GET", r"^/rules/usage/cpu$",
          lambda m: self.rules.cpu_usage())
        r("GET", r"^/rules/usage/latency$",
          lambda m: self.rules.latency_usage())
        r("GET", r"^/rules/(?P<id>[^/]+)/status$",
          lambda m: self.rules.status(m["id"]))
        r("GET", r"^/rules/(?P<id>[^/]+)/health$",
          lambda m: self.rule_health(m["id"]))
        r("GET", r"^/rules/(?P<id>[^/]+)/topo$",
          lambda m: self.rules.topo_json(m["id"]))
        r("GET", r"^/rules/(?P<id>[^/]+)/explain$",
          lambda m: self.rules.explain(m["id"]))
        r("GET", r"^/ruleset/export$", lambda m: self.ruleset.export())
        r("POST", r"^/ruleset/import$",
          lambda m, body=None: self.ruleset.import_ruleset(body))
        # full-state import/export with async mode (reference rest.go
        # /data/import /data/export + importStatus)
        r("GET", r"^/data/export$", lambda m: self.ruleset.export())
        r("POST", r"^/data/import$", self.data_import)
        r("GET", r"^/data/import/status$", lambda m: dict(self._import_status))
        # runtime config overlay (reference PATCH /configs,
        # internal/server/rest.go configurationPatch)
        r("PATCH", r"^/configs$", self.patch_configs)
        r("GET", r"^/configs$", lambda m: self._config_overlay())
        # file uploads (reference rest.go /config/uploads)
        r("GET", r"^/config/uploads$", lambda m: self.list_uploads())
        r("POST", r"^/config/uploads$", self.create_upload)
        r("DELETE", r"^/config/uploads/(?P<name>[^/]+)$",
          lambda m: self.delete_upload(m["name"]))
        r("POST", r"^/ruletest$", lambda m, body=None: self.trials.create(body))
        r("POST", r"^/ruletest/(?P<id>[^/]+)/start$",
          lambda m: self.trials.start(m["id"]))
        r("GET", r"^/ruletest/(?P<id>[^/]+)$", lambda m: self.trials.results(m["id"]))
        r("DELETE", r"^/ruletest/(?P<id>[^/]+)$", lambda m: self.trials.stop(m["id"]))
        # schema registry (reference: internal/server/rest.go schema routes,
        # internal/schema/registry.go:49-184)
        r("GET", r"^/schemas/protobuf$", lambda m: self._schemas().list())
        r("POST", r"^/schemas/protobuf$",
          lambda m, body=None: self._schemas().create(body or {})
          or f"Schema {(body or {}).get('name')} is created.")
        r("GET", r"^/schemas/protobuf/(?P<name>[^/]+)$", self.describe_schema)
        r("PUT", r"^/schemas/protobuf/(?P<name>[^/]+)$",
          lambda m, body=None: self._schemas().create(
              {**(body or {}), "name": m["name"]}, overwrite=True)
          or f"Schema {m['name']} is updated.")
        r("DELETE", r"^/schemas/protobuf/(?P<name>[^/]+)$",
          lambda m: self._schemas().delete(m["name"])
          or f"Schema {m['name']} is dropped.")
        # script UDFs (reference: rpc_script.go CreateScript/DescScript/...)
        r("GET", r"^/scripts$", lambda m: self._scripts().list())
        r("POST", r"^/scripts$",
          lambda m, body=None: self._scripts().create(body or {})
          or f"Script {body.get('id')} is created.")
        r("GET", r"^/scripts/(?P<name>[^/]+)$", self.describe_script)
        r("PUT", r"^/scripts/(?P<name>[^/]+)$",
          lambda m, body=None: self._scripts().update(
              {**(body or {}), "id": m["name"]})
          or f"Script {m['name']} is updated.")
        r("DELETE", r"^/scripts/(?P<name>[^/]+)$",
          lambda m: self._scripts().delete(m["name"])
          or f"Script {m['name']} is dropped.")
        # UI metadata + confKey profiles (reference internal/meta routes)
        r("GET", r"^/metadata/sources$",
          lambda m: self._meta().list_sources())
        r("GET", r"^/metadata/sinks$", lambda m: self._meta().list_sinks())
        r("GET", r"^/metadata/functions$",
          lambda m: self._meta().list_functions())
        r("GET", r"^/metadata/functions/(?P<name>[^/]+)$",
          lambda m: self._meta().describe_function(m["name"]))
        r("GET", r"^/metadata/sources/(?P<name>[^/]+)$",
          lambda m: self._meta().describe_source(m["name"]))
        r("GET", r"^/metadata/sinks/(?P<name>[^/]+)$",
          lambda m: self._meta().describe_sink(m["name"]))
        r("GET", r"^/metadata/sources/(?P<typ>[^/]+)/confKeys$",
          lambda m: self.list_conf_keys(m["typ"]))
        r("PUT", r"^/metadata/sources/(?P<typ>[^/]+)/confKeys/(?P<key>[^/]+)$",
          lambda m, body=None: self.set_conf_key(m["typ"], m["key"], body)
          or f"confKey {m['key']} is saved.")
        r("DELETE",
          r"^/metadata/sources/(?P<typ>[^/]+)/confKeys/(?P<key>[^/]+)$",
          lambda m: self.del_conf_key(m["typ"], m["key"])
          or f"confKey {m['key']} is dropped.")
        # observability (reference: prome_init.go /metrics, pkg/tracer
        # trace routes, metrics/metrics_dump.go)
        r("GET", r"^/metrics$", lambda m: self.prometheus_metrics())
        r("GET", r"^/metrics/dump$", lambda m: self.metrics_dump())
        # engine-health diagnostics: the flight recorder's event ring,
        # per-component device/host memory accounting, and the XLA
        # compile watcher — the views tools/kuiperdiag.py bundles
        r("GET", r"^/diagnostics/events$",
          lambda m, query=None: self.diagnostics_events(query or {}))
        r("GET", r"^/diagnostics/memory$",
          lambda m: self.diagnostics_memory())
        r("GET", r"^/diagnostics/xla$", lambda m: self.diagnostics_xla())
        r("GET", r"^/diagnostics/kernels$",
          lambda m: self.diagnostics_kernels())
        # health plane: per-rule SLO verdicts + engine view, and the
        # on-demand bounded profiler capture (observability/health.py)
        r("GET", r"^/diagnostics/health$",
          lambda m: self.diagnostics_health())
        r("POST", r"^/diagnostics/profile$",
          lambda m, body=None: self.diagnostics_profile(body or {}))
        # QoS control plane: admission counters + queue, shed state,
        # autosize log (runtime/control.py)
        r("GET", r"^/diagnostics/control$",
          lambda m: self.diagnostics_control())
        # tiered key state: per-rule hot/cold placement counters and the
        # host spill arena (ops/tierstore.py)
        r("GET", r"^/diagnostics/tier$",
          lambda m: self.diagnostics_tier())
        # fleet observatory: mesh skew/collective attribution
        # (observability/meshwatch.py) and the durable telemetry
        # timeline's replay (observability/timeline.py)
        r("GET", r"^/diagnostics/mesh$",
          lambda m: self.diagnostics_mesh())
        r("GET", r"^/diagnostics/timeline$",
          lambda m, query=None: self.diagnostics_timeline(query or {}))
        r("POST", r"^/rules/(?P<id>[^/]+)/trace/start$",
          lambda m, body=None: self._tracer().enable(
              m["id"], (body or {}).get("strategy", "always"))
          or f"Tracing enabled for rule {m['id']}.")
        r("POST", r"^/rules/(?P<id>[^/]+)/trace/stop$",
          lambda m: self._tracer().disable(m["id"])
          or f"Tracing disabled for rule {m['id']}.")
        r("GET", r"^/trace/rule/(?P<id>[^/]+)$",
          lambda m: self._tracer().rule_traces(m["id"]))
        r("GET", r"^/trace/(?P<id>[^/]+)$",
          lambda m: self._tracer().trace(m["id"]))
        # connections CRUD + ping (reference: rest.go connection routes)
        r("GET", r"^/connections$", lambda m: self._connections().list())
        r("POST", r"^/connections$",
          lambda m, body=None: self._connections().create(body or {})
          or f"Connection {(body or {}).get('id')} is created.")
        r("GET", r"^/connections/(?P<id>[^/]+)/ping$",
          lambda m: self._connections().ping(m["id"]))
        r("GET", r"^/connections/(?P<id>[^/]+)$",
          lambda m: self._connections().get(m["id"]))
        r("PUT", r"^/connections/(?P<id>[^/]+)$",
          lambda m, body=None: self._connections().update(m["id"], body or {})
          or f"Connection {m['id']} is updated.")
        r("DELETE", r"^/connections/(?P<id>[^/]+)$",
          lambda m: self._connections().delete(m["id"])
          or f"Connection {m['id']} is deleted.")
        # external services (reference: rest.go service routes,
        # internal/service/manager.go)
        r("GET", r"^/services$", lambda m: self._services().list())
        r("POST", r"^/services$",
          lambda m, body=None: self._services().create(
              (body or {}).get("name", ""), (body or {}).get("file")
              or (body or {}).get("descriptor") or {})
          or f"Service {(body or {}).get('name')} is created.")
        r("GET", r"^/services/functions$",
          lambda m: self._services().list_functions())
        r("GET", r"^/services/functions/(?P<name>[^/]+)$",
          lambda m: self._services().describe_function(m["name"]))
        r("GET", r"^/services/(?P<name>[^/]+)$",
          lambda m: self._services().describe(m["name"]))
        r("PUT", r"^/services/(?P<name>[^/]+)$",
          lambda m, body=None: self._services().create(
              m["name"], (body or {}).get("descriptor") or body or {},
              overwrite=True)
          or f"Service {m['name']} is updated.")
        r("DELETE", r"^/services/(?P<name>[^/]+)$",
          lambda m: self._services().delete(m["name"])
          or f"Service {m['name']} is deleted.")
        # portable plugins (reference: rest.go plugin routes)
        r("GET", r"^/plugins/portables$", lambda m: self._plugins().list())
        r("POST", r"^/plugins/portables$", self.install_plugin)
        r("GET", r"^/plugins/portables/(?P<name>[^/]+)$", self.describe_plugin)
        r("DELETE", r"^/plugins/portables/(?P<name>[^/]+)$",
          lambda m: self._plugins().delete(m["name"]) or f"Plugin {m['name']} is deleted.")
        # health evaluator: periodic per-rule SLO/bottleneck/watermark
        # verdicts over this registry's live topos
        from ..observability import health as _health

        self.health_evaluator = _health.install(self._health_rules)
        # QoS controller: acts on the evaluator's verdicts — admission
        # queue retries, per-rule SLO shedding, decode-pool autosizing
        from ..runtime import control as _control

        self.qos_controller = _control.install(
            self._health_rules, start_fn=self.rules.start,
            unqueue_fn=lambda rid: self.store.kv(
                "admission_queue").delete(rid))
        # durable telemetry timeline: periodic delta-encoded snapshots of
        # the full /metrics render + health verdicts into on-disk
        # segments under the store path (observability/timeline.py); the
        # flight recorder mirrors events in as they happen
        from ..observability import timeline as _timeline

        self.timeline = _timeline.install(
            scrape_fn=lambda: str(self.prometheus_metrics()),
            verdicts_fn=lambda: self.health_evaluator.verdicts())

    # ----------------------------------------------------- data import/export
    def data_import(self, m, body: Optional[dict] = None,
                    query: Optional[dict] = None) -> Any:
        """POST /data/import — ?partial=true merges into the running system;
        the default (full import) stops every rule first, then imports
        (reference rest.go importHandler semantics). ?async=true runs in the
        background with progress at /data/import/status."""
        doc = (body or {}).get("content") or body or {}
        if isinstance(doc, str):
            doc = json.loads(doc)
        partial = (query or {}).get("partial") in ("true", "1")

        def run():
            self._import_status.update(status="importing")
            try:
                if not partial:
                    self.rules.stop_all()
                counts = self.ruleset.import_ruleset(doc)
                self._import_status.update(status="done", counts=counts)
            except Exception as exc:
                self._import_status.update(status="error", error=str(exc))

        if (query or {}).get("async") in ("true", "1"):
            self._import_status = {"status": "importing"}
            threading.Thread(target=run, daemon=True,
                             name="data-import").start()
            return "Import started; poll /data/import/status."
        self._import_status = {"status": "importing"}
        run()
        if self._import_status.get("status") == "error":
            raise EngineError(self._import_status.get("error", "import failed"))
        return self._import_status.get("counts")

    # ----------------------------------------------------------- config patch
    def patch_configs(self, m, body: Optional[dict] = None) -> str:
        """PATCH /configs: runtime-adjustable basics (log level, timezone)
        persisted as an overlay in the KV store."""
        from ..utils.config import get_config

        body = body or {}
        cfg = get_config()
        overlay_kv = self.store.kv("config_overlay")
        basic = body.get("basic", body)
        allowed = {"log_level", "time_zone", "ignore_case", "prometheus"}
        # validate the whole batch BEFORE mutating live config — a rejected
        # key must not leave a half-applied patch
        applied = {}
        for key, val in basic.items():
            norm = key.replace("logLevel", "log_level").replace(
                "timezone", "time_zone")
            if norm not in allowed:
                raise EngineError(f"config key {key!r} is not patchable")
            applied[norm] = val
        for norm, val in applied.items():
            setattr(cfg.basic, norm, val)
        if "log_level" in applied:
            import logging as _logging

            logger.setLevel(getattr(
                _logging, str(applied["log_level"]).upper(), _logging.INFO))
        for k, v in applied.items():
            overlay_kv.set(k, v)
        return f"Configuration patched: {sorted(applied)}"

    def _config_overlay(self) -> Dict[str, Any]:
        from ..utils.config import get_config

        cfg = get_config()
        return {"basic": {
            "log_level": cfg.basic.log_level,
            "time_zone": cfg.basic.time_zone,
            "ignore_case": cfg.basic.ignore_case,
            "prometheus": cfg.basic.prometheus,
            "rest_port": cfg.basic.rest_port,
        }}

    # ---------------------------------------------------------------- uploads
    def _uploads_dir(self) -> str:
        from ..utils.config import get_config

        path = os.path.join(get_config().store.path, "uploads")
        os.makedirs(path, exist_ok=True)
        return path

    @staticmethod
    def _safe_name(name: str) -> str:
        base = os.path.basename(name or "")
        if not base or base != name:
            raise EngineError(f"invalid upload name {name!r}")
        return base

    def list_uploads(self) -> List[str]:
        return sorted(os.listdir(self._uploads_dir()))

    def create_upload(self, m, body: Optional[dict] = None) -> str:
        body = body or {}
        name = self._safe_name(body.get("name", ""))
        path = os.path.join(self._uploads_dir(), name)
        if "base64" in body:
            import base64

            data = base64.b64decode(body["base64"])
            with open(path, "wb") as f:
                f.write(data)
        else:
            with open(path, "w") as f:
                f.write(str(body.get("content", "")))
        return path

    def delete_upload(self, name: str) -> str:
        path = os.path.join(self._uploads_dir(), self._safe_name(name))
        if not os.path.isfile(path):
            raise EngineError(f"upload {name} not found")
        os.remove(path)
        return f"Upload {name} is deleted."

    # ------------------------------------------------------------- metadata
    @staticmethod
    def _meta():
        from .. import meta

        return meta

    def list_conf_keys(self, typ: str) -> List[str]:
        prefix = f"{typ}:"
        return sorted(k[len(prefix):]
                      for k in self.store.kv("source_conf").keys()
                      if k.startswith(prefix))

    def set_conf_key(self, typ: str, key: str, body: Optional[dict]) -> None:
        if not isinstance(body, dict):
            raise EngineError("confKey body must be a json object")
        self.store.kv("source_conf").set(f"{typ}:{key}", body)

    def del_conf_key(self, typ: str, key: str) -> None:
        if not self.store.kv("source_conf").delete(f"{typ}:{key}"):
            raise EngineError(f"confKey {typ}:{key} not found")

    # ---------------------------------------------------------- observability
    @staticmethod
    def _tracer():
        from ..observability.tracer import Tracer

        return Tracer.global_instance()

    def prometheus_metrics(self):
        from ..observability import prometheus

        return prometheus.TextResponse(prometheus.render(self.rules))

    def _health_rules(self) -> List[tuple]:
        """(rule_id, topo, options) triples for the health evaluator —
        every rule with a live topo."""
        out = []
        for entry in self.rules.list():
            rid = entry.get("id")
            if not rid:
                continue
            rs = self.rules.state(rid)
            if rs is None or rs.topo is None:
                continue
            out.append((rid, rs.topo, rs.rule.options))
        return out

    def rule_health(self, rule_id: str) -> Dict[str, Any]:
        """GET /rules/{id}/health — the rule's last health verdict (one
        synchronous tick seeds it when the evaluator hasn't seen the rule
        yet)."""
        from ..observability import health

        self.rules.processor.get(rule_id)  # 400 on unknown rule
        ev = health.evaluator() or self.health_evaluator
        # only let the request force a seeding tick when the rule is
        # actually evaluable (live topo — the same per-entry test
        # _health_rules applies): a stopped rule never grows a track,
        # and a forced tick PER POLL would decay every other rule's
        # burn windows and hysteresis off-cadence
        rs = self.rules.state(rule_id)
        evaluable = rs is not None and rs.topo is not None
        verdict = ev.rule_health(rule_id, refresh_if_missing=evaluable)
        if verdict is None:
            if evaluable:
                # the rule IS running; its per-tick evaluation raises
                return {"rule": rule_id, "state": "unknown",
                        "reason": "health evaluation is failing for "
                                  "this rule; see engine log"}
            return {"rule": rule_id, "state": "unknown",
                    "reason": "rule is not running (no live topo to "
                              "evaluate)"}
        return verdict

    def diagnostics_health(self) -> Dict[str, Any]:
        """GET /diagnostics/health — every rule's verdict plus the
        evaluator/HBM engine view."""
        from ..observability import health

        ev = health.evaluator() or self.health_evaluator
        # seed only rules the evaluator has never ATTEMPTED (no track) —
        # keying on missing verdicts would re-tick on every poll for a
        # rule whose evaluation persistently raises
        if any(not ev.has_track(rid) for rid, _topo, _o in
               self._health_rules()):
            ev.tick()  # a live rule the periodic tick hasn't seen yet
        return ev.diagnostics()

    @staticmethod
    def diagnostics_profile(body: Dict[str, Any]) -> Dict[str, Any]:
        """POST /diagnostics/profile {duration_ms?, out_dir?} — bounded
        jax.profiler trace + devwatch/memwatch/health dump into a bundle
        directory (collected by tools/kuiperdiag.py --profile)."""
        from ..observability import health

        try:
            duration = int(body.get("duration_ms", 1000))
        except (TypeError, ValueError):
            raise EngineError(
                f"invalid duration_ms {body.get('duration_ms')!r}")
        out_dir = body.get("out_dir") or None
        if out_dir is not None:
            # the REST port is the untrusted boundary: an arbitrary
            # out_dir would let any client create directories and write
            # files anywhere the engine user can — captures over HTTP
            # must land under the store path (capture_profile itself
            # stays flexible for in-process tools/tests)
            from ..utils.config import get_config

            base = os.path.realpath(get_config().store.path)
            cand = os.path.realpath(out_dir)
            if cand != base and not cand.startswith(base + os.sep):
                raise EngineError(
                    f"out_dir must be under the store path {base!r}")
            out_dir = cand
        try:
            return health.capture_profile(duration_ms=duration,
                                          out_dir=out_dir)
        except RuntimeError as exc:
            raise EngineError(str(exc))

    @staticmethod
    def diagnostics_events(query: Dict[str, str]) -> Dict[str, Any]:
        """GET /diagnostics/events?kind=&rule=&limit=&since= — the flight
        recorder's ring, oldest→newest (since returns only events with
        seq > since, for incremental tailing; limit keeps the newest n,
        or the OLDEST n when combined with since so a tailer pages
        forward without skipping)."""
        from ..runtime.events import recorder

        limit = None
        if query.get("limit"):
            try:
                limit = max(int(query["limit"]), 0)
            except ValueError:
                raise EngineError(f"invalid limit {query['limit']!r}")
        since = None
        if query.get("since"):
            try:
                since = max(int(query["since"]), 0)
            except ValueError:
                raise EngineError(f"invalid since {query['since']!r}")
        return recorder().diagnostics(
            kind=query.get("kind") or None,
            rule=query.get("rule") or None, limit=limit, since=since)

    def diagnostics_control(self) -> Dict[str, Any]:
        """GET /diagnostics/control — the QoS control plane's admission
        counters/queue, per-rule shed state, and autosize log."""
        from ..runtime import control

        ctl = control.controller() or self.qos_controller
        return ctl.diagnostics()

    @staticmethod
    def diagnostics_mesh() -> Dict[str, Any]:
        """GET /diagnostics/mesh — fleet observatory: per-rule shard skew
        report + collective-vs-compute split (observability/meshwatch.py)
        and the controller's rebalance-hint state."""
        from ..observability import meshwatch
        from ..runtime import control

        out = meshwatch.diagnostics()
        ctl = control.controller()
        if ctl is not None:
            out["control"] = ctl.diagnostics().get("mesh")
        return out

    def diagnostics_timeline(self, query: Dict[str, str]) -> Dict[str, Any]:
        """GET /diagnostics/timeline?family=&rule=&since=&limit= — replay
        the durable telemetry ring (observability/timeline.py): family
        filters by series name (`kuiper_shard_*` prefix form allowed),
        since by engine ms, limit keeps the newest n records."""
        from ..observability import timeline as _timeline

        tl = _timeline.timeline() or getattr(self, "timeline", None)
        if tl is None:
            raise EngineError("timeline not installed")
        limit = 200
        if query.get("limit"):
            try:
                limit = max(int(query["limit"]), 0)
            except ValueError:
                raise EngineError(f"invalid limit {query['limit']!r}")
        since = None
        if query.get("since"):
            try:
                since = max(int(query["since"]), 0)
            except ValueError:
                raise EngineError(f"invalid since {query['since']!r}")
        out = tl.query(family=query.get("family") or None,
                       rule=query.get("rule") or None,
                       since=since, limit=limit)
        if query.get("dump") in ("1", "true"):
            # kuiperdiag --timeline: pack the raw segments (bounded) so
            # the bundle carries the replayable ring, not just a query
            out["segment_dump"] = tl.segment_dump()
        return out

    @staticmethod
    def diagnostics_tier() -> Dict[str, Any]:
        """GET /diagnostics/tier — per-tiered-rule placement state:
        demote/promote/recycle counters, cold-tier residency, host arena
        bytes, and the plan-time geometry (ops/tierstore.py)."""
        from ..ops import tierstore

        return {"rules": tierstore.diagnostics()}

    @staticmethod
    def diagnostics_memory() -> Dict[str, Any]:
        """GET /diagnostics/memory — per-component byte probes plus the
        jax.live_arrays() allocator view."""
        from ..observability import memwatch

        return memwatch.diagnostics()

    @staticmethod
    def diagnostics_xla() -> Dict[str, Any]:
        """GET /diagnostics/xla — per-site compile/cache-hit accounting
        plus the jitcert compile-contract diff: every observed signature
        outside a site's certified set is reported individually (an
        uncertified signature is the report, not a counter)."""
        from ..observability import devwatch, jitcert

        reg = devwatch.registry()
        out = {"totals": reg.totals(),
               "sites": [w.snapshot() for w in reg.watches()]}
        try:
            out["jitcert"] = jitcert.diff_live()
        except Exception as exc:  # diagnostics degrade, never 500
            out["jitcert"] = {"error": str(exc)}
        return out

    @staticmethod
    def diagnostics_kernels() -> Dict[str, Any]:
        """GET /diagnostics/kernels — sampled device-time split, XLA cost
        estimates, and roofline utilization per jit site
        (observability/kernwatch.py)."""
        from ..observability import kernwatch

        return kernwatch.diagnostics()

    def metrics_dump(self):
        """Write every rule's status snapshot to the data dir and return the
        dump (reference metrics/metrics_dump.go:40-85)."""
        import os

        from ..utils.config import get_config

        lines = []
        for entry in self.rules.list():
            rid = entry["id"]
            try:
                lines.append(json.dumps(
                    {"rule": rid, "status": self.rules.status(rid)}))
            except Exception as exc:
                lines.append(json.dumps({"rule": rid, "error": str(exc)}))
        content = "\n".join(lines) + "\n"
        path = os.path.join(get_config().store.path, "metrics.dump")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            f.write(content)
        return {"file": path, "rules": len(lines)}

    # ------------------------------------------------------------ connections
    def _connections(self):
        from ..io.connections import ConnectionManager

        return ConnectionManager(self.store)

    # --------------------------------------------------------------- services
    @staticmethod
    def _services():
        from ..services.manager import ServiceManager

        return ServiceManager.global_instance()

    # ---------------------------------------------------------------- schemas
    @staticmethod
    def _schemas():
        from ..schema.registry import SchemaRegistry

        return SchemaRegistry.global_instance()

    def describe_schema(self, m) -> Dict[str, Any]:
        spec = self._schemas().get(m["name"])
        if spec is None:
            raise EngineError(f"schema {m['name']} not found")
        return spec

    # ---------------------------------------------------------------- scripts
    @staticmethod
    def _scripts():
        from ..plugin.script import ScriptManager

        return ScriptManager.global_instance()

    def describe_script(self, m) -> Dict[str, Any]:
        spec = self._scripts().get(m["name"])
        if spec is None:
            raise EngineError(f"script {m['name']} not found")
        return spec

    # ---------------------------------------------------------------- plugins
    @staticmethod
    def _plugins():
        from ..plugin.manager import PortableManager

        return PortableManager.global_instance()

    def install_plugin(self, m, body: Optional[dict] = None) -> str:
        from ..plugin.manager import PluginMeta

        if not body or "name" not in body or "executable" not in body:
            raise ParseError("body must contain name and executable")
        self._plugins().register(PluginMeta.from_dict(body))
        return f"Plugin {body['name']} is created."

    def describe_plugin(self, m) -> Dict[str, Any]:
        meta = self._plugins().get(m["name"])
        if meta is None:
            raise EngineError(f"plugin {m['name']} not found")
        return meta.to_dict()

    def _route(self, method: str, pattern: str, fn: Callable) -> None:
        self.routes.append((method, re.compile(pattern), fn))

    # ---------------------------------------------------------------- handlers
    def info(self, m) -> Dict[str, Any]:
        import jax

        return {
            "version": _version,
            "engine": "ekuiper_tpu",
            "backend": str(jax.devices()[0]) if jax.devices() else "none",
        }

    def create_def(self, m, body: Optional[dict] = None) -> str:
        if not body or "sql" not in body:
            raise ParseError("body must contain a sql field")
        return self.streams.exec_stmt(body["sql"])

    def create_rule(self, m, body: Optional[dict] = None) -> Any:
        if not body:
            raise ParseError("rule json body required")
        rule_id = self.rules.create(body)
        from ..runtime import control

        ctl = control.controller()
        queued = ctl.queued(rule_id) if ctl is not None else None
        if queued is not None:
            return {"id": rule_id, "admission": "queued",
                    "reason": queued.get("reason", ""),
                    "message": f"Rule {rule_id} was created and queued "
                               "by admission control."}
        return f"Rule {rule_id} was created successfully."

    def update_rule(self, m, body: Optional[dict] = None) -> str:
        if not body:
            raise ParseError("rule json body required")
        body.setdefault("id", m["id"])
        self.rules.update(body)
        return f"Rule {m['id']} was updated successfully."

    # --------------------------------------------------------------- dispatch
    def dispatch(self, method: str, path: str, body: Optional[dict],
                 query: Optional[Dict[str, str]] = None) -> Tuple[int, Any]:
        for rmethod, pattern, fn in self.routes:
            if rmethod != method:
                continue
            match = pattern.match(path)
            if match is None:
                continue
            kwargs = {}
            import inspect

            params = inspect.signature(fn).parameters
            if "body" in params:
                kwargs["body"] = body
            if "query" in params:
                kwargs["query"] = query or {}
            try:
                result = fn(match.groupdict(), **kwargs)
                code = 201 if method == "POST" and path in ("/streams", "/tables", "/rules") else 200
                return code, result
            except (ParseError, PlanError) as exc:
                return 400, {"error": str(exc)}
            except AdmissionRejected as exc:
                # structured refusal (reason + price), not an opaque
                # error string — 429: the engine is declining load, the
                # rule definition itself may be perfectly valid
                return 429, {"error": str(exc),
                             "admission": exc.decision}
            except EngineError as exc:
                return 400, {"error": str(exc)}
            except Exception as exc:  # noqa: BLE001
                logger.exception("handler error %s %s", method, path)
                return 500, {"error": str(exc)}
        return 404, {"error": f"no route {method} {path}"}


#: routes reachable without a token when authentication is on (reference
#: leaves ping-style endpoints open)
_AUTH_EXEMPT = {"/", "/ping"}


def _b64url_decode(s: str) -> bytes:
    import base64

    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def _auth_check(headers, path: str) -> Optional[str]:
    """HS256 JWT bearer validation when basic.authentication is on.
    Returns an error string or None. Checks signature and exp."""
    from ..utils.config import get_config

    cfg = get_config().basic
    if not cfg.authentication or path in _AUTH_EXEMPT:
        return None
    if not cfg.jwt_secret:
        # fail closed: HMAC with an empty key is forgeable by anyone
        return "authentication enabled but no jwt_secret configured"
    auth = headers.get("Authorization", "")
    if not auth.startswith("Bearer "):
        return "missing bearer token"
    token = auth[len("Bearer "):].strip()
    try:
        import hashlib
        import hmac
        import time as _t

        head_b64, payload_b64, sig_b64 = token.split(".")
        header = json.loads(_b64url_decode(head_b64))
        if header.get("alg") != "HS256":
            return f"unsupported jwt alg {header.get('alg')!r}"
        expect = hmac.new(
            cfg.jwt_secret.encode(), f"{head_b64}.{payload_b64}".encode(),
            hashlib.sha256).digest()
        if not hmac.compare_digest(expect, _b64url_decode(sig_b64)):
            return "invalid token signature"
        payload = json.loads(_b64url_decode(payload_b64))
        if "exp" in payload and _t.time() > float(payload["exp"]):
            return "token expired"
        return None
    except Exception as exc:
        return f"malformed token: {exc}"


def serve(api: RestApi, host: str = "127.0.0.1", port: int = 9081):
    """Start the HTTP server (returns the server; call .shutdown() to stop)."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # route to engine logger
            logger.debug("rest: " + fmt, *args)

        def _handle(self, method: str) -> None:
            parsed = urlparse(self.path)
            path = parsed.path.rstrip("/") or "/"
            query = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
            err = _auth_check(self.headers, path)
            if err is not None:
                self._reply(401, {"error": err})
                return
            length = int(self.headers.get("Content-Length") or 0)
            body = None
            if length:
                try:
                    body = json.loads(self.rfile.read(length))
                except json.JSONDecodeError:
                    self._reply(400, {"error": "invalid json body"})
                    return
            code, result = api.dispatch(method, path, body, query)
            self._reply(code, result)

        def _reply(self, code: int, result: Any) -> None:
            ctype = getattr(result, "content_type", None)
            if ctype is not None:  # raw text payload (e.g. /metrics)
                data = str(result).encode()
            else:
                ctype = "application/json"
                data = json.dumps(result, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            self._handle("GET")

        def do_POST(self):
            self._handle("POST")

        def do_PUT(self):
            self._handle("PUT")

        def do_DELETE(self):
            self._handle("DELETE")

        def do_PATCH(self):
            self._handle("PATCH")

    server = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="rest-server")
    thread.start()
    logger.info("REST server listening on %s:%d", host, port)
    return server
