"""Server bootstrap — analogue of eKuiper's StartUp sequence
(internal/server/server.go:139-330): config → store → keyed state →
processors → rule recovery → REST server → run until signalled.

Run: python -m ekuiper_tpu.server.main [--config conf.json]
"""
from __future__ import annotations

import argparse
import logging
import os
import signal
import threading

from ..store import kv
from ..utils.config import get_config, load_config, set_config
from ..utils.infra import logger
from .rest import RestApi, serve


def start_up(config_path: str | None = None, block: bool = True):
    cfg = load_config(config_path)
    set_config(cfg)
    logging.basicConfig(
        level=getattr(logging, cfg.basic.log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    if cfg.cluster.enabled:
        # validate BEFORE the (blocking) init — a half-filled cluster
        # section must fail loudly, not hang a silent boot
        cc = cfg.cluster
        if not cc.coordinator_address:
            raise ValueError("cluster.coordinator_address is required")
        if not (0 <= cc.process_id < cc.num_processes):
            raise ValueError(
                f"cluster.process_id {cc.process_id} out of range for "
                f"{cc.num_processes} processes")
        # must run before anything touches jax: after this, jax.devices()
        # spans every participating host and meshes shard across them
        # (collectives ride ICI within a slice, DCN across slices)
        import jax

        logging.getLogger("ekuiper_tpu").info(
            "joining cluster %s as process %d/%d",
            cc.coordinator_address, cc.process_id, cc.num_processes)
        jax.distributed.initialize(
            coordinator_address=cc.coordinator_address,
            num_processes=cc.num_processes,
            process_id=cc.process_id,
        )
    store = kv.setup(cfg.store.type, cfg.store.path)
    from ..utils.config import apply_config_overlay

    apply_config_overlay(store)  # PATCH /configs overlays survive restarts
    if cfg.basic.rule_log_enabled:
        from ..utils import rulelog

        rulelog.install(os.path.join(cfg.store.path, "logs"))
    # portable plugin manager (restores installed plugins + binds symbols,
    # reference: server.go:218-226 binder init)
    from ..plugin.manager import PortableManager
    from ..plugin.script import ScriptManager

    from ..schema.registry import SchemaRegistry

    PortableManager.set_global(PortableManager(store))
    ScriptManager.set_global(ScriptManager(store))
    SchemaRegistry.set_global(SchemaRegistry(
        store, etc_dir=f"{cfg.store.path}/schemas"))
    from ..services.manager import ServiceManager

    ServiceManager.set_global(ServiceManager(store))
    # remote OTLP span tee (off by default; pkg/tracer/manager.go:28-45)
    from ..observability.otlp import from_config as otlp_from_config
    from ..observability.tracer import Tracer

    exporter = otlp_from_config(cfg)
    if exporter is not None:
        Tracer.global_instance().set_exporter(exporter)
        logger.info("OTLP span export -> %s", exporter.url)
    api = RestApi(store)
    api.rules.recover()
    server = serve(api, cfg.basic.rest_ip, cfg.basic.rest_port)

    stop_event = threading.Event()

    def shutdown(*_args) -> None:
        logger.info("shutting down")
        from ..observability import health
        from ..runtime import control

        control.reset()  # stop the QoS controller's recurring timer
        health.reset()  # stop the evaluator's recurring timer
        api.rules.stop_all()
        PortableManager.global_instance().kill_all()  # server.go:329 KillAll
        if exporter is not None:
            Tracer.global_instance().set_exporter(None)  # closes + final flush
        server.shutdown()
        stop_event.set()

    if block:
        signal.signal(signal.SIGINT, shutdown)
        signal.signal(signal.SIGTERM, shutdown)
        stop_event.wait()
        return None
    return api, server


def main() -> None:
    ap = argparse.ArgumentParser(description="ekuiper_tpu server")
    ap.add_argument("--config", default=None, help="config json path")
    args = ap.parse_args()
    start_up(args.config)


if __name__ == "__main__":
    main()
