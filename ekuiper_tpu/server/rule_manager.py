"""Rule registry — analogue of eKuiper's RuleRegistry
(internal/server/rule_manager.go:112-238): owns the live RuleState machines,
coordinates create/start/stop/restart/delete, recovers rules at boot.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..planner.planner import RuleDef, explain as plan_explain, plan_rule
from ..runtime.rule import RuleState, RunState
from ..utils.infra import PlanError, logger
from .processors import RuleProcessor


class RuleRegistry:
    def __init__(self, store) -> None:
        self.store = store
        self.processor = RuleProcessor(store)
        self._rules: Dict[str, RuleState] = {}
        self._lock = threading.RLock()

    # ---------------------------------------------------------------- recovery
    def recover(self) -> None:
        """Start rules marked running at last shutdown (boot recovery,
        reference: server.go rule restore). Rules parked in the
        persisted admission queue are re-enqueued with the controller —
        they were promised a start 'when pressure clears', and the
        in-memory queue died with the process."""
        run_table = self.store.kv("rule_run_state")
        aq_table = self.store.kv("admission_queue")
        live = set(self.processor.list())
        for rule_id in live:
            try:
                rule = self.processor.get(rule_id)
                rs = RuleState(rule, self.store)
                with self._lock:
                    self._rules[rule_id] = rs
                queued, q_ok = aq_table.get_ok(rule_id)
                if q_ok and queued is not None:
                    from ..runtime import control

                    ctl = control.controller()
                    if ctl is not None and ctl.enqueue(rule_id, {
                            "reason": (queued or {}).get("reason", ""),
                            "price": (queued or {}).get("price", {})}):
                        continue  # retried at control ticks
                    # no controller to honor the promise: start it now
                    # rather than strand it as pseudo-stopped
                    aq_table.delete(rule_id)
                    rs.start()
                    run_table.set(rule_id, True)
                    continue
                started, _ = run_table.get_ok(rule_id)
                auto_start = rule.options.get("triggered", True)
                if started if started is not None else auto_start:
                    # rebuild the admission ledger: the committed fold
                    # budget died with the process, and enforcing it
                    # against zero would over-admit a full engine. No
                    # gating here — boot recovery never refuses a rule
                    # that was already admitted.
                    self._bill(rule)
                    rs.start()
            except Exception as exc:
                logger.error("recover rule %s failed: %s", rule_id, exc)
        # queue entries for rules whose definition vanished are stale
        try:
            for rule_id in list(aq_table.keys()):
                if rule_id not in live:
                    aq_table.delete(rule_id)
        except Exception:
            pass

    # -------------------------------------------------------------------- CRUD
    def create(self, rule_json: Dict[str, Any]) -> str:
        rule = self.processor.create(rule_json)
        # validate by planning + constructing the FSM once (schedule options
        # are parsed there); any failure rolls the definition back so a
        # corrected re-POST with the same id works
        try:
            plan_rule(rule, self.store).close()
            rs = RuleState(rule, self.store)
        except Exception:
            self.processor.drop(rule.id)
            # the failed validation plan may have declared sharing
            # candidacy for a rule that will never exist
            from ..planner import sharing

            sharing.undeclare(rule.id)
            raise
        # admission control (runtime/control.py): price the rule against
        # the sharing cost model + live HBM/compile telemetry BEFORE it
        # starts. reject rolls the definition back with a STRUCTURED
        # decision; queue keeps the definition but defers the start to
        # the controller's next clear tick.
        from ..runtime import control

        triggered = rule.options.get("triggered", True)
        decision = {"decision": "accept"}
        if triggered:
            decision = control.admit_rule(rule, self.store)
        if decision["decision"] == "reject":
            self.processor.drop(rule.id)
            from ..planner import sharing

            sharing.undeclare(rule.id)
            raise control.AdmissionRejected(decision)
        with self._lock:
            self._rules[rule.id] = rs
        if decision["decision"] == "queue":
            ctl = control.controller()
            if ctl is not None and ctl.enqueue(rule.id, decision):
                self.store.kv("rule_run_state").set(rule.id, False)
                # persist the queue slot: a restart before pressure
                # clears must re-enqueue this rule (recover()), not
                # strand it indistinguishable from a user-stopped one
                self.store.kv("admission_queue").set(rule.id, {
                    "reason": decision.get("reason", ""),
                    "price": decision.get("price", {}),
                })
                return rule.id
            # no controller to retry it (or queue full): a queued rule
            # nobody will ever start is a silent reject — refuse loudly,
            # and COUNT it as the reject it became (enqueue never
            # counted a queue for it)
            if ctl is not None:
                ctl.note_admission("reject")
                from ..runtime.events import recorder

                recorder().record(
                    "admission", rule=rule.id, severity="warn",
                    decision="reject",
                    reason="admission queue unavailable")
            self.processor.drop(rule.id)
            from ..planner import sharing

            sharing.undeclare(rule.id)
            with self._lock:
                self._rules.pop(rule.id, None)
            raise control.AdmissionRejected({
                **decision, "decision": "reject",
                "reason": decision.get("reason", "")
                + " (admission queue unavailable)"})
        if triggered:
            ctl = control.controller()
            if ctl is not None:
                price = decision.get("price") or {}
                ctl.commit(rule.id,
                           float(price.get("fold_us_per_s", 0.0)),
                           placement=price.get("placement"))
            rs.start()
            self.store.kv("rule_run_state").set(rule.id, True)
        return rule.id

    def update(self, rule_json: Dict[str, Any]) -> None:
        # re-price the NEW definition before applying it: an update can
        # turn a cheap rule into one that blows the budgets. Updates are
        # never queued (allow_queue=False — the old definition keeps
        # running, there is nothing to defer) and the ledger is only
        # re-billed AFTER the processor accepts the new definition: a
        # parse-rejected update must not leave the ledger billing a
        # definition that never applied.
        from ..runtime import control

        candidate = RuleDef.from_dict(rule_json)
        decision = None
        if candidate.id:
            decision = control.admit_rule(candidate, self.store,
                                          allow_queue=False)
            if decision["decision"] == "reject":
                raise control.AdmissionRejected(decision)
        rule = self.processor.update(rule_json)
        # drop stale sharing candidacy (the SQL/options may have changed
        # its store key); the restart below re-declares under the new one
        from ..planner import sharing

        sharing.undeclare(rule.id)
        with self._lock:
            rs = self._rules.get(rule.id)
        if rs is not None:
            # cron rules waiting between firings are ACTIVE — an update must
            # re-arm their schedule, not silently deactivate it
            was_running = rs.state in (
                RunState.RUNNING, RunState.STARTING, RunState.SCHEDULED)
            rs.stop()
            # stop is ASYNC (FSM action queue): the old topo must release
            # its shared-source attachment before the new RuleState plans,
            # or the new start races "already attached" and dies
            # stopped_by_error — under rule-churn storms this silently
            # killed updated rules
            import time as _time

            deadline = _time.monotonic() + 10.0
            while _time.monotonic() < deadline and rs.state not in (
                    RunState.STOPPED, RunState.STOPPED_BY_ERR):
                _time.sleep(0.005)
            new_rs = RuleState(rule, self.store)
            with self._lock:
                self._rules[rule.id] = new_rs
            if was_running:
                # only a definition that will actually RUN is billed —
                # updating a stopped rule must not consume fold budget
                if decision is not None:
                    ctl = control.controller()
                    if ctl is not None:
                        price = decision.get("price") or {}
                        ctl.commit(
                            rule.id,
                            float(price.get("fold_us_per_s", 0.0)),
                            placement=price.get("placement"))
                new_rs.start()
        else:
            with self._lock:
                self._rules[rule.id] = RuleState(rule, self.store)

    def delete(self, rule_id: str) -> None:
        with self._lock:
            rs = self._rules.pop(rule_id, None)
        if rs is not None:
            rs.stop()
        self.processor.drop(rule_id)
        self.store.kv("rule_run_state").delete(rule_id)
        self.store.kv("admission_queue").delete(rule_id)
        # a deleted rule must stop counting as a sharing peer (ghost
        # declarations would make a later lone rule share with nobody)
        from ..planner import sharing

        sharing.undeclare(rule_id)
        # ...and must release its admission ledger entry / queue slot
        from ..runtime import control

        ctl = control.controller()
        if ctl is not None:
            ctl.release(rule_id)

    # --------------------------------------------------------------- lifecycle
    def _bill(self, rule) -> None:
        """Record a rule's priced fold cost in the admission ledger
        (no gating). The ledger tracks RUNNING rules: create-triggered,
        operator start, queue drain, and boot recovery all bill;
        stop/delete release."""
        from ..runtime import control

        ctl = control.controller()
        if ctl is None:
            return
        try:
            price = control.price_rule(rule, self.store)
            # price_rule never sets "placement" (the admission gate
            # does) — recovery/operator-start billing derives one from
            # the live ledger so restarts keep the per-chip accounting
            ctl.commit(rule.id, float(price.get("fold_us_per_s", 0.0)),
                       placement=control.bill_placement(price))
        except Exception:
            pass

    def _get(self, rule_id: str) -> RuleState:
        with self._lock:
            rs = self._rules.get(rule_id)
        if rs is None:
            # definition may exist without a live state (post-restart)
            rule = self.processor.get(rule_id)
            rs = RuleState(rule, self.store)
            with self._lock:
                self._rules[rule_id] = rs
        return rs

    def start(self, rule_id: str) -> None:
        # an operator start overrides a pending admission queue slot —
        # claim() pops it and commits its price atomically so the
        # controller won't start it a second time later
        from ..runtime import control

        ctl = control.controller()
        if ctl is not None and ctl.claim(rule_id) is None:
            # not queued (e.g. created triggered=false, or stopped then
            # restarted): the ledger must still bill what now runs
            self._bill(self._get(rule_id).rule)
        self.store.kv("admission_queue").delete(rule_id)
        self._get(rule_id).start()
        self.store.kv("rule_run_state").set(rule_id, True)

    def stop(self, rule_id: str) -> None:
        self._get(rule_id).stop()
        self.store.kv("rule_run_state").set(rule_id, False)
        # a stopped rule costs nothing: release its ledger entry (and
        # any pending queue slot — an operator stop cancels the promise
        # to start it later)
        from ..runtime import control

        ctl = control.controller()
        if ctl is not None:
            ctl.release(rule_id)
        self.store.kv("admission_queue").delete(rule_id)

    def restart(self, rule_id: str) -> None:
        self._get(rule_id).restart()
        self.store.kv("rule_run_state").set(rule_id, True)

    # ------------------------------------------------------------------ query
    def list(self, tags: Optional[List[str]] = None) -> List[Dict[str, Any]]:
        out = []
        for rule_id in self.processor.list():
            with self._lock:
                rs = self._rules.get(rule_id)
            raw, ok = self.processor._table().get_ok(rule_id)
            rule_tags = list(raw.get("tags") or []) if ok and isinstance(
                raw, dict) else []
            if tags and not set(tags) <= set(rule_tags):
                continue  # reference: tag filter requires ALL given tags
            status = rs.state.value if rs is not None else "stopped"
            entry = {"id": rule_id, "status": status}
            if rule_tags:
                entry["tags"] = rule_tags
            out.append(entry)
        return out

    def set_tags(self, rule_id: str, tags: List[str], add: bool) -> None:
        rule = self.processor.get(rule_id)
        if add:
            rule.tags = sorted(set(rule.tags) | set(tags))
        else:
            rule.tags = [t for t in rule.tags if t not in set(tags)]
        self.processor.update(rule.to_dict())

    def state(self, rule_id: str) -> Optional[RuleState]:
        """Live RuleState (None when not instantiated) — observability."""
        with self._lock:
            return self._rules.get(rule_id)

    def status(self, rule_id: str) -> Dict[str, Any]:
        return self._get(rule_id).status()

    def cpu_usage(self) -> Dict[str, Any]:
        """Per-rule cumulative busy time in ms (reference REST
        /rules/usage/cpu, rest.go:199 — there a sampling CPU profiler;
        here each node's accumulated in-process time, a documented
        wall-clock proxy)."""
        out: Dict[str, Any] = {}
        with self._lock:
            rules = dict(self._rules)
        for rule_id, rs in rules.items():
            topo = rs.topo  # capture: stop/restart may null it concurrently
            if topo is None:
                continue
            raw_us = {n.name: n.stats.process_time_us_total
                      for n in topo.all_nodes()}
            out[rule_id] = {
                "total_ms": round(sum(raw_us.values()) / 1000.0, 1),
                "nodes": {k: round(v / 1000.0, 1)
                          for k, v in raw_us.items()},
            }
        return out

    def latency_usage(self) -> Dict[str, Any]:
        """Per-rule ingest→emit latency summary (REST
        /rules/usage/latency, sibling of /rules/usage/cpu): the SLO view
        across every live rule at a glance — {count, p50, p90, p99, max}
        in ms off each topo's end-to-end histogram."""
        out: Dict[str, Any] = {}
        with self._lock:
            rules = dict(self._rules)
        for rule_id, rs in rules.items():
            topo = rs.topo  # capture: stop/restart may null it concurrently
            if topo is None:
                continue
            out[rule_id] = topo.e2e_hist.snapshot()
        return out

    def explain(self, rule_id: str) -> Dict[str, Any]:
        rule = self.processor.get(rule_id)
        return plan_explain(rule, self.store)

    def topo_json(self, rule_id: str) -> Dict[str, Any]:
        rs = self._get(rule_id)
        if rs.topo is not None:
            return rs.topo.topo_json()
        topo = plan_rule(rs.rule, self.store)
        out = topo.topo_json()
        topo.close()
        return out

    def validate(self, rule_json: Dict[str, Any]) -> Dict[str, Any]:
        rule = RuleDef.from_dict(rule_json)
        if not rule.sql and rule.graph is None:
            return {"valid": False, "error": "rule sql or graph is required"}
        # a validation probe must neither LEAVE sharing candidacy behind
        # (a phantom peer would flip later lone rules to shared) nor
        # OVERWRITE a registered rule's live declaration (probing an
        # existing id with a different window would skew the pane GCD of
        # future stores); the rollback is scoped to the probed id so
        # concurrent rule CRUD on other rules is untouched
        from ..planner import sharing

        existed = rule.id in set(self.processor.list()) if rule.id else False
        try:
            with sharing.probe_declarations(rule.id):
                try:
                    plan_rule(rule, self.store).close()
                    return {"valid": True}
                except Exception as exc:
                    return {"valid": False, "error": str(exc)}
        finally:
            # probe restore races a concurrent DELETE of the same id: the
            # restored declaration would resurrect a ghost peer — drop it
            # when the rule vanished (or never existed) during the probe
            if rule.id and (not existed
                            or rule.id not in set(self.processor.list())):
                sharing.undeclare(rule.id)

    def reset_state(self, rule_id: str) -> None:
        """Drop checkpointed state (REST /rules/{id}/reset_state)."""
        self.store.drop(f"checkpoint:{rule_id}")

    def stop_all(self) -> None:
        with self._lock:
            rules = list(self._rules.values())
        for rs in rules:
            rs.stop()
