"""Rule registry — analogue of eKuiper's RuleRegistry
(internal/server/rule_manager.go:112-238): owns the live RuleState machines,
coordinates create/start/stop/restart/delete, recovers rules at boot.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..planner.planner import RuleDef, explain as plan_explain, plan_rule
from ..runtime.rule import RuleState, RunState
from ..utils.infra import PlanError, logger
from .processors import RuleProcessor


class RuleRegistry:
    def __init__(self, store) -> None:
        self.store = store
        self.processor = RuleProcessor(store)
        self._rules: Dict[str, RuleState] = {}
        self._lock = threading.RLock()

    # ---------------------------------------------------------------- recovery
    def recover(self) -> None:
        """Start rules marked running at last shutdown (boot recovery,
        reference: server.go rule restore)."""
        run_table = self.store.kv("rule_run_state")
        for rule_id in self.processor.list():
            try:
                rule = self.processor.get(rule_id)
                rs = RuleState(rule, self.store)
                with self._lock:
                    self._rules[rule_id] = rs
                started, _ = run_table.get_ok(rule_id)
                auto_start = rule.options.get("triggered", True)
                if started if started is not None else auto_start:
                    rs.start()
            except Exception as exc:
                logger.error("recover rule %s failed: %s", rule_id, exc)

    # -------------------------------------------------------------------- CRUD
    def create(self, rule_json: Dict[str, Any]) -> str:
        rule = self.processor.create(rule_json)
        # validate by planning + constructing the FSM once (schedule options
        # are parsed there); any failure rolls the definition back so a
        # corrected re-POST with the same id works
        try:
            plan_rule(rule, self.store).close()
            rs = RuleState(rule, self.store)
        except Exception:
            self.processor.drop(rule.id)
            # the failed validation plan may have declared sharing
            # candidacy for a rule that will never exist
            from ..planner import sharing

            sharing.undeclare(rule.id)
            raise
        with self._lock:
            self._rules[rule.id] = rs
        if rule.options.get("triggered", True):
            rs.start()
            self.store.kv("rule_run_state").set(rule.id, True)
        return rule.id

    def update(self, rule_json: Dict[str, Any]) -> None:
        rule = self.processor.update(rule_json)
        # drop stale sharing candidacy (the SQL/options may have changed
        # its store key); the restart below re-declares under the new one
        from ..planner import sharing

        sharing.undeclare(rule.id)
        with self._lock:
            rs = self._rules.get(rule.id)
        if rs is not None:
            # cron rules waiting between firings are ACTIVE — an update must
            # re-arm their schedule, not silently deactivate it
            was_running = rs.state in (
                RunState.RUNNING, RunState.STARTING, RunState.SCHEDULED)
            rs.stop()
            new_rs = RuleState(rule, self.store)
            with self._lock:
                self._rules[rule.id] = new_rs
            if was_running:
                new_rs.start()
        else:
            with self._lock:
                self._rules[rule.id] = RuleState(rule, self.store)

    def delete(self, rule_id: str) -> None:
        with self._lock:
            rs = self._rules.pop(rule_id, None)
        if rs is not None:
            rs.stop()
        self.processor.drop(rule_id)
        self.store.kv("rule_run_state").delete(rule_id)
        # a deleted rule must stop counting as a sharing peer (ghost
        # declarations would make a later lone rule share with nobody)
        from ..planner import sharing

        sharing.undeclare(rule_id)

    # --------------------------------------------------------------- lifecycle
    def _get(self, rule_id: str) -> RuleState:
        with self._lock:
            rs = self._rules.get(rule_id)
        if rs is None:
            # definition may exist without a live state (post-restart)
            rule = self.processor.get(rule_id)
            rs = RuleState(rule, self.store)
            with self._lock:
                self._rules[rule_id] = rs
        return rs

    def start(self, rule_id: str) -> None:
        self._get(rule_id).start()
        self.store.kv("rule_run_state").set(rule_id, True)

    def stop(self, rule_id: str) -> None:
        self._get(rule_id).stop()
        self.store.kv("rule_run_state").set(rule_id, False)

    def restart(self, rule_id: str) -> None:
        self._get(rule_id).restart()
        self.store.kv("rule_run_state").set(rule_id, True)

    # ------------------------------------------------------------------ query
    def list(self, tags: Optional[List[str]] = None) -> List[Dict[str, Any]]:
        out = []
        for rule_id in self.processor.list():
            with self._lock:
                rs = self._rules.get(rule_id)
            raw, ok = self.processor._table().get_ok(rule_id)
            rule_tags = list(raw.get("tags") or []) if ok and isinstance(
                raw, dict) else []
            if tags and not set(tags) <= set(rule_tags):
                continue  # reference: tag filter requires ALL given tags
            status = rs.state.value if rs is not None else "stopped"
            entry = {"id": rule_id, "status": status}
            if rule_tags:
                entry["tags"] = rule_tags
            out.append(entry)
        return out

    def set_tags(self, rule_id: str, tags: List[str], add: bool) -> None:
        rule = self.processor.get(rule_id)
        if add:
            rule.tags = sorted(set(rule.tags) | set(tags))
        else:
            rule.tags = [t for t in rule.tags if t not in set(tags)]
        self.processor.update(rule.to_dict())

    def state(self, rule_id: str) -> Optional[RuleState]:
        """Live RuleState (None when not instantiated) — observability."""
        with self._lock:
            return self._rules.get(rule_id)

    def status(self, rule_id: str) -> Dict[str, Any]:
        return self._get(rule_id).status()

    def cpu_usage(self) -> Dict[str, Any]:
        """Per-rule cumulative busy time in ms (reference REST
        /rules/usage/cpu, rest.go:199 — there a sampling CPU profiler;
        here each node's accumulated in-process time, a documented
        wall-clock proxy)."""
        out: Dict[str, Any] = {}
        with self._lock:
            rules = dict(self._rules)
        for rule_id, rs in rules.items():
            topo = rs.topo  # capture: stop/restart may null it concurrently
            if topo is None:
                continue
            raw_us = {n.name: n.stats.process_time_us_total
                      for n in topo.all_nodes()}
            out[rule_id] = {
                "total_ms": round(sum(raw_us.values()) / 1000.0, 1),
                "nodes": {k: round(v / 1000.0, 1)
                          for k, v in raw_us.items()},
            }
        return out

    def latency_usage(self) -> Dict[str, Any]:
        """Per-rule ingest→emit latency summary (REST
        /rules/usage/latency, sibling of /rules/usage/cpu): the SLO view
        across every live rule at a glance — {count, p50, p90, p99, max}
        in ms off each topo's end-to-end histogram."""
        out: Dict[str, Any] = {}
        with self._lock:
            rules = dict(self._rules)
        for rule_id, rs in rules.items():
            topo = rs.topo  # capture: stop/restart may null it concurrently
            if topo is None:
                continue
            out[rule_id] = topo.e2e_hist.snapshot()
        return out

    def explain(self, rule_id: str) -> Dict[str, Any]:
        rule = self.processor.get(rule_id)
        return plan_explain(rule, self.store)

    def topo_json(self, rule_id: str) -> Dict[str, Any]:
        rs = self._get(rule_id)
        if rs.topo is not None:
            return rs.topo.topo_json()
        topo = plan_rule(rs.rule, self.store)
        out = topo.topo_json()
        topo.close()
        return out

    def validate(self, rule_json: Dict[str, Any]) -> Dict[str, Any]:
        rule = RuleDef.from_dict(rule_json)
        if not rule.sql and rule.graph is None:
            return {"valid": False, "error": "rule sql or graph is required"}
        # a validation probe must neither LEAVE sharing candidacy behind
        # (a phantom peer would flip later lone rules to shared) nor
        # OVERWRITE a registered rule's live declaration (probing an
        # existing id with a different window would skew the pane GCD of
        # future stores); the rollback is scoped to the probed id so
        # concurrent rule CRUD on other rules is untouched
        from ..planner import sharing

        existed = rule.id in set(self.processor.list()) if rule.id else False
        try:
            with sharing.probe_declarations(rule.id):
                try:
                    plan_rule(rule, self.store).close()
                    return {"valid": True}
                except Exception as exc:
                    return {"valid": False, "error": str(exc)}
        finally:
            # probe restore races a concurrent DELETE of the same id: the
            # restored declaration would resurrect a ghost peer — drop it
            # when the rule vanished (or never existed) during the probe
            if rule.id and (not existed
                            or rule.id not in set(self.processor.list())):
                sharing.undeclare(rule.id)

    def reset_state(self, rule_id: str) -> None:
        """Drop checkpointed state (REST /rules/{id}/reset_state)."""
        self.store.drop(f"checkpoint:{rule_id}")

    def stop_all(self) -> None:
        with self._lock:
            rules = list(self._rules.values())
        for rs in rules:
            rs.stop()
