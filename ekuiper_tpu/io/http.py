"""HTTP connectors — analogues of eKuiper's httppull/httppush sources and
rest sink (internal/io/http). httppush endpoints are hosted by one shared
HTTP data server (internal/io/http/httpserver/data_server.go:36-103).
"""
from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from ..utils import timex
from ..utils.infra import EngineError, logger
from .contract import Sink, Source


class HttpPullSource(Source):
    """Polls a URL at an interval (reference httppull)."""

    def __init__(self) -> None:
        self.url = ""
        self.method = "GET"
        self.interval_ms = 10_000
        self.headers: Dict[str, str] = {}
        self.body = ""
        self.incremental = False
        self._last: Any = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.url = props.get("url", datasource)
        self.method = props.get("method", "GET").upper()
        self.interval_ms = int(props.get("interval", 10_000))
        self.headers = props.get("headers", {})
        self.body = props.get("body", "")
        self.incremental = bool(props.get("incremental", False))

    def open(self, ingest) -> None:
        self._stop.clear()

        def run() -> None:
            while not self._stop.is_set():
                try:
                    data = self.body.encode() if self.body else None
                    req = urllib.request.Request(
                        self.url, data=data, method=self.method,
                        headers={"Content-Type": "application/json", **self.headers},
                    )
                    with urllib.request.urlopen(req, timeout=30) as resp:
                        payload = json.loads(resp.read().decode())
                    if not self.incremental or payload != self._last:
                        self._last = payload
                        ingest(payload, {"url": self.url})
                except Exception as exc:
                    logger.warning("httppull %s: %s", self.url, exc)
                timex.sleep(self.interval_ms)

        self._thread = threading.Thread(target=run, daemon=True, name="httppull")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()


# ---------------------------------------------------------- shared data server
class _DataServer:
    """One process-wide HTTP server hosting all httppush endpoints."""

    def __init__(self) -> None:
        self._server: Optional[ThreadingHTTPServer] = None
        self._endpoints: Dict[str, Callable[[Any, Dict[str, Any]], None]] = {}
        self._lock = threading.Lock()
        self.port = 0

    def ensure_started(self, host: str, port: int) -> None:
        with self._lock:
            if self._server is not None:
                return
            endpoints = self._endpoints

            class Handler(BaseHTTPRequestHandler):
                def log_message(self, fmt, *args):
                    logger.debug("httppush: " + fmt, *args)

                def do_POST(self):
                    with _data_server._lock:
                        handler = endpoints.get(self.path)
                    if handler is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    length = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(length)
                    try:
                        payload = json.loads(raw) if raw else {}
                    except json.JSONDecodeError:
                        self.send_response(400)
                        self.end_headers()
                        return
                    handler(payload, {"path": self.path})
                    self.send_response(200)
                    self.send_header("Content-Length", "0")
                    self.end_headers()

                do_PUT = do_POST

            self._server = ThreadingHTTPServer((host, port), Handler)
            self.port = self._server.server_address[1]
            threading.Thread(
                target=self._server.serve_forever, daemon=True,
                name="httppush-server",
            ).start()

    def register(self, path: str, handler) -> None:
        with self._lock:
            self._endpoints[path] = handler

    def unregister(self, path: str) -> None:
        with self._lock:
            self._endpoints.pop(path, None)

    def shutdown(self) -> None:
        with self._lock:
            if self._server is not None:
                self._server.shutdown()
                self._server = None


_data_server = _DataServer()


def get_data_server() -> _DataServer:
    return _data_server


class HttpPushSource(Source):
    """Receives events POSTed to a path on the shared data server."""

    def __init__(self) -> None:
        self.path = "/"
        self.host = "127.0.0.1"
        self.port = 10081

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.path = props.get("endpoint", datasource or "/")
        if not self.path.startswith("/"):
            self.path = "/" + self.path
        self.host = props.get("server_ip", "127.0.0.1")
        self.port = int(props.get("server_port", 10081))

    def open(self, ingest) -> None:
        _data_server.ensure_started(self.host, self.port)
        _data_server.register(self.path, lambda payload, meta: ingest(payload, meta))

    def close(self) -> None:
        _data_server.unregister(self.path)


class HttpLookupSource:
    """Lookup-table over an HTTP endpoint: GET url with key=value query
    params per lookup (reference: httppull lookup source)."""

    def __init__(self) -> None:
        self.url = ""
        self.headers: Dict[str, str] = {}
        self.timeout_ms = 5000

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.url = props.get("url", datasource)
        self.headers = props.get("headers", {})
        self.timeout_ms = int(props.get("timeout", 5000))

    def open(self) -> None:
        pass

    def lookup(self, fields: List[str], keys: List[str], values: List[Any]) -> List[Dict[str, Any]]:
        import urllib.parse

        query = urllib.parse.urlencode(
            {k: v for k, v in zip(keys, values) if v is not None}
        )
        url = self.url + ("&" if "?" in self.url else "?") + query if query else self.url
        req = urllib.request.Request(url, headers=self.headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_ms / 1000.0) as resp:
                payload = json.loads(resp.read().decode())
        except Exception as exc:
            logger.warning("http lookup %s: %s", url, exc)
            return []
        if isinstance(payload, list):
            return [p for p in payload if isinstance(p, dict)]
        return [payload] if isinstance(payload, dict) else []

    def close(self) -> None:
        pass


class RestSink(Sink):
    """POSTs results to a URL (reference rest sink)."""

    def __init__(self) -> None:
        self.url = ""
        self.method = "POST"
        self.headers: Dict[str, str] = {}
        self.timeout_ms = 5000

    def configure(self, props: Dict[str, Any]) -> None:
        self.url = props.get("url", "")
        self.method = props.get("method", "POST").upper()
        self.headers = props.get("headers", {})
        self.timeout_ms = int(props.get("timeout", 5000))
        if not self.url:
            raise EngineError("rest sink requires url")

    def collect(self, item: Any) -> None:
        data = json.dumps(item, default=str).encode()
        req = urllib.request.Request(
            self.url, data=data, method=self.method,
            headers={"Content-Type": "application/json", **self.headers},
        )
        with urllib.request.urlopen(req, timeout=self.timeout_ms / 1000.0):
            pass
