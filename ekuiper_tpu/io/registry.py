"""IO registry — analogue of the binder io factories
(internal/binder/io/builtin.go:36-61): maps connector type names to
source/sink/lookup constructors. Extension connectors register here too
(plugins, later rounds).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

_sources: Dict[str, Callable[[], Any]] = {}
_sinks: Dict[str, Callable[[], Any]] = {}
_lookups: Dict[str, Callable[[], Any]] = {}


def register_source(name: str, factory: Callable[[], Any]) -> None:
    _sources[name.lower()] = factory


def register_sink(name: str, factory: Callable[[], Any]) -> None:
    _sinks[name.lower()] = factory


def register_lookup(name: str, factory: Callable[[], Any]) -> None:
    _lookups[name.lower()] = factory


def has_source(name: str) -> bool:
    _ensure()
    return name.lower() in _sources


def has_sink(name: str) -> bool:
    _ensure()
    return name.lower() in _sinks


def unregister_source(name: str) -> None:
    _sources.pop(name.lower(), None)


def unregister_sink(name: str) -> None:
    _sinks.pop(name.lower(), None)


def create_source(name: str):
    _ensure()
    f = _sources.get(name.lower())
    if f is None:
        raise ValueError(f"unknown source type {name!r}")
    return f()


def create_sink(name: str):
    _ensure()
    f = _sinks.get(name.lower())
    if f is None:
        raise ValueError(f"unknown sink type {name!r}")
    return f()


def create_lookup(name: str):
    _ensure()
    f = _lookups.get(name.lower())
    if f is None:
        raise ValueError(f"unknown lookup source type {name!r}")
    return f()


def source_types():
    _ensure()
    return sorted(_sources.keys())


def sink_types():
    _ensure()
    return sorted(_sinks.keys())


_loaded = False


def _ensure() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    from .memory import MemoryLookupSource, MemorySink, MemorySource
    from .simulator import SimulatorSource
    from .sinks import LogSink, NopSink

    register_source("memory", MemorySource)
    register_source("simulator", SimulatorSource)
    register_sink("memory", MemorySink)
    register_sink("log", LogSink)
    register_sink("nop", NopSink)
    register_lookup("memory", MemoryLookupSource)

    from .file import FileSink, FileSource
    from .http import HttpPullSource, HttpPushSource, RestSink

    register_source("file", FileSource)
    register_sink("file", FileSink)
    register_source("httppull", HttpPullSource)
    register_source("httppush", HttpPushSource)
    register_sink("rest", RestSink)
    from .http import HttpLookupSource

    register_lookup("httppull", HttpLookupSource)

    # mqtt always registers: paho when installed, else the bundled native
    # MQTT 3.1.1 client (io/mqtt_native.py)
    from .mqtt import MqttSink, MqttSource

    register_source("mqtt", MqttSource)
    register_sink("mqtt", MqttSink)

    # websocket needs the `websockets` package — optional, same gating
    try:
        from .websocket import WebsocketSink, WebsocketSource

        register_source("websocket", WebsocketSource)
        register_sink("websocket", WebsocketSink)
    except ImportError:
        pass

    from .neuron import NeuronSink, NeuronSource
    from .redis_io import RedisLookupSource, RedisSink, RedisSubSource
    from .sql_io import SqlLookupSource, SqlSink, SqlSource

    register_source("redissub", RedisSubSource)
    register_sink("redis", RedisSink)
    register_lookup("redis", RedisLookupSource)
    register_source("neuron", NeuronSource)
    register_sink("neuron", NeuronSink)
    register_source("sql", SqlSource)
    register_sink("sql", SqlSink)
    register_lookup("sql", SqlLookupSource)

    # edgex rides the repo's own MQTT/redis clients (io/edgex_io.py) —
    # no external EdgeX client library needed
    from .edgex_io import EdgexSink, EdgexSource

    register_source("edgex", EdgexSource)
    register_sink("edgex", EdgexSink)

    # influx speaks line protocol over plain HTTP (io/influx_io.py)
    from .influx_io import Influx2Sink, InfluxSink

    register_sink("influx", InfluxSink)
    register_sink("influx2", Influx2Sink)

    from .kafka_io import KafkaSink, KafkaSource

    register_source("kafka", KafkaSource)
    register_sink("kafka", KafkaSink)

    from .zmq_io import ZmqSink, ZmqSource

    register_source("zmq", ZmqSource)
    register_sink("zmq", ZmqSink)

    from .tdengine_io import Tdengine3Sink

    register_sink("tdengine3", Tdengine3Sink)

    from .video_io import VideoSource

    register_source("video", VideoSource)
